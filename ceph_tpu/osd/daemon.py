"""OSD daemon — the EC data plane tied end-to-end (reference: src/osd/OSD.cc
boot/dispatch, src/osd/PrimaryLogPG.cc op execution, src/osd/ECBackend.cc
encode/fan-out/reconstruct/recover; SURVEY.md §3.1-3.2 call stacks).

One OSD process = messenger (lossless peer policy) + MonClient session +
ObjectStore + per-PG state.  The data model is the reference's at object
granularity:

- write: primary encodes the object through the pool's EC profile codec
  (ErasureCodePluginRegistry — the TPU path), ships one chunk per shard as
  MECSubOpWrite (each carrying the pg_log entry), commits its own shard,
  acks the client at >= min_size shard commits after an UPFRONT min_size
  reachability gate (ECBackend::submit_transaction shape + PrimaryLogPG's
  min_size refusal).
- ranged write / append: partial-stripe RMW as a parity-delta update —
  touched data shards get spliced segments, parity shards GF-XOR one
  matrix-apply's worth of delta over just the touched column window
  (reference: ECTransaction::generate_transactions, in the optimized-EC
  delta formulation).  Safety comes from per-object version stamps
  (object_info_t analog): stale-generation shards refuse the delta and
  are rebuilt by recovery; resends are answered by the per-PG reqid dup
  cache (pg_log dup entries analog).
- read: primary gathers k chunks (local + MECSubOpRead), reconstructs
  through minimum_to_decode/decode when shards are gone
  (objects_read_and_reconstruct), reassembles bytes.
- recovery: on map change the primary runs peering-lite — MPGQuery each
  acting shard, delta-push objects the peer's pg_log version misses
  (PGLog.missing_since), or full-backfill a shard whose log is too old
  (recover_object / backfill split, §5.4).

Scope notes vs the reference: scalar versions rather than eversion_t, and
peering without the boost::statechart machine — the invariants these
protect (log/data atomicity, min_size-gated acks, delta-vs-backfill
choice, no mixed-generation decodes, missing_loc-style stray-source
recovery) are kept.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict

from ..common.crc32c import crc32c
from ..common.lockdep import make_lock
from ..common.perf_counters import PerfCountersBuilder
from ..ec.registry import ErasureCodePluginRegistry
from ..mon.mon_client import MonClient
from ..msg import Dispatcher, Messenger
from ..msg.messenger import POLICY_LOSSLESS_PEER
from ..osd.osdmap import OSDMap, PG_POOL_ERASURE, object_ps
from ..store.memstore import MemStore
from ..store.object_store import NotFound, Transaction
from .messages import (
    MECSubOpRead,
    MWatchNotify,
    MWatchNotifyAck,
    MECSubOpReadReply,
    MECSubOpWrite,
    MECSubOpWriteReply,
    MOSDOp,
    MOSDOpReply,
    MOSDPingMsg,
    MPGClean,
    MPGNotify,
    MPGPull,
    MPGPullReply,
    MPGQuery,
    MScrubShard,
    MScrubShardReply,
    pack_data,
    unpack_data,
)
from .pg_log import LogEntry, PGLog
from .scheduler import MClockScheduler, QoSParams

import numpy as np


class PGState:
    def __init__(self, pgid: str, pool_id: int, ps: int):
        self.pgid = pgid
        self.pool_id = pool_id
        self.ps = ps
        self.log = PGLog()
        self.version = 0
        # highest pool pg_num this PG has been split-scanned under (0 =
        # scan on next pass; in-memory: a restart just rescans)
        self.split_scanned = 0
        # live-snap-id tuple this PG was last trimmed against (None =
        # never trimmed; distinct from () = trimmed against empty set)
        self.snap_trimmed: tuple | None = None
        # epoch at which this PG's up/acting last CHANGED (reference:
        # pg_history_t::same_interval_since): sub-ops stamped with an
        # older epoch come from a primary of a PAST interval — a stale
        # primary racing a map change — and must be refused, or its
        # writes fork the PG's history behind the current interval's back
        self.interval_start = 0
        # interval this PG last completed its peering round in (phase 0
        # of _recover_pg: query peers, adopt the authoritative log).
        # A primary serves NO client ops until activated for the
        # CURRENT interval (reference: PG activation gates ops) — a
        # revived primary answering from its stale log/version would
        # fork history or falsely ack writes it cannot place.
        self.activated_interval = -1
        # formal history of CLOSED up/acting intervals (reference:
        # PastIntervals) — drives choose_acting's candidate pool, the
        # build_prior activation block, and bounded stray probing
        from .past_intervals import PastIntervals

        self.past_intervals = PastIntervals()
        # cumulative closures recorded this process-lifetime (observability
        # only — prune clears the history, not this)
        self.intervals_closed = 0
        # newest map epoch under which this PG logged a write (persisted
        # with the log): a revived OSD uses it as the starting point to
        # REBUILD interval history from the mon's old maps — intervals
        # that passed while it was down were never seen by _on_map
        # (reference: pg_history_t + build via past OSDMaps)
        self.last_map_epoch = 0
        self.intervals_rebuilt = False
        # shard collections known to hold this PG's meta locally (filled
        # by _load_pg_meta/_log_txn so _save_intervals never rescans the
        # whole store per map change)
        self.meta_cids: set[str] = set()
        # interval for which this primary last broadcast MPGClean
        self.clean_broadcast_interval = -1
        # reqid -> (retval, result) of COMPLETED mutations: a client
        # resend whose reply was lost is answered from here instead of
        # re-executed (reference: pg_log dup entries / osd_reqid_t);
        # success-only so retryable -EAGAIN refusals still re-execute
        self.reqid_cache: "OrderedDict[str, tuple]" = OrderedDict()
        # reqid -> Event of a mutation mid-execution: a resend racing the
        # original waits here instead of double-executing (reference:
        # PrimaryLogPG::check_in_progress_op)
        self.inflight: dict[str, threading.Event] = {}
        self.lock = make_lock("osd::pg")

    def meta_oid(self) -> str:
        return "_pgmeta"


# clone-object name separator (reference: clones are (oid, snapid) hobjects;
# here the snapid rides in the name, invisible to client listings)
CLONE_SEP = "\x02"

# client ops covered by reqid dup detection (mutations whose re-execution
# on a resend would be wrong or wasteful)
MUTATING_OPS = frozenset(
    {"write_full", "write", "append", "delete", "setxattr",
     "omap_set", "omap_rm", "omap_clear", "exec"}
)


def _current_generation(chunks: dict, vers: dict,
                        floor: int | None = None) -> dict:
    """Drop stale-GENERATION chunks: shards versioned below the newest
    version seen carry pre-RMW bytes that must never be mixed into a
    decode (None = wildcard, e.g. backfill-rebuilt).  `floor` is the
    LOG's newest data version for the object (when known): even if every
    reachable chunk is older — the current copies are on a crashed
    disk — the stale generation must read as MISSING, not as current,
    or a later splice-and-rewrite would launder the rollback into a
    fresh higher version (reference: the missing/unfound machinery)."""
    present = [v for v in vers.values() if v is not None]
    if floor is not None:
        present.append(floor)
    if not present:
        return chunks
    target = max(present)
    return {
        s: b for s, b in chunks.items()
        if vers.get(s) is None or vers.get(s) == target
    }


class OSD(Dispatcher):
    """reference: src/osd/OSD.{h,cc} (boot, dispatch, heartbeats) +
    PrimaryLogPG/ECBackend op execution, collapsed to one class."""

    def __init__(self, cct, osd_id: int, mon_addrs, store=None):
        self.cct = cct
        self.id = osd_id
        self.whoami = f"osd.{osd_id}"
        if store is not None:
            self.store = store
        else:
            # config-driven backend (reference: OSD reads `osd objectstore`)
            kind = cct.conf.get("objectstore")
            if kind == "memstore":
                self.store = MemStore()
            else:
                import os

                from ..store.object_store import create_store

                data_dir = cct.conf.get("osd_data") or None
                if data_dir:
                    # per-daemon subdir (reference: osd_data defaults to
                    # /var/lib/ceph/osd/$cluster-$id — never shared)
                    data_dir = os.path.join(data_dir, self.whoami)
                self.store = create_store(
                    kind,
                    data_dir,
                    compression=cct.conf.get("objectstore_compression"),
                    sync=cct.conf.get("objectstore_wal_sync"),
                    checksum=cct.conf.get("objectstore_checksum"),
                    device_size=cct.conf.get("bluestore_block_size"),
                )
                if cct.conf.get("osd_fsck_on_mount"):
                    # boot-time consistency pass over the freshly
                    # mounted (WAL-replayed) store (reference:
                    # bluestore_fsck_on_mount)
                    errs = self.store.fsck()
                    bad = (
                        errs.get("errors") if isinstance(errs, dict)
                        else errs
                    )
                    if bad:
                        raise RuntimeError(
                            f"{self.whoami} fsck on mount: {bad}"
                        )
        self.messenger = Messenger.create(cct, self.whoami)
        self.messenger.default_policy = POLICY_LOSSLESS_PEER
        self.messenger.add_dispatcher(self)
        # ticket validation tracks the map's auth generation, so `auth
        # rotate` cuts stale clients off as soon as this OSD sees the
        # new epoch (reference: rotating service keys via MAuth)
        self.messenger.auth_gen_provider = lambda: (
            self.osdmap.auth_gens.get("osd", 1) if self.osdmap else 1
        )
        self.mc = MonClient(cct, mon_addrs, name=f"{self.whoami}-monc")
        self.osdmap: OSDMap | None = None
        self.pgs: dict[str, PGState] = {}
        self._pgs_lock = make_lock("osd::pgs")
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._sub_replies: dict[int, dict] = {}   # tid -> reply fields
        self._tid = 0
        self._stop = threading.Event()
        self._tick_thread: threading.Thread | None = None
        self._hb_failures: dict[int, int] = {}
        self._codecs: dict[str, object] = {}
        self._recovery_wakeup = threading.Event()
        # mClock QoS dispatch (reference: osd_mclock_profile
        # balanced-ish): client I/O keeps a reservation floor; recovery
        # and scrub share leftovers under ceilings
        self.scheduler = MClockScheduler({
            "client": QoSParams(reservation=100.0, weight=10.0),
            "background_recovery": QoSParams(
                reservation=10.0, weight=2.0, limit=200.0
            ),
            "background_scrub": QoSParams(weight=1.0, limit=50.0),
        })
        self._workers: list[threading.Thread] = []
        self._recovery_inflight = False
        self._split_inflight = False
        self._clone_mutex = make_lock("osd::snap_clone")
        # watch/notify state (reference: PrimaryLogPG watchers): primary-
        # local; clients re-register lingering watches on map change
        self.watchers: dict[tuple, dict[int, str]] = {}
        self._watch_lock = threading.Lock()
        self._client_conns: dict[str, object] = {}
        self._watch_cond = threading.Condition()
        self._notify_acks: dict[tuple[int, int], bool] = {}
        self._last_scrub = 0.0
        self._scrubs_queued: set[str] = set()
        # reference: OSD::create_logger (l_osd_op / l_osd_op_w / ...)
        self.logger = cct.perf.add(
            PerfCountersBuilder("osd")
            .add_u64_counter("op", "client operations")
            .add_u64_counter("op_w", "client writes")
            .add_u64_counter("op_r", "client reads")
            .add_u64_counter("op_w_bytes", "bytes written")
            .add_u64_counter("op_r_bytes", "bytes read")
            .add_time_avg("op_latency", "op latency")
            .add_u64_counter("recovery_ops", "objects pushed in recovery")
            .add_u64_counter("stray_probes", "stray-location probes sent")
            .add_u64_counter("subop_w", "shard sub-writes applied")
            .add_u64_counter("scrubs", "PG scrubs completed")
            .add_u64_counter("scrub_errors", "shard inconsistencies found")
            .add_u64_counter("scrub_repairs", "shards repaired by scrub")
            .add_u64_counter("tier_promote", "cache-tier promotions")
            .add_u64_counter("tier_flush", "cache-tier flushes")
            .add_u64_counter("tier_evict", "cache-tier evictions")
            .add_u64("numpg", "placement groups hosted")
            .create_perf_counters()
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.store.mount()
        addr = self.messenger.bind(("127.0.0.1", 0))
        self.messenger.start()
        self.mc.subscribe_osdmap(callback=self._on_map)
        # resend boot until the map shows our address (reference: OSD
        # re-sends MOSDBoot until it sees itself up) — a boot riding a
        # connection that resets mid-handshake would otherwise be lost
        deadline = time.monotonic() + 30.0
        min_epoch = 1
        while True:
            try:
                self.mc.send_boot(self.id, addr)
            except (OSError, ConnectionError):
                pass
            try:
                m = self.mc.wait_for_osdmap(min_epoch=min_epoch, timeout=2.0)
            except TimeoutError:
                m = self.mc.osdmap
            if m is not None:
                if tuple(m.osd_addrs.get(self.id) or ()) == tuple(addr):
                    self.osdmap = m
                    break
                # wait for a NEWER epoch next round so the retry loop
                # blocks instead of spinning on the same stale map
                min_epoch = m.epoch + 1
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"{self.whoami}: boot not acknowledged in 30s"
                )
        self._load_pgs()
        self._tick_thread = threading.Thread(
            target=self._tick_loop, name=f"{self.whoami}-tick", daemon=True
        )
        self._tick_thread.start()
        # op worker pool draining the mClock queue (reference: osd_op_tp)
        for i in range(2):
            t = threading.Thread(
                target=self._op_worker, name=f"{self.whoami}-op-{i}",
                daemon=True,
            )
            self._workers.append(t)
            t.start()

    def _op_worker(self) -> None:
        while not self._stop.is_set():
            picked = self.scheduler.dequeue(timeout=1.0)
            if picked is None:
                continue
            cls, work = picked
            if cls == "client":
                # mClock orders ADMISSION; execution gets its own thread
                # so a client op blocked on a slow peer's sub-op never
                # pins a worker that background work (or the recovery
                # that would fix the peer) needs
                threading.Thread(
                    target=self._run_op, args=(work,),
                    name=f"{self.whoami}-op", daemon=True,
                ).start()
            else:
                # background work runs inline: worker count bounds its
                # concurrency, which is the point of the QoS classes
                self._run_op(work)

    def _run_op(self, work) -> None:
        try:
            work()
        except Exception as e:
            self.cct.dout("osd", 0, f"{self.whoami} op failed: {e!r}")

    def shutdown(self, umount: bool = True) -> None:
        """umount=False is the thrasher's CRASH kill: threads stop but
        the store is dropped without a graceful unmount, so a revive
        from the same directory exercises real WAL replay + fsck."""
        self._stop.set()
        self.scheduler.stop()
        self._recovery_wakeup.set()
        self.mc.shutdown()
        self.messenger.shutdown()
        if self._tick_thread is not None:
            self._tick_thread.join(timeout=5)
        if umount:
            self.store.umount()

    # -- map handling ------------------------------------------------------
    def _on_map(self, m: OSDMap) -> None:
        old = self.osdmap
        self.osdmap = m
        if old is not None:
            # interval bookkeeping (same_interval_since): a PG whose
            # up/acting changed starts a NEW interval at this epoch
            with self._pgs_lock:
                pgs = list(self.pgs.values())
            for pg in pgs:
                try:
                    o = old.pg_to_up_acting_osds(pg.pool_id, pg.ps)
                    n = m.pg_to_up_acting_osds(pg.pool_id, pg.ps)
                except Exception:
                    continue
                if (o[2], o[3]) != (n[2], n[3]):
                    # close the old interval into the history BEFORE
                    # starting the new one (reference: check_new_interval)
                    old_pool = old.pools.get(pg.pool_id)
                    went_rw = (
                        o[3] >= 0
                        and old_pool is not None
                        and sum(1 for a in o[2] if a >= 0)
                        >= old_pool.min_size
                    )
                    pg.past_intervals.add(
                        first=pg.interval_start or old.epoch,
                        last=m.epoch - 1,
                        up=o[0], acting=o[2], primary=o[3],
                        maybe_went_rw=went_rw,
                    )
                    pg.intervals_closed += 1
                    pg.interval_start = m.epoch
                    self._save_intervals(pg)
        self._recovery_wakeup.set()  # re-peer with the new map

    def my_epoch(self) -> int:
        return self.osdmap.epoch if self.osdmap else 0

    # -- helpers -----------------------------------------------------------
    def _codec_for_pool(self, pool):
        """Per-profile compiled codec cache (reference: ECBackend holds its
        ErasureCodeInterfaceRef; SURVEY.md §2.9 'per-profile kernel cache')."""
        name = pool.ec_profile or ""
        codec = self._codecs.get(name)
        if codec is None:
            profile = dict(self.osdmap.ec_profiles.get(name) or {})
            profile.setdefault("plugin", "jax")
            codec = ErasureCodePluginRegistry.instance().factory(profile)
            self._codecs[name] = codec
        return codec

    def _acting(self, pool_id: int, ps: int) -> tuple[list[int], int]:
        up, up_p, acting, acting_p = self.osdmap.pg_to_up_acting_osds(
            pool_id, ps
        )
        return acting, acting_p

    def _pg(self, pool_id: int, ps: int) -> PGState:
        pgid = f"{pool_id}.{ps}"
        with self._pgs_lock:
            pg = self.pgs.get(pgid)
            if pg is None:
                pg = PGState(pgid, pool_id, ps)
                self._load_pg_meta(pg)
                # an OSD (re)booting IS an interval change for its PGs:
                # without this a revived OSD would accept sub-ops from a
                # primary deposed while it was down (interval_start=0
                # would pass everything)
                pg.interval_start = self.my_epoch()
                self.pgs[pgid] = pg
            return pg

    def _cid(self, pgid: str, shard: int) -> str:
        return f"{pgid}s{shard}"

    def _conn_to_osd(self, osd: int):
        addr = self.osdmap.osd_addrs.get(osd)
        if addr is None:
            raise ConnectionError(f"no address for osd.{osd}")
        return self.messenger.connect(tuple(addr))

    def _next_tid(self) -> int:
        with self._lock:
            self._tid += 1
            return self._tid

    # -- persistence of PG meta -------------------------------------------
    def _load_pgs(self) -> None:
        for cid in self.store.list_collections():
            if "s" not in cid or "." not in cid:
                continue
            pgid = cid.rsplit("s", 1)[0]
            pool_id, ps = pgid.split(".")
            self._pg(int(pool_id), int(ps))

    def _load_pg_meta(self, pg: PGState) -> None:
        from .past_intervals import PastIntervals

        # any shard collection of this pg carries the meta object
        for cid in self.store.list_collections():
            if cid.rsplit("s", 1)[0] != pg.pgid:
                continue
            try:
                pairs = self.store.omap_get(cid, pg.meta_oid())
            except (NotFound, KeyError):
                continue
            head = int(pairs.get("head", b"0"))
            tail = int(pairs.get("tail", b"0"))
            pg.log = PGLog.load(pairs, head, tail)
            pg.version = head
            pg.past_intervals = PastIntervals.from_bytes(
                pairs.get("past_intervals")
            )
            pg.last_map_epoch = int(pairs.get("last_epoch", b"0"))
            pg.meta_cids.add(cid)
            return

    def _save_intervals(self, pg: PGState) -> None:
        """Persist the interval history + rebuild floor next to the PG
        log (same meta omap; reference: PastIntervals + history ride
        pg_info_t in the pg meta).  Uses the PG's known shard
        collections (meta_cids) — a full store scan per map change was
        O(pgs x collections) on the map-handling path (review r4); the
        scan runs once, only when the cache is cold."""
        if not pg.meta_cids:
            pg.meta_cids = {
                cid for cid in self.store.list_collections()
                if cid.rsplit("s", 1)[0] == pg.pgid
            }
            if not pg.meta_cids:
                # no local collection yet (freshly assigned primary):
                # stash under the would-be-primary shard so the history
                # survives a restart
                pg.meta_cids = {self._cid(pg.pgid, 0)}
        keys = {
            "past_intervals": pg.past_intervals.to_bytes(),
            "last_epoch": str(pg.last_map_epoch).encode(),
        }
        for cid in pg.meta_cids:
            t = Transaction()
            t.try_create_collection(cid)
            t.touch(cid, pg.meta_oid())
            t.omap_setkeys(cid, pg.meta_oid(), keys)
            self.store.queue_transaction(t)

    def _log_txn(self, t: Transaction, cid: str, pg: PGState,
                 entry: LogEntry) -> None:
        """Append the log entry + version keys to the same transaction as
        the data op (log/data atomicity, reference: PGLog::write_log)."""
        import json

        trimmed = pg.log.append(entry)
        pg.version = entry.version
        pg.last_map_epoch = self.my_epoch()
        keys = {
            PGLog.omap_key(entry.version): json.dumps(entry.to_list()).encode(),
            "head": str(pg.log.head).encode(),
            "tail": str(pg.log.tail).encode(),
            "last_epoch": str(pg.last_map_epoch).encode(),
        }
        t.touch(cid, pg.meta_oid())
        t.omap_setkeys(cid, pg.meta_oid(), keys)
        pg.meta_cids.add(cid)
        if trimmed:
            t.omap_rmkeys(
                cid, pg.meta_oid(), [PGLog.omap_key(e.version) for e in trimmed]
            )

    def _log_seal_txn(self, t: Transaction, cid: str, pg: PGState,
                      version: int) -> None:
        """Seal an empty log window at `version` (backfill completion)."""
        old_keys = [PGLog.omap_key(e.version) for e in pg.log.entries]
        pg.log.reset_to(version)
        pg.version = version
        t.touch(cid, pg.meta_oid())
        t.omap_setkeys(cid, pg.meta_oid(), {
            "head": str(version).encode(),
            "tail": str(version).encode(),
        })
        if old_keys:
            t.omap_rmkeys(cid, pg.meta_oid(), old_keys)

    # -- dispatch ----------------------------------------------------------
    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MOSDOp):
            src = getattr(msg, "src", None)
            if src is not None:
                # notify fan-out reaches a watcher over the SAME
                # connection its ops arrive on (reference: the watch's
                # Session connection).  Bounded: oldest client entries
                # are dropped (their watches re-linger on the next map)
                self._client_conns.pop(src, None)
                self._client_conns[src] = conn  # re-insert: LRU position
                if len(self._client_conns) > 512:
                    self._client_conns.pop(
                        next(iter(self._client_conns)), None)
            # client ops flow through the mClock queue (reference:
            # OSD::ms_fast_dispatch -> op_shardedwq enqueue)
            self.scheduler.enqueue(
                "client", lambda: self._handle_client_op(conn, msg)
            )
            return True
        if isinstance(msg, MWatchNotifyAck):
            with self._watch_cond:
                self._notify_acks[(msg.notify_id, msg.cookie)] = True
                # bound the ack ledger (ids are monotonic; stale ones
                # are dead after their notify's timeout)
                while len(self._notify_acks) > 4096:
                    self._notify_acks.pop(next(iter(self._notify_acks)))
                self._watch_cond.notify_all()
            return True
        if isinstance(msg, MECSubOpWrite):
            self._handle_sub_write(conn, msg)
            return True
        if isinstance(msg, MECSubOpRead):
            self._handle_sub_read(conn, msg)
            return True
        if isinstance(msg, MPGPull):
            self._handle_pg_pull(conn, msg)
            return True
        if isinstance(
            msg,
            (MECSubOpWriteReply, MECSubOpReadReply, MPGNotify,
             MScrubShardReply, MOSDOpReply, MPGPullReply),
        ):
            # MOSDOpReply arrives when this OSD acts as its own client
            # (split migration forwarding ops to the post-split primary)
            with self._lock:
                self._sub_replies[msg.tid] = msg
                self._cond.notify_all()
            return True
        if isinstance(msg, MPGQuery):
            self._handle_pg_query(conn, msg)
            return True
        if isinstance(msg, MPGClean):
            self._handle_pg_clean(msg)
            return True
        if isinstance(msg, MScrubShard):
            self._handle_scrub_shard(conn, msg)
            return True
        if isinstance(msg, MOSDPingMsg):
            if msg.op == "ping":
                try:
                    conn.send_message(
                        MOSDPingMsg(op="reply", osd=self.id, epoch=self.my_epoch())
                    )
                except (OSError, ConnectionError):
                    pass
            elif msg.op == "reply":
                self._hb_failures.pop(msg.osd, None)
            return True
        return False

    def _wait_reply(self, tid: int, timeout: float = 10.0):
        with self._lock:
            ok = self._cond.wait_for(
                lambda: tid in self._sub_replies, timeout=timeout
            )
            return self._sub_replies.pop(tid, None) if ok else None

    # -- client ops (primary) ---------------------------------------------
    def _handle_client_op(self, conn, msg: MOSDOp) -> None:
        t0 = time.perf_counter()
        self.logger.inc("op")
        if msg.op == "write_full":
            self.logger.inc("op_w")
            self.logger.inc("op_w_bytes", len(msg.data or "") * 3 // 4)
        elif msg.op == "read":
            self.logger.inc("op_r")
        try:
            reply = self._execute_client_op(msg)
        except Exception as e:  # never leave the client hanging
            self.cct.dout("osd", 0, f"{self.whoami} op failed: {e!r}")
            reply = MOSDOpReply(
                tid=msg.tid, retval=-5, epoch=self.my_epoch(),
                result=f"internal error: {e}",
            )
        if msg.op == "read" and reply.retval == 0 and reply.data:
            self.logger.inc("op_r_bytes", len(reply.data) * 3 // 4)
        self.logger.tinc("op_latency", time.perf_counter() - t0)
        try:
            conn.send_message(reply)
        except (OSError, ConnectionError):
            pass

    def _execute_client_op(self, msg: MOSDOp) -> MOSDOpReply:
        # the client targeted with a NEWER map than ours: wait for it
        # before deciding anything (reference: OSD::require_same_or_newer_map
        # waiting_for_map) — answering from the stale map would yield
        # false 'no such pool' / wrong-primary verdicts
        if msg.epoch and msg.epoch > self.my_epoch():
            deadline = time.monotonic() + 10.0
            while (
                msg.epoch > self.my_epoch()
                and time.monotonic() < deadline
                and not self._stop.is_set()
            ):
                time.sleep(0.05)
            if msg.epoch > self.my_epoch():
                # still behind: NACK retryably — answering from a map the
                # client provably outdates would yield FINAL wrong results
                # ('no such pool', wrong primary)
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result="waiting for newer osdmap",
                )
        m = self.osdmap
        pool = m.pools.get(msg.pool) if m else None
        if m is None or pool is None:
            return MOSDOpReply(tid=msg.tid, retval=-2, epoch=self.my_epoch(),
                               result="no such pool")
        if (
            msg.op in ("list", "scrub")
            and msg.oid
            and msg.oid.startswith(":pg:")
        ):
            ps = int(msg.oid[4:])  # pg-targeted op (tools/librados)
        elif getattr(msg, "ps", None) is not None:
            # explicit placement seed: the split migrator addressing an
            # object still housed in its pre-split PG
            ps = int(msg.ps)
        else:
            ps = object_ps(msg.oid, pool.pg_num) if msg.oid else 0
        if msg.op == "scrub":
            try:
                result = self.scrub_pg(msg.pool, ps, repair=True)
                return MOSDOpReply(tid=msg.tid, retval=0,
                                   epoch=self.my_epoch(), result=result)
            except RuntimeError:
                pass  # not primary: fall through to the -116 NACK below
        acting, primary = self._acting(msg.pool, ps)
        if primary != self.id:
            # client raced a map change (Objecter resend rule)
            return MOSDOpReply(
                tid=msg.tid, retval=-116, epoch=self.my_epoch(),
                result={"primary": primary},
            )
        pg = self._pg(msg.pool, ps)
        if pg.activated_interval != pg.interval_start:
            # not yet peered for the current interval: refuse retryably
            # and peer NOW (reference: ops wait on PG activation)
            self._recovery_wakeup.set()
            return MOSDOpReply(
                tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                result="peering: pg not active in this interval",
            )
        # dup detection + in-flight serialization (reference: pg_log dup
        # entries + PrimaryLogPG::check_in_progress_op): a resend of a
        # completed mutation is answered without re-executing — from the
        # reply cache, or (surviving primary changes) from the reqid the
        # REPLICATED log entry carries; a resend racing the still-running
        # original waits for it instead of double-executing
        reqid = getattr(msg, "reqid", None)
        if reqid is not None and msg.op in MUTATING_OPS:
            rep = self._check_dup(pg, pool, acting, msg, reqid)
            if rep is not None:
                return rep
            while True:
                guard = threading.Event()
                prior = pg.inflight.setdefault(reqid, guard)
                if prior is guard:
                    # we own the slot — but the original may have
                    # COMPLETED between our _check_dup miss and now
                    # (check-then-act): re-check before executing
                    rep = self._check_dup(pg, pool, acting, msg, reqid)
                    if rep is not None:
                        pg.inflight.pop(reqid, None)
                        guard.set()
                        return rep
                    break
                if not prior.wait(60.0):
                    # original STILL running (e.g. a long degraded
                    # splice): executing now would double-apply — refuse
                    # retryably and let the next resend re-check
                    return MOSDOpReply(
                        tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                        result="op with same reqid still in flight",
                    )
                rep = self._check_dup(pg, pool, acting, msg, reqid)
                if rep is not None:
                    return rep
                # the original died before logging anything — loop back
                # to CONTEND for the slot (setdefault): two waiters must
                # not both install themselves and double-execute
            try:
                return self._execute_routed_op(pg, pool, acting, ps, msg)
            finally:
                pg.inflight.pop(reqid, None)
                guard.set()
        return self._execute_routed_op(pg, pool, acting, ps, msg)

    def _check_dup(self, pg, pool, acting, msg, reqid) -> MOSDOpReply | None:
        """Reply for an already-seen reqid, or None to execute."""
        hit = pg.reqid_cache.get(reqid)
        if hit is not None and hit[0] == "forked":
            # executed here in a DEAD interval: the fork is invisible to
            # the real history; re-execute (a still-stale primary gets
            # deposed again until its map catches up)
            return None
        if hit is None:
            v = pg.log.find_reqid(reqid)
            if v is not None:
                hit = ("applied", v)
        if hit is None:
            return None
        if hit[0] == "done":
            return MOSDOpReply(tid=msg.tid, retval=hit[1],
                               epoch=self.my_epoch(), result=hit[2])
        # ("applied", v): the op mutated state exactly once but was
        # under-acked (< min_size commits) at the time.  Never re-execute.
        # Success is reported only when the write has ACTUALLY reached
        # min_size shards — counted from the per-object version stamps,
        # not mere reachability (reachable-but-unrecovered shards don't
        # hold the data yet).  Deletes are idempotent at the log level:
        # applied = done.
        if msg.op == "delete":
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"version": pg.version, "dup": True})
        holding = 0
        is_ec = pool.type == PG_POOL_ERASURE
        for shard, osd in enumerate(acting):
            if osd < 0:
                continue
            # replicated pools keep every replica in the shard-0
            # collection; only EC pools have per-shard collections
            store_shard = shard if is_ec else 0
            if osd == self.id:
                v = self._stored_ver(self._cid(pg.pgid, store_shard),
                                     msg.oid)
                if v is not None and v >= hit[1]:
                    holding += 1
                continue
            if not self.osdmap.is_up(osd):
                continue
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(MECSubOpRead(
                    tid=tid, pgid=pg.pgid, oid=msg.oid, shard=store_shard,
                    offsets=[], epoch=self.my_epoch(),
                ))
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=5.0)
            if rep is None or rep.retval != 0:
                continue
            v = getattr(rep, "ver", None)
            if v is not None and v >= hit[1]:
                holding += 1
        if holding >= pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"version": pg.version, "dup": True})
        # the op is durably logged but under-replicated: recovery is the
        # only path to an ack, so kick it rather than wait for the tick
        self._recovery_wakeup.set()
        return MOSDOpReply(
            tid=msg.tid, retval=-11, epoch=self.my_epoch(),
            result=f"applied at v{hit[1]}; {holding} shards hold it "
                   f"< min_size {pool.min_size}",
        )

    def _execute_routed_op(self, pg, pool, acting, ps, msg) -> MOSDOpReply:
        if msg.op == "write" and int(msg.off or 0) < 0:
            # reference: negative offsets are -EINVAL; Python slicing
            # would otherwise silently splice into the object's tail
            return MOSDOpReply(tid=msg.tid, retval=-22,
                               epoch=self.my_epoch(),
                               result="negative write offset")
        # cache-tier front-end: a PG in a cache pool stages/proxies/
        # whiteouts before normal execution (reference: PrimaryLogPG::
        # maybe_handle_cache_detail runs before do_op proper)
        if pool.tier_of >= 0 and pool.cache_mode != "none":
            rep = self._cache_tier_op(pg, pool, acting, ps, msg)
            if rep is not None:
                return self._record_reqid(pg, msg, rep)
        # pool snapshots (reference: make_writeable's clone-on-write +
        # SnapSet resolution in PrimaryLogPG)
        # clone against the newest LIVE snap (snap_seq never resets, and
        # cloning for snaps that no longer exist would leak un-trimmable
        # copies on every first write); the client's snap context covers
        # the window where this map lags a fresh mksnap
        live_max = max(pool.snaps, default=0)
        snap_seq = max(live_max, int(getattr(msg, "snap_seq", 0) or 0))
        if (
            msg.op in ("write_full", "write", "append", "delete")
            and snap_seq
            and msg.oid
            and CLONE_SEP not in msg.oid
            and getattr(msg, "ps", None) is None
            # explicit-ps ops are internal machinery (split migration,
            # trim), not client mutations: the split's old-PG delete must
            # not mint a stranded clone — the head's bytes live on,
            # unchanged, in the post-split PG
        ):
            try:
                head_existed = self._maybe_clone(pg, pool, msg.oid, snap_seq)
            except Exception as e:
                # clone failures are overwhelmingly transient races (a
                # map change mid-op re-targeting the internal clone
                # write, a peer mid-recovery): refuse RETRYABLY so the
                # client resends to the current primary — a fatal -EIO
                # here would fail a write that the next attempt performs
                # cleanly
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result=f"snap clone failed: {e}",
                )
            if msg.op in ("write_full", "write", "append") and not head_existed:
                rep = (
                    self._ec_op(pg, pool, acting, msg)
                    if pool.type == PG_POOL_ERASURE
                    else self._replicated_op(pg, pool, acting, msg)
                )
                if rep.retval == 0:
                    try:
                        self._mark_born(pg, pool, msg.oid, snap_seq)
                    except Exception as e:
                        # same contract as _set_born: a lost born marker
                        # would surface this object in snap views older
                        # than its creation, so fail the write instead
                        return MOSDOpReply(
                            tid=msg.tid, retval=-5, epoch=self.my_epoch(),
                            result=f"snapborn mark failed: {e}",
                        )
                return self._record_reqid(pg, msg, rep)
        if (
            msg.op == "read"
            and getattr(msg, "snapid", None)
            and CLONE_SEP not in msg.oid
        ):
            clone_oid = self._resolve_snap_read(
                pg, pool, acting, msg.oid, int(msg.snapid)
            )
            if clone_oid is None:
                # object was created after the snapshot
                return MOSDOpReply(
                    tid=msg.tid, retval=-2, epoch=self.my_epoch(),
                    result="did not exist at snap",
                )
            if clone_oid != msg.oid:
                msg = MOSDOp(
                    tid=msg.tid, pool=msg.pool, oid=clone_oid, op="read",
                    epoch=msg.epoch, off=msg.off, length=msg.length,
                    ps=ps,
                )
        if pool.type == PG_POOL_ERASURE:
            rep = self._ec_op(pg, pool, acting, msg)
        else:
            rep = self._replicated_op(pg, pool, acting, msg)
        return self._record_reqid(pg, msg, rep)

    def _collect_subop_acks(self, tids: dict, acting=None):
        """(acked_remote, deposed, failed_osds) over a tid->shard map.
        `deposed` = some peer answered -116: it is in a NEWER interval
        than the epoch we stamped — we may have been deposed mid-op."""
        acked = 0
        deposed = False
        failed: list[int] = []
        for tid, shard in tids.items():
            rep = self._wait_reply(tid)
            if rep is not None and rep.retval == 0:
                acked += 1
            elif rep is not None and rep.retval == -116:
                deposed = True
            elif acting is not None:
                failed.append(acting[shard])
        return acked, deposed, failed

    def _record_reqid(self, pg, msg, rep: MOSDOpReply) -> MOSDOpReply:
        """Remember a completed mutation's outcome for dup detection.
        Successes cache the full reply; an UNDER-ACKED mutation (applied
        and logged, but < min_size commits, reported -11) caches the
        applied-at version so the resend re-evaluates availability
        instead of re-executing — re-running an append/RMW would
        double-apply.  Plain refusals (gate -11, -ESTALE) that mutated
        nothing cache nothing and re-execute freely."""
        reqid = getattr(msg, "reqid", None)
        if reqid is None or msg.op not in MUTATING_OPS:
            return rep
        if rep.retval == 0:
            pg.reqid_cache[reqid] = ("done", rep.retval, rep.result)
        elif (
            rep.retval == -116
            and isinstance(rep.result, dict)
            and rep.result.get("deposed")
        ):
            # the op executed on a DEPOSED primary: its local log entry
            # is a fork in a dead interval — the marker stops this OSD's
            # own log from answering the resend as an "applied" dup
            pg.reqid_cache[reqid] = ("forked",)
        elif (
            rep.retval == -11
            and isinstance(rep.result, dict)
            and "applied" in rep.result
        ):
            pg.reqid_cache[reqid] = ("applied", rep.result["applied"])
            self._recovery_wakeup.set()  # under-acked: converge now
        else:
            return rep
        while len(pg.reqid_cache) > 1024:
            pg.reqid_cache.popitem(last=False)
        return rep

    # -- pool snapshots ----------------------------------------------------
    def _clone_oid(self, oid: str, snapid: int) -> str:
        return f"{oid}{CLONE_SEP}{snapid:08d}"

    def _maybe_clone(self, pg, pool, oid: str, snap_seq: int) -> None:
        """Clone-on-first-write-after-snap: preserve the head's bytes as
        clone `snap_seq` before an overwrite/delete mutates it.  The clone
        is a full normal object in the SAME PG (explicit ps), so
        replication/EC encoding, recovery, and scrub all cover it.

        The stat->read->write sequence is serialized under _clone_mutex:
        two concurrent writers racing it could otherwise both miss the
        stat and the later one would capture POST-snap bytes as the
        clone, corrupting the snapshot view."""
        with self._clone_mutex:
            return self._maybe_clone_locked(pg, pool, oid, snap_seq)

    def _maybe_clone_locked(self, pg, pool, oid: str, snap_seq: int) -> bool:
        """Returns True when the head EXISTED (clone made or already
        present); False = brand-new object this write creates."""
        clone = self._clone_oid(oid, snap_seq)
        e = self.my_epoch()
        st = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pool.pool_id, oid=clone, op="stat",
            epoch=e, ps=pg.ps,
        ))
        if st.retval == 0:
            # this snap generation already preserved; a retried clone
            # whose marker write was interrupted gets repaired here (the
            # marker is what keeps born-after objects out of older views)
            if self._born_of(pg, pool, clone) == 0:
                born = self._born_of(pg, pool, oid)
                if born:
                    self._set_born(pg, pool, clone, born)
            return True
        r = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pool.pool_id, oid=oid, op="read",
            epoch=e, ps=pg.ps, off=0, length=0,
        ))
        if r.retval != 0:
            return False  # no head: nothing to preserve
        w = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pool.pool_id, oid=clone,
            op="write_full", data=r.data, epoch=e, ps=pg.ps,
        ))
        if w.retval != 0:
            raise RuntimeError(f"clone write: {w.result}")
        born = self._born_of(pg, pool, oid)
        if born:
            self._set_born(pg, pool, clone, born)
        return True

    def _set_born(self, pg, pool, oid: str, born: int) -> None:
        r = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pool.pool_id, oid=oid,
            op="setxattr", epoch=self.my_epoch(), ps=pg.ps,
            data={"_snapborn": pack_data(str(born).encode())},
        ))
        if r.retval != 0:
            # fail the client write rather than leave a clone that would
            # surface a born-after object in older snap views
            raise RuntimeError(f"clone born-marker write: {r.result}")

    def _born_of(self, pg, pool, oid: str) -> int:
        """Snap generation an object (head or clone) was created in; 0 =
        pre-snapshot or unmarked."""
        xr = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pool.pool_id, oid=oid,
            op="getxattrs", epoch=self.my_epoch(), ps=pg.ps,
        ))
        if xr.retval == 0 and isinstance(xr.result, dict):
            born = xr.result.get("_snapborn")
            if born is not None:
                try:
                    return int(unpack_data(born).decode())
                except (ValueError, AttributeError):
                    pass
        return 0

    def _mark_born(self, pg, pool, oid: str, snap_seq: int) -> None:
        """Stamp a newly created object with the snap generation it was
        born in, so snapshot reads older than its creation return ENOENT
        instead of the head (reference: SnapSet knows object existence
        per snap).  Rides the replicated user-xattr path under a
        reserved '_'-name the client surface filters out.  Raises on
        persistent failure (after one retry) — the caller fails the
        client write, matching _set_born's contract."""
        r = None
        for _ in range(2):
            r = self._execute_client_op(MOSDOp(
                tid=self._next_tid(), pool=pool.pool_id, oid=oid,
                op="setxattr", epoch=self.my_epoch(), ps=pg.ps,
                data={"_snapborn": pack_data(str(snap_seq).encode())},
            ))
            if r.retval == 0:
                return
        raise RuntimeError(f"snapborn marker write: {r.result}")

    def _primary_cid(self, pg, pool, acting) -> str:
        shard = acting.index(self.id) if pool.type == PG_POOL_ERASURE else 0
        return self._cid(pg.pgid, shard)

    def _resolve_snap_read(
        self, pg, pool, acting, oid: str, snapid: int
    ) -> str:
        """Oldest clone at-or-after `snapid` serves the snapshot view; no
        such clone means the head hasn't changed since (or never existed).
        reference: SnapSet::get_clone_bytes / find_object lookup."""
        prefix = oid + CLONE_SEP
        try:
            names = self.store.list_objects(
                self._primary_cid(pg, pool, acting)
            )
        except (NotFound, KeyError):
            return oid
        ids = sorted(
            int(n[len(prefix):]) for n in names if n.startswith(prefix)
        )
        for c in ids:
            if c >= snapid:
                clone = self._clone_oid(oid, c)
                # the clone inherits its head's born marker: a clone made
                # AFTER a post-snap creation must not make the object
                # appear in older snap views
                if self._born_of(pg, pool, clone) >= snapid:
                    return None
                return clone
        # no clone: the head serves the snap view — unless the object was
        # born after the snapshot (its _snapborn generation >= snapid)
        if self._born_of(pg, pool, oid) >= snapid:
            return None
        return oid

    def _snaptrim_pass(self) -> None:
        """Remove clones no live snap needs (reference: the snap-trim
        queue PrimaryLogPG works through after a snap is deleted, fed by
        SnapMapper).  A clone c of a head covers snaps in (prev_clone, c];
        with none of those alive it is garbage."""
        m = self.osdmap
        if m is None:
            return
        for pgid, pg in list(self.pgs.items()):
            if self._stop.is_set():
                return
            pool = m.pools.get(pg.pool_id)
            if pool is None:
                continue
            live_key = tuple(sorted(pool.snaps))
            if pg.snap_trimmed == live_key:
                continue
            acting, primary = self._acting(pg.pool_id, pg.ps)
            if primary != self.id or self.id not in acting:
                continue
            try:
                self._snaptrim_pg(pg, pool, acting, live_key)
                pg.snap_trimmed = live_key
            except Exception as e:
                self.cct.dout(
                    "osd", 1, f"{self.whoami} snaptrim {pgid}: {e!r}"
                )

    def _snaptrim_pg(self, pg, pool, acting, live_key) -> None:
        try:
            names = self.store.list_objects(
                self._primary_cid(pg, pool, acting)
            )
        except (NotFound, KeyError):
            return
        by_head: dict[str, list[int]] = {}
        for n in names:
            if CLONE_SEP in n:
                head, _, suffix = n.partition(CLONE_SEP)
                by_head.setdefault(head, []).append(int(suffix))
        live = sorted(live_key)
        snap_seq = max([pool.snap_seq, *live_key]) if live_key else pool.snap_seq
        for head, ids in by_head.items():
            ids.sort()
            prev = 0
            for c in ids:
                if c > snap_seq:
                    # a generation this map hasn't seen yet (clone minted
                    # from a newer client's snap context right after a
                    # mksnap): deleting it would destroy the new snapshot
                    prev = c
                    continue
                needed = any(prev < s <= c for s in live)
                prev = c
                if needed:
                    continue
                d = self._execute_client_op(MOSDOp(
                    tid=self._next_tid(), pool=pool.pool_id,
                    oid=self._clone_oid(head, c), op="delete",
                    epoch=self.my_epoch(), ps=pg.ps,
                ))
                if d.retval != 0:
                    raise RuntimeError(f"trim {head}@{c}: {d.result}")

    # .. EC pool ...........................................................
    def _ec_op(self, pg: PGState, pool, acting: list[int], msg: MOSDOp):
        codec = self._codec_for_pool(pool)
        my_shard = acting.index(self.id)
        if msg.op in ("write_full", "write", "append", "delete"):
            # min_size gate BEFORE any mutation (reference: PrimaryLogPG
            # refuses ops while acting < pool.min_size): refusing up front
            # both protects durability (never take a write we may not be
            # able to re-protect) and keeps -EAGAIN retries side-effect
            # free — a partially-applied-then-refused write would make
            # the client resend double-apply
            reachable = sum(
                1 for o in acting
                if o >= 0 and (o == self.id or self.osdmap.is_up(o))
            )
            if reachable < pool.min_size:
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result=f"{reachable} acting shards reachable < "
                           f"min_size {pool.min_size}",
                )
        if msg.op == "write_full":
            data = unpack_data(msg.data) or b""
            with pg.lock:
                return self._ec_write(
                    pg, pool, codec, acting, my_shard, msg, data
                )
        if msg.op in ("write", "append"):
            data = unpack_data(msg.data) or b""
            with pg.lock:
                return self._ec_rmw(
                    pg, pool, codec, acting, my_shard, msg, data
                )
        if msg.op == "read":
            return self._ec_read(pg, codec, acting, msg)
        if msg.op == "delete":
            with pg.lock:
                return self._ec_delete(pg, acting, my_shard, msg)
        if msg.op == "stat":
            try:
                size = int(
                    self.store.getattr(
                        self._cid(pg.pgid, my_shard), msg.oid, "size"
                    )
                )
                return MOSDOpReply(tid=msg.tid, retval=0,
                                   epoch=self.my_epoch(),
                                   result={"size": size, "version": pg.version})
            except (NotFound, KeyError):
                return MOSDOpReply(tid=msg.tid, retval=-2,
                                   epoch=self.my_epoch(), result="not found")
        if msg.op == "list":
            oids = sorted(
                o for o in self.store.list_objects(self._cid(pg.pgid, my_shard))
                if not o.startswith("_") and CLONE_SEP not in o
            )
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"oids": oids})
        if msg.op in ("setxattr", "getxattrs"):
            return self._xattr_op(pg, acting, my_shard, msg)
        if msg.op.startswith("omap_") or msg.op == "exec":
            # reference parity: EC pools support neither omap nor the
            # omap-backed object classes
            # (PrimaryLogPG::do_osd_ops returns -EOPNOTSUPP)
            return MOSDOpReply(tid=msg.tid, retval=-95,
                               epoch=self.my_epoch(),
                               result=f"{msg.op} not supported on EC pools")
        if msg.op in ("watch", "unwatch", "notify"):
            return self._watch_op(pg, pool, msg)
        return MOSDOpReply(tid=msg.tid, retval=-22, epoch=self.my_epoch(),
                           result=f"bad op {msg.op}")

    # .. user xattrs (both pool types) .....................................
    def _xattr_op(self, pg, acting, my_shard, msg) -> MOSDOpReply:
        """librados xattr surface (reference: rados_setxattr/getxattrs).
        User attrs live as `u_<name>` on every shard so any future primary
        answers; updates append a pg_log entry so recovery replays them."""
        cid = self._cid(pg.pgid, my_shard)
        if msg.op == "getxattrs":
            try:
                attrs = {
                    n[2:]: pack_data(v)
                    for n, v in self.store.getattrs(cid, msg.oid).items()
                    if n.startswith("u_")
                }
            except (NotFound, KeyError):
                # degraded primary (remap before recovery): any shard that
                # holds the object carries the same user xattrs
                attrs = self._probe_peer_xattrs(pg, acting, msg.oid)
                if attrs is None:
                    return MOSDOpReply(
                        tid=msg.tid, retval=-2, epoch=self.my_epoch(),
                        result="not found",
                    )
            return MOSDOpReply(
                tid=msg.tid, retval=0, epoch=self.my_epoch(), result=attrs
            )
        updates = msg.data or {}
        pool = self.osdmap.pools.get(pg.pool_id)
        # user-xattr content flushes to the base pool: a cache-pool user
        # setxattr re-dirties the object atomically (merged into the SAME
        # update set / sub-ops) and stamps `ver` so the flush's version
        # recheck also sees xattr-only mutations.  Tier-marker updates
        # (tier.*) are the dirty-tracking machinery itself and must not
        # self-trigger.
        user_mutation = any(not n.startswith("tier.") for n in updates)
        stamp_ver = False
        if (user_mutation and self._tier_autoclean(pool, msg.oid)
                and "tier.clean" not in updates):
            updates = dict(updates)
            updates["tier.clean"] = None
            stamp_ver = True
        with pg.lock:
            try:
                self.store.stat(cid, msg.oid)
            except (NotFound, KeyError):
                # no local copy: object missing cluster-wide (-2, final)
                # vs degraded primary pending recovery (-11, retryable)
                if self._probe_peer_xattrs(pg, acting, msg.oid) is None:
                    return MOSDOpReply(
                        tid=msg.tid, retval=-2, epoch=self.my_epoch(),
                        result="not found",
                    )
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result="object not recovered here yet",
                )
            version = pg.version + 1
            entry = LogEntry(version, "attr", msg.oid)
            tids: dict[int, int] = {}
            for shard, osd in enumerate(acting):
                if osd == self.id or osd < 0 or not self.osdmap.is_up(osd):
                    continue
                tid = self._next_tid()
                tids[tid] = shard
                try:
                    self._conn_to_osd(osd).send_message(
                        MECSubOpWrite(
                            tid=tid, pgid=pg.pgid, oid=msg.oid,
                            shard=shard if self._is_ec_pg(pg) else 0,
                            data=None, crc=None, version=version,
                            entry=entry.to_list(), epoch=self.my_epoch(),
                            xattrs=updates,
                        )
                    )
                except (OSError, ConnectionError):
                    tids.pop(tid, None)
            t = Transaction()
            self._apply_xattr_updates(t, cid, msg.oid, updates)
            if stamp_ver:
                t.setattr(cid, msg.oid, "ver", str(version).encode())
            self._log_txn(t, cid, pg, entry)
            self.store.queue_transaction(t)
            a, deposed, _f = self._collect_subop_acks(tids)
            acked = 1 + a
        if deposed and (pool is None or acked < pool.min_size):
            return MOSDOpReply(tid=msg.tid, retval=-116,
                               epoch=self.my_epoch(),
                               result={"deposed": True})
        # same durability bar as write_full: the update must be on enough
        # shards to survive (reference: xattr ops ride the same repop)
        if pool is not None and acked < pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=-11,
                               epoch=self.my_epoch(),
                               result=f"only {acked} shard commits")
        return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                           result={"version": pg.version})

    def _apply_xattr_updates(self, t: Transaction, cid: str, oid: str,
                             updates: dict, snapshot: bool = False) -> None:
        """Apply user-xattr updates {name: b64|None} to a transaction;
        snapshot=True means `updates` is the complete set (recovery) and
        any other u_* attr must go."""
        try:
            existing = {
                n[2:] for n in self.store.getattrs(cid, oid)
                if n.startswith("u_")
            }
        except (NotFound, KeyError):
            existing = set()
        for name, val in updates.items():
            if val is None:
                if name in existing:
                    t.rmattr(cid, oid, f"u_{name}")
            else:
                t.setattr(cid, oid, f"u_{name}", unpack_data(val))
        if snapshot:
            for name in existing - set(updates):
                t.rmattr(cid, oid, f"u_{name}")

    def _probe_peer_xattrs(self, pg, acting, oid: str) -> dict | None:
        """User xattrs for oid from the FRESHEST up shard (degraded
        getxattrs).  Peers are ordered by their pg_log version so a
        just-revived stale shard cannot answer with pre-update attrs;
        metadata-only reads (offsets=[]) keep the object body off the
        wire."""
        is_ec = self._is_ec_pg(pg)
        peers = []  # (version, shard, osd)
        for shard, osd in enumerate(acting):
            if osd < 0 or osd == self.id or not self.osdmap.is_up(osd):
                continue
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(
                    MPGQuery(tid=tid, pgid=pg.pgid,
                             shard=shard if is_ec else 0,
                             epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=5.0)
            peers.append(
                ((rep.version if rep is not None else 0) or 0, shard, osd)
            )
        for _v, shard, osd in sorted(peers, reverse=True):
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpRead(
                        tid=tid, pgid=pg.pgid, oid=oid,
                        shard=shard if is_ec else 0,
                        offsets=[], epoch=self.my_epoch(),
                    )
                )
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=5.0)
            if rep is not None and rep.retval == 0:
                return rep.xattrs or {}
        return None

    def _is_ec_pg(self, pg) -> bool:
        pool = self.osdmap.pools.get(pg.pool_id) if self.osdmap else None
        return bool(pool and pool.type == PG_POOL_ERASURE)

    def _ec_write(self, pg, pool, codec, acting, my_shard, msg, data) -> MOSDOpReply:
        n = codec.get_chunk_count()
        enc = codec.encode(set(range(n)), data)
        version = pg.version + 1
        # entry rides a 4th element (object size) so every shard can answer
        # size/stat even after the primary moves
        entry = LogEntry(version, "modify", msg.oid,
                         reqid=getattr(msg, "reqid", None))
        wire_entry = entry.to_list()
        tids: dict[int, int] = {}
        for shard, osd in enumerate(acting):
            if shard == my_shard or osd < 0:
                continue
            if not self.osdmap.is_up(osd):
                continue
            chunk = np.asarray(enc[shard], np.uint8).tobytes()
            tid = self._next_tid()
            tids[tid] = shard
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpWrite(
                        tid=tid, pgid=pg.pgid, oid=msg.oid, shard=shard,
                        data=pack_data(chunk), crc=crc32c(chunk),
                        version=version, entry=wire_entry,
                        epoch=self.my_epoch(), osize=len(data),
                    )
                )
            except (OSError, ConnectionError):
                tids.pop(tid, None)
                self.mc.report_failure(osd)
        # local shard commit (chunk + log in one transaction)
        cid = self._cid(pg.pgid, my_shard)
        chunk = np.asarray(enc[my_shard], np.uint8).tobytes()
        t = Transaction()
        t.try_create_collection(cid)
        t.write(cid, msg.oid, 0, chunk)
        t.truncate(cid, msg.oid, len(chunk))
        t.setattr(cid, msg.oid, "hinfo", str(crc32c(chunk)).encode())
        t.setattr(cid, msg.oid, "size", str(len(data)).encode())
        t.setattr(cid, msg.oid, "ver", str(version).encode())
        self._log_txn(t, cid, pg, entry)
        self.store.queue_transaction(t)
        a, deposed, failed = self._collect_subop_acks(tids, acting)
        acked = 1 + a
        for osd in failed:
            self.mc.report_failure(osd)
        if deposed and acked < pool.min_size:
            # deposed mid-op below quorum: the local apply is a FORK in a
            # dead interval — never acked, never answered as a dup
            # (_record_reqid marks the reqid "forked" so the resend
            # re-executes on the real primary).  At >= min_size the op
            # is durable in THIS interval despite the stray -116 (e.g. a
            # peer that just rebooted): ack it normally below.
            return MOSDOpReply(tid=msg.tid, retval=-116,
                               epoch=self.my_epoch(),
                               result={"deposed": True})
        # degraded-write policy: ack at min_size commits.  Shards that
        # missed the write are reported to the mon and filled by delta
        # recovery off the pg_log (reference: ECBackend requires min_size
        # acting shards; recovery completes the stripe)
        if acked >= pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"version": pg.version, "acked": acked})
        # structured under-ack refusal: the op IS applied+logged locally;
        # "applied" lets dup detection refuse re-execution on the resend
        return MOSDOpReply(tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                           result={"applied": pg.version, "acked": acked,
                                   "error": "below min_size commits"})

    # .. partial-stripe RMW ................................................
    def _ec_object_size(self, pg, acting, oid: str):
        """Stored object size (the `size` xattr), local shard preferred,
        else reachable peers' metadata probes.  Returns an int, "absent"
        (a shard DEFINITIVELY reported no such object), or "unknown"
        (nobody answered either way — e.g. transient connection faults).
        The distinction matters: treating unreachable as absent would
        let a ranged write re-create an existing object as zeros."""
        for shard, osd in enumerate(acting):
            if osd != self.id:
                continue
            try:
                return int(self.store.getattr(
                    self._cid(pg.pgid, shard), oid, "size"))
            except (NotFound, KeyError, ValueError):
                break
        verdict = "unknown"
        best_size = None
        best_ver = -1
        for shard, osd in enumerate(acting):
            if osd < 0 or osd == self.id or not self.osdmap.is_up(osd):
                continue
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpRead(tid=tid, pgid=pg.pgid, oid=oid, shard=shard,
                                 offsets=[], epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid)
            if rep is None:
                continue
            if rep.retval == 0 and rep.size is not None:
                # prefer the NEWEST-generation shard's size: a stale
                # shard that missed the last append would hand back the
                # old size and the append would overwrite live bytes
                v = getattr(rep, "ver", None)
                if v is None:
                    v = 0
                if v > best_ver or best_size is None:
                    best_ver, best_size = v, int(rep.size)
            elif rep.retval == -2:
                verdict = "absent"  # a live shard is sure it isn't there
        if best_size is not None:
            return best_size
        return verdict

    def _fetch_shard_range(self, pg, acting, shard: int, oid: str,
                           off: int, ln: int):
        """(`ln` bytes at `off` of one shard's stored chunk, that shard's
        stored per-object version) — local or via a ranged MECSubOpRead.
        (None, None) = holder down / chunk missing / short read."""
        osd = acting[shard] if shard < len(acting) else -1
        if osd == self.id:
            cid = self._cid(pg.pgid, shard)
            try:
                b = self.store.read(cid, oid, off, ln)
            except (NotFound, KeyError):
                return None, None
            return (bytes(b), self._stored_ver(cid, oid)) \
                if len(b) == ln else (None, None)
        if osd < 0 or not self.osdmap.is_up(osd):
            return None, None
        tid = self._next_tid()
        try:
            self._conn_to_osd(osd).send_message(
                MECSubOpRead(tid=tid, pgid=pg.pgid, oid=oid, shard=shard,
                             offsets=[[off, ln]], epoch=self.my_epoch())
            )
        except (OSError, ConnectionError):
            return None, None
        rep = self._wait_reply(tid)
        if rep is None or rep.retval != 0:
            return None, None
        b = unpack_data(rep.data) or b""
        return (b, rep.ver) if len(b) == ln else (None, None)

    def _stored_ver(self, cid: str, oid: str) -> int | None:
        """Per-object version xattr (object_info_t analog); None =
        unversioned (legacy object or backfill-pushed wildcard)."""
        try:
            v = self.store.getattr(cid, oid, "ver")
        except (NotFound, KeyError):
            return None
        try:
            return int(v)
        except (TypeError, ValueError):
            return None

    def _rmw_apply_local(self, t: Transaction, cid: str, oid: str,
                         full: bytearray, off: int, payload: bytes,
                         xor: bool) -> None:
        """Splice (xor=False) or GF-XOR (xor=True) `payload` into the
        primary's own pre-validated chunk bytes `full` at `off`, keeping
        the hinfo CRC current."""
        if xor:
            seg = (
                np.frombuffer(bytes(full[off:off + len(payload)]), np.uint8)
                ^ np.frombuffer(payload, np.uint8)
            ).tobytes()
        else:
            seg = payload
        full[off:off + len(seg)] = seg
        t.write(cid, oid, off, seg)
        t.setattr(cid, oid, "hinfo", str(crc32c(bytes(full))).encode())

    def _ec_full_splice(self, pg, pool, codec, acting, my_shard, msg,
                        data: bytes, off: int, size) -> MOSDOpReply:
        """RMW slow path: read the whole (possibly degraded) object,
        splice, re-encode everything via the full-object write.  Used when
        the write grows the stripe, the codec is sub-chunked (CLAY), or an
        affected shard's old bytes are unreachable (reconstruction needed).
        """
        old = b""
        if size:
            rd = self._ec_read(pg, codec, acting, MOSDOp(
                tid=self._next_tid(), pool=msg.pool, oid=msg.oid, op="read",
                epoch=self.my_epoch(), ps=pg.ps,
            ))
            if rd.retval != 0:
                # the current generation is temporarily sourceless
                # (unfound-pending): refuse retryably — serving/splicing
                # a stale base would launder a rollback into a fresh
                # version (reference: ops wait on missing objects)
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result=f"rmw base unreadable now: {rd.result}",
                )
            old = unpack_data(rd.data) or b""
        buf = bytearray(max(len(old), off + len(data)))
        buf[:len(old)] = old
        buf[off:off + len(data)] = data
        return self._ec_write(pg, pool, codec, acting, my_shard, msg,
                              bytes(buf))

    def _ec_rmw(self, pg, pool, codec, acting, my_shard, msg,
                data: bytes) -> MOSDOpReply:
        """Ranged write / append on an EC object (reference:
        src/osd/ECTransaction.cc :: generate_transactions — the RMW that
        reads the old stripe remainder and re-encodes the touched stripes;
        expressed here as a PARITY-DELTA update, the optimized-EC
        formulation, which is also the TPU-shaped one: the parity delta is
        one GF matrix apply over just the touched column window).

        Correctness rests on GF-linearity of every registered plugin's
        encode_chunks: parity(new) = parity(old) XOR parity(delta), column
        by column.  Shards that would fuse stale bytes with the delta
        refuse the sub-op (version-jump guard in _handle_sub_write) and
        are rebuilt by log-delta recovery instead."""
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        size = self._ec_object_size(pg, acting, msg.oid)
        if size == "unknown":
            # can't tell whether the object exists (transient faults):
            # refusing retryably is the only safe answer — guessing
            # "absent" would zero-fill over live data
            return MOSDOpReply(tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                               result="object existence unknown (peers "
                                      "unreachable)")
        if size == "absent":
            size = None
        off = (size or 0) if msg.op == "append" else int(msg.off or 0)
        if not data:
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"version": pg.version})
        end = off + len(data)
        if size is None:
            # object doesn't exist yet: a ranged write below `off` reads
            # back as zeros (reference: sparse write semantics)
            return self._ec_write(pg, pool, codec, acting, my_shard, msg,
                                  b"\x00" * off + data)
        L = codec.get_chunk_size(size) if size else 0
        sub_chunks = 1
        try:
            sub_chunks = codec.get_sub_chunk_count()
        except Exception:
            pass
        try:
            delta_ok = bool(codec.supports_parity_delta())
        except Exception:
            delta_ok = False
        if size == 0 or end > k * L or sub_chunks != 1 or not delta_ok:
            # codecs whose encode is not byte-column-local (bitmatrix
            # packet techniques, CLAY sub-chunks, LRC remapping) re-encode
            # the full stripe — a windowed delta would corrupt parity
            return self._ec_full_splice(pg, pool, codec, acting, my_shard,
                                        msg, data, off, size)
        # local pre-validation: the delta fast path needs the primary's
        # own chunk present, rot-free, and version-stamped — the stamp is
        # the authoritative old object version every other shard must
        # match (the primary serialized all prior writes)
        cid = self._cid(pg.pgid, my_shard)
        try:
            my_chunk = bytearray(self.store.read(cid, msg.oid))
        except (NotFound, KeyError):
            return self._ec_full_splice(pg, pool, codec, acting, my_shard,
                                        msg, data, off, size)
        my_ver = self._stored_ver(cid, msg.oid)
        try:
            stored_h = int(self.store.getattr(cid, msg.oid, "hinfo"))
        except (NotFound, KeyError, ValueError):
            stored_h = None
        floor = pg.log.obj_newest.get(msg.oid)
        if (
            my_ver is None
            or (floor is not None and my_ver < floor)
            or len(my_chunk) != L
            or (stored_h is not None and crc32c(bytes(my_chunk)) != stored_h)
        ):
            # unversioned legacy object, unexpected chunk length, or
            # local rot (full-splice reads exclude the rotted chunk and
            # the re-encode heals it)
            return self._ec_full_splice(pg, pool, codec, acting, my_shard,
                                        msg, data, off, size)
        # per-data-shard touched segments: shard j holds object bytes
        # [j*L, (j+1)*L) (contiguous-split layout, ErasureCode.encode_prepare)
        segs: dict[int, tuple[int, bytes]] = {}
        for j in range(k):
            lo, hi = max(off, j * L), min(end, (j + 1) * L)
            if lo < hi:
                segs[j] = (lo - j * L, data[lo - off:hi - off])
        c0 = min(o for o, _ in segs.values())
        c1 = max(o + len(b) for o, b in segs.values())
        w = c1 - c0
        old: dict[int, bytes] = {}
        for j, (o, b) in segs.items():
            if j == my_shard:
                old[j] = bytes(my_chunk[o:o + len(b)])
                continue
            ob, over = self._fetch_shard_range(
                pg, acting, j, msg.oid, o, len(b)
            )
            if ob is None or over != my_ver:
                # unreachable, or the holder is a STALE generation whose
                # old bytes would poison the parity delta (the retry-
                # after-partial-apply case): reconstruct via the decode
                # slow path instead, which filters by version
                return self._ec_full_splice(pg, pool, codec, acting,
                                            my_shard, msg, data, off, size)
            old[j] = ob
        # parity delta = encode_chunks(delta window): zero rows for
        # untouched shards, new^old for touched ones; padded to the
        # codec's alignment (zero delta => zero parity delta, trim back)
        W = codec.get_chunk_size(k * w)
        delta = np.zeros((k, W), np.uint8)
        for j, (o, b) in segs.items():
            delta[j, o - c0:o - c0 + len(b)] = (
                np.frombuffer(b, np.uint8) ^ np.frombuffer(old[j], np.uint8)
            )
        parity_delta = np.asarray(codec.encode_chunks(delta), np.uint8)[:, :w]
        new_size = max(size, end)
        version = pg.version + 1
        entry = LogEntry(version, "modify", msg.oid,
                         reqid=getattr(msg, "reqid", None))
        wire_entry = entry.to_list()
        tids: dict[int, int] = {}
        for shard, osd in enumerate(acting):
            if shard == my_shard or osd < 0 or not self.osdmap.is_up(osd):
                continue
            if shard in segs:
                mode, moff, payload = "range", segs[shard][0], segs[shard][1]
            elif shard >= k:
                mode, moff = "delta", c0
                payload = parity_delta[shard - k].tobytes()
            else:
                mode, moff, payload = None, None, None  # entry+size only
            tid = self._next_tid()
            tids[tid] = shard
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpWrite(
                        tid=tid, pgid=pg.pgid, oid=msg.oid, shard=shard,
                        data=pack_data(payload) if payload is not None
                        else None,
                        crc=crc32c(payload) if payload is not None else None,
                        version=version, entry=wire_entry,
                        epoch=self.my_epoch(), mode=mode, off=moff,
                        over=my_ver, osize=new_size,
                    )
                )
            except (OSError, ConnectionError):
                tids.pop(tid, None)
                self.mc.report_failure(osd)
        t = Transaction()
        t.try_create_collection(cid)
        if my_shard in segs:
            o, b = segs[my_shard]
            self._rmw_apply_local(t, cid, msg.oid, my_chunk, o, b, xor=False)
        elif my_shard >= k:
            self._rmw_apply_local(
                t, cid, msg.oid, my_chunk, c0,
                parity_delta[my_shard - k].tobytes(), xor=True,
            )
        t.setattr(cid, msg.oid, "size", str(new_size).encode())
        t.setattr(cid, msg.oid, "ver", str(version).encode())
        self._log_txn(t, cid, pg, entry)
        self.store.queue_transaction(t)
        a, deposed, failed = self._collect_subop_acks(tids, acting)
        acked = 1 + a
        for osd in failed:
            self.mc.report_failure(osd)
        if deposed and acked < pool.min_size:
            # deposed mid-op below quorum: the local apply is a FORK in a
            # dead interval — never acked, never answered as a dup
            # (_record_reqid marks the reqid "forked" so the resend
            # re-executes on the real primary).  At >= min_size the op
            # is durable in THIS interval despite the stray -116 (e.g. a
            # peer that just rebooted): ack it normally below.
            return MOSDOpReply(tid=msg.tid, retval=-116,
                               epoch=self.my_epoch(),
                               result={"deposed": True})
        if acked >= pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"version": pg.version, "acked": acked})
        # structured under-ack refusal: the op IS applied+logged locally;
        # "applied" lets dup detection refuse re-execution on the resend
        return MOSDOpReply(tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                           result={"applied": pg.version, "acked": acked,
                                   "error": "below min_size commits"})

    def _ec_delete(self, pg, acting, my_shard, msg) -> MOSDOpReply:
        version = pg.version + 1
        entry = LogEntry(version, "delete", msg.oid,
                         reqid=getattr(msg, "reqid", None))
        tids: dict[int, int] = {}
        for shard, osd in enumerate(acting):
            if shard == my_shard or osd < 0 or not self.osdmap.is_up(osd):
                continue
            tid = self._next_tid()
            tids[tid] = shard
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpWrite(
                        tid=tid, pgid=pg.pgid, oid=msg.oid, shard=shard,
                        data=None, crc=None, version=version,
                        entry=entry.to_list(), epoch=self.my_epoch(),
                    )
                )
            except (OSError, ConnectionError):
                tids.pop(tid, None)
        cid = self._cid(pg.pgid, my_shard)
        t = Transaction()
        t.try_create_collection(cid)
        try:
            self.store.stat(cid, msg.oid)
            t.remove(cid, msg.oid)
        except (NotFound, KeyError):
            pass
        self._log_txn(t, cid, pg, entry)
        self.store.queue_transaction(t)
        for tid in tids:
            self._wait_reply(tid)
        return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                           result={"version": pg.version})

    def _gather_chunks(
        self, pg, codec, acting, oid: str, want: set[int],
        sizes: dict[int, int] | None = None,
        vers: dict[int, int | None] | None = None,
        stray: bool = False,
        floor: int | None = None,
    ) -> dict[int, bytes]:
        """Fetch chunk bytes for shard ids in `want` (local or remote).
        `sizes`, if given, collects the object-size xattr each replying
        shard reports (for padding-strip when the primary has no copy);
        `vers` likewise collects each shard's stored per-object version
        (None = wildcard) for stale-generation filtering.  `stray` also
        probes non-acting locations for shards the acting map cannot
        serve (see _gather_stray_chunks)."""
        got: dict[int, bytes] = {}
        tids: dict[int, int] = {}
        for shard in sorted(want):
            osd = acting[shard] if shard < len(acting) else -1
            if osd == self.id:
                cid = self._cid(pg.pgid, shard)
                try:
                    chunk = self.store.read(cid, oid)
                except (NotFound, KeyError):
                    continue
                try:
                    stored = int(self.store.getattr(cid, oid, "hinfo"))
                except (NotFound, KeyError, ValueError):
                    stored = None
                if stored is not None and crc32c(chunk) != stored:
                    # rotted local chunk counts as missing: reconstruct
                    # from peers rather than decode garbage (hinfo read
                    # check, as in _handle_sub_read)
                    self.cct.dout(
                        "osd", 0,
                        f"{self.whoami} hinfo mismatch on local read "
                        f"{pg.pgid}/{oid} shard {shard}",
                    )
                    continue
                got[shard] = chunk
                if vers is not None:
                    vers[shard] = self._stored_ver(cid, oid)
                continue
            if osd < 0 or not self.osdmap.is_up(osd):
                continue
            tid = self._next_tid()
            tids[tid] = shard
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpRead(tid=tid, pgid=pg.pgid, oid=oid, shard=shard,
                                 offsets=None, epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                tids.pop(tid, None)
        for tid, shard in tids.items():
            rep = self._wait_reply(tid)
            if rep is not None and rep.retval == 0:
                got[shard] = unpack_data(rep.data)
                if sizes is not None and rep.size is not None:
                    sizes[shard] = int(rep.size)
                if vers is not None:
                    vers[shard] = getattr(rep, "ver", None)
        if stray:
            self._stray_upgrade(pg, oid, want, got, sizes, vers, acting,
                                floor)
        return got

    def _stray_upgrade(self, pg, oid: str, want: set[int], got: dict,
                       sizes, vers, acting,
                       floor: int | None = None) -> None:
        """Hunt NON-acting locations (reference: PeeringState's
        missing_loc — recovery reads from any OSD known to hold the
        object, not just the acting set) for two cases an acting
        permutation creates:
        - a shard with NO chunk at all (its new holder never held the
          role) — any copy helps;
        - a shard whose acting chunk is a STALE generation — only a
          copy stamped at (or above) the newest generation seen helps,
          and crucially the stale chunk must NOT suppress the hunt, or
          a current stray that could complete the stripe stays
          invisible and reads fail with too-few chunks.
        Iterates because finding a higher generation can reclassify
        previously-accepted chunks as stale."""
        for _round in range(3):
            present = [v for v in vers.values() if v is not None]
            if floor is not None:
                present.append(floor)
            target = max(present) if present else None
            todo = [
                sh for sh in sorted(want)
                if sh not in got
                or (target is not None and vers.get(sh) is not None
                    and vers[sh] < target)
            ]
            if not todo:
                return
            improved = False
            for shard in todo:
                min_ver = target if shard in got else None
                found = self._probe_stray(pg, oid, shard, acting, min_ver)
                if found is None:
                    continue
                data, ver, size = found
                got[shard] = data
                if vers is not None:
                    vers[shard] = ver
                if sizes is not None and size is not None:
                    sizes[shard] = size
                improved = True
            if not improved:
                return

    def _probe_stray(self, pg, oid: str, shard: int, acting,
                     min_ver: int | None):
        """One shard's chunk from any non-acting location.  min_ver set:
        only a copy with a NUMERIC generation >= min_ver qualifies (a
        wildcard stamp proves nothing about currency); min_ver None (the
        shard has no chunk at all): any copy, wildcard included."""
        holder = acting[shard] if shard < len(acting) else -1
        cid = self._cid(pg.pgid, shard)
        if holder != self.id:  # acting-local was already tried
            try:
                chunk = self.store.read(cid, oid)
            except (NotFound, KeyError):
                chunk = None
            if chunk is not None:
                try:
                    stored = int(self.store.getattr(cid, oid, "hinfo"))
                except (NotFound, KeyError, ValueError):
                    stored = None
                ver = self._stored_ver(cid, oid)
                if (
                    (stored is None or crc32c(chunk) == stored)
                    and (min_ver is None
                         or (ver is not None and ver >= min_ver))
                ):
                    size = None
                    try:
                        size = int(self.store.getattr(cid, oid, "size"))
                    except (NotFound, KeyError, ValueError):
                        pass
                    return bytes(chunk), ver, size
        # candidate order (reference: missing_loc built from
        # PastIntervals): past holders of THIS shard first — they are
        # the only OSDs that can plausibly hold it — then the bounded
        # global walk as a suffix, so an INCOMPLETE history (capped,
        # trimmed maps) can still find a holder the pre-history walk
        # would have (review r4); the probe cap below bounds the cost
        exclude = {self.id, holder}
        candidates = pg.past_intervals.holders_of_shard(shard, exclude)
        seen = set(candidates)
        candidates += [
            osd for osd in range(self.osdmap.max_osd)
            if osd not in exclude and osd not in seen
        ]
        probes = 0
        for osd in candidates:
            if not self.osdmap.is_up(osd):
                continue
            if probes >= 16:
                break  # bound the walk on big maps (client-path cost)
            probes += 1
            self.logger.inc("stray_probes")
            # metadata-only probe first (offsets=[]): a miss or a
            # non-qualifying generation costs a tiny round trip, not a
            # full-chunk transfer
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(MECSubOpRead(
                    tid=tid, pgid=pg.pgid, oid=oid, shard=shard,
                    offsets=[], epoch=self.my_epoch(),
                ))
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=3.0)
            if rep is None or rep.retval != 0:
                continue
            ver = getattr(rep, "ver", None)
            if min_ver is not None and (ver is None or ver < min_ver):
                continue
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(MECSubOpRead(
                    tid=tid, pgid=pg.pgid, oid=oid, shard=shard,
                    offsets=None, epoch=self.my_epoch(),
                ))
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=5.0)
            if rep is not None and rep.retval == 0:
                return (
                    unpack_data(rep.data),
                    getattr(rep, "ver", None),
                    int(rep.size) if rep.size is not None else None,
                )
        return None

    def _ec_read(self, pg, codec, acting, msg) -> MOSDOpReply:
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        my_shard = acting.index(self.id) if self.id in acting else -1
        # size from any shard we can reach (primary's own shard normally)
        size = None
        if my_shard >= 0:
            try:
                size = int(self.store.getattr(
                    self._cid(pg.pgid, my_shard), msg.oid, "size"))
            except (NotFound, KeyError):
                pass
        peer_sizes: dict[int, int] = {}
        vers: dict[int, int | None] = {}
        floor = pg.log.obj_newest.get(msg.oid)
        want_data = set(range(k))
        got = self._gather_chunks(
            pg, codec, acting, msg.oid, want_data, sizes=peer_sizes,
            vers=vers, floor=floor,
        )

        got = _current_generation(got, vers, floor)
        missing = want_data - set(got)
        if missing:
            # degraded: consult minimum_to_decode over everything
            # reachable, including stray (non-acting) chunk locations
            avail_probe = self._gather_chunks(
                pg, codec, acting, msg.oid, set(range(k, n)) | missing,
                sizes=peer_sizes, vers=vers, stray=True, floor=floor,
            )
            avail_probe.update(got)
            avail_probe = _current_generation(avail_probe, vers, floor)
            if len(avail_probe) < k:
                return MOSDOpReply(
                    tid=msg.tid, retval=-5, epoch=self.my_epoch(),
                    result=f"unreadable: only {len(avail_probe)} chunks",
                )
            chunks = {
                s: np.frombuffer(b, dtype=np.uint8)
                for s, b in avail_probe.items()
            }
            need = codec.minimum_to_decode(want_data, set(chunks))
            dec = codec.decode(
                want_data, {s: chunks[s] for s in need if s in chunks},
                len(next(iter(chunks.values()))),
            )
            data = b"".join(
                np.asarray(dec[i], np.uint8).tobytes() for i in range(k)
            )
        else:
            data = b"".join(got[i] for i in range(k))
        if size is None and peer_sizes:
            # prefer a size reported by a current-generation shard — a
            # stale shard's size xattr predates the newest RMW
            present = [v for v in vers.values() if v is not None]
            target = max(present) if present else None
            good = [
                sz for s, sz in peer_sizes.items()
                if target is None or vers.get(s) in (None, target)
            ]
            size = good[0] if good else next(iter(peer_sizes.values()))
        if size is None:
            # no shard could report a size xattr: the full (padded) stripe
            # is the best available answer
            size = len(data)
        obj = data[:size]
        if msg.off or (msg.length or 0) > 0:
            off = msg.off or 0
            ln = msg.length if msg.length else len(obj) - off
            obj = obj[off : off + ln]
        return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                           data=pack_data(obj),
                           result={"size": size})

    # .. replicated pool ...................................................
    def _replicated_op(self, pg, pool, acting, msg) -> MOSDOpReply:
        """Primary-copy replication (reference: ReplicatedBackend): full
        object bytes to every acting replica, same log machinery."""
        acting = [o for o in acting if o >= 0]
        my_shard = 0  # replicated: every replica stores the full object
        cid = self._cid(pg.pgid, 0)
        if msg.op in ("write_full", "write", "append", "delete"):
            # min_size gate, as on the EC path
            reachable = sum(
                1 for o in acting
                if o == self.id or self.osdmap.is_up(o)
            )
            if reachable < pool.min_size:
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result=f"{reachable} replicas reachable < "
                           f"min_size {pool.min_size}",
                )
        if msg.op in ("write", "append"):
            # ranged write / append: splice into the primary's copy (the
            # primary always holds the authoritative full object on a
            # replicated pool) and replicate the result full-object —
            # the reference ships op-level deltas; full-object keeps the
            # one replication path here while the EC pool carries the
            # real RMW machinery.  The read-splice-replicate sequence
            # runs under pg.lock (reentrant) so two concurrent appends
            # cannot both read the same old length and lose one update;
            # the rebuilt op KEEPS the reqid so the logged entry still
            # answers cross-primary resends.
            with pg.lock:
                new = unpack_data(msg.data) or b""
                try:
                    old = bytes(self.store.read(cid, msg.oid))
                except (NotFound, KeyError):
                    old = b""
                off = len(old) if msg.op == "append" else int(msg.off or 0)
                buf = bytearray(max(len(old), off + len(new)))
                buf[:len(old)] = old
                buf[off:off + len(new)] = new
                msg = MOSDOp(
                    tid=msg.tid, pool=msg.pool, oid=msg.oid,
                    op="write_full", data=pack_data(bytes(buf)),
                    epoch=msg.epoch, ps=msg.ps,
                    reqid=getattr(msg, "reqid", None),
                )
                return self._replicated_op(pg, pool, acting, msg)
        if msg.op == "write_full":
            data = unpack_data(msg.data) or b""
            # cache-tier pools: the clean-marker clear must ride THIS
            # mutation's transaction + sub-ops, not a separate staging
            # check (advisor r4 — the separate check races the flush's
            # clean-mark and an evict then drops the only copy)
            autoclean = self._tier_autoclean(pool, msg.oid)
            rmattrs = ["tier.clean"] if autoclean else None
            with pg.lock:
                version = pg.version + 1
                entry = LogEntry(version, "modify", msg.oid,
                                 reqid=getattr(msg, "reqid", None))
                tids = {}
                for osd in acting:
                    if osd == self.id or not self.osdmap.is_up(osd):
                        continue
                    tid = self._next_tid()
                    tids[tid] = osd
                    try:
                        self._conn_to_osd(osd).send_message(
                            MECSubOpWrite(
                                tid=tid, pgid=pg.pgid, oid=msg.oid, shard=0,
                                data=msg.data, crc=crc32c(data),
                                version=version,
                                entry=entry.to_list(),
                                epoch=self.my_epoch(), osize=len(data),
                                rmattrs=rmattrs,
                            )
                        )
                    except (OSError, ConnectionError):
                        tids.pop(tid, None)
                t = Transaction()
                t.try_create_collection(cid)
                t.write(cid, msg.oid, 0, data)
                t.truncate(cid, msg.oid, len(data))
                # self-digest so scrub can tell at-rest rot on the primary
                # from divergence (replicas get theirs via sub-write)
                t.setattr(cid, msg.oid, "hinfo", str(crc32c(data)).encode())
                t.setattr(cid, msg.oid, "size", str(len(data)).encode())
                t.setattr(cid, msg.oid, "ver", str(version).encode())
                if autoclean:
                    self._txn_clear_clean(t, cid, msg.oid)
                self._log_txn(t, cid, pg, entry)
                self.store.queue_transaction(t)
                a, deposed, _f = self._collect_subop_acks(tids)
                acked = 1 + a
                if deposed and acked < pool.min_size:
                    return MOSDOpReply(tid=msg.tid, retval=-116,
                                       epoch=self.my_epoch(),
                                       result={"deposed": True})
                if acked >= pool.min_size:
                    return MOSDOpReply(
                        tid=msg.tid, retval=0, epoch=self.my_epoch(),
                        result={"version": pg.version, "acked": acked},
                    )
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result={"applied": pg.version, "acked": acked,
                            "error": "below min_size commits"})
        if msg.op == "read":
            try:
                data = self.store.read(cid, msg.oid)
            except (NotFound, KeyError):
                return MOSDOpReply(tid=msg.tid, retval=-2,
                                   epoch=self.my_epoch(), result="not found")
            if msg.off or (msg.length or 0) > 0:
                off = msg.off or 0
                ln = msg.length if msg.length else len(data) - off
                data = data[off : off + ln]
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               data=pack_data(data), result={})
        if msg.op == "delete":
            with pg.lock:
                version = pg.version + 1
                entry = LogEntry(version, "delete", msg.oid,
                                 reqid=getattr(msg, "reqid", None))
                for osd in acting:
                    if osd == self.id or not self.osdmap.is_up(osd):
                        continue
                    tid = self._next_tid()
                    try:
                        self._conn_to_osd(osd).send_message(
                            MECSubOpWrite(
                                tid=tid, pgid=pg.pgid, oid=msg.oid, shard=0,
                                data=None, crc=None, version=version,
                                entry=entry.to_list(), epoch=self.my_epoch(),
                            )
                        )
                    except (OSError, ConnectionError):
                        pass
                t = Transaction()
                t.try_create_collection(cid)
                try:
                    self.store.stat(cid, msg.oid)
                    t.remove(cid, msg.oid)
                except (NotFound, KeyError):
                    pass
                self._log_txn(t, cid, pg, entry)
                self.store.queue_transaction(t)
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={})
        if msg.op == "stat":
            try:
                st = self.store.stat(cid, msg.oid)
                return MOSDOpReply(tid=msg.tid, retval=0,
                                   epoch=self.my_epoch(), result=st)
            except (NotFound, KeyError):
                return MOSDOpReply(tid=msg.tid, retval=-2,
                                   epoch=self.my_epoch(), result="not found")
        if msg.op == "list":
            oids = sorted(
                o for o in self.store.list_objects(cid)
                if not o.startswith("_") and CLONE_SEP not in o
            )
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"oids": oids})
        if msg.op in ("setxattr", "getxattrs"):
            return self._xattr_op(pg, acting, 0, msg)
        if msg.op.startswith("omap_"):
            return self._omap_op(pg, pool, acting, msg)
        if msg.op == "exec":
            return self._exec_op(pg, pool, acting, msg)
        if msg.op in ("watch", "unwatch", "notify"):
            return self._watch_op(pg, pool, msg)
        return MOSDOpReply(tid=msg.tid, retval=-22, epoch=self.my_epoch(),
                           result=f"bad op {msg.op}")

    # .. omap (replicated pools only, like the reference) ..................
    def _omap_op(self, pg, pool, acting, msg) -> MOSDOpReply:
        """librados omap surface (reference: rados_omap_get_vals /
        omap_set / omap_rm_keys / omap_clear, executed by
        PrimaryLogPG::do_osd_ops OMAP* cases).  Key-value pairs ride the
        object; mutations replicate and log exactly like xattr updates,
        and recovery pushes carry a full omap snapshot."""
        cid = self._cid(pg.pgid, 0)
        args = msg.data or {}
        if msg.op == "omap_get":
            try:
                self.store.stat(cid, msg.oid)
            except (NotFound, KeyError):
                return MOSDOpReply(tid=msg.tid, retval=-2,
                                   epoch=self.my_epoch(), result="not found")
            kv = self.store.omap_get(cid, msg.oid)
            want = args.get("keys")
            if want is not None:
                kv = {k: v for k, v in kv.items() if k in want}
            else:
                after = args.get("after") or ""
                maxn = int(args.get("max") or 0)
                keys = sorted(k for k in kv if k > after)
                if maxn:
                    keys = keys[:maxn]
                kv = {k: kv[k] for k in keys}
            return MOSDOpReply(
                tid=msg.tid, retval=0, epoch=self.my_epoch(),
                result={"kv": {k: pack_data(v) for k, v in kv.items()}},
            )
        # mutations
        omap_payload = None
        if msg.op == "omap_set":
            omap_payload = {"set": args.get("keys") or {}}
        elif msg.op == "omap_rm":
            omap_payload = {"rm": list(args.get("keys") or [])}
        elif msg.op == "omap_clear":
            omap_payload = {"clear": True}
        else:
            return MOSDOpReply(tid=msg.tid, retval=-22,
                               epoch=self.my_epoch(),
                               result=f"bad op {msg.op}")
        # omap content flushes to the base pool too: the clean clear must
        # be atomic with the mutation exactly like the data path
        autoclean = self._tier_autoclean(pool, msg.oid)
        with pg.lock:
            version = pg.version + 1
            entry = LogEntry(version, "modify", msg.oid,
                             reqid=getattr(msg, "reqid", None))
            tids: dict[int, int] = {}
            for shard, osd in enumerate(acting):
                if osd == self.id or osd < 0 or not self.osdmap.is_up(osd):
                    continue
                tid = self._next_tid()
                tids[tid] = shard
                try:
                    self._conn_to_osd(osd).send_message(MECSubOpWrite(
                        tid=tid, pgid=pg.pgid, oid=msg.oid, shard=0,
                        data=None, crc=None, version=version,
                        entry=entry.to_list(), epoch=self.my_epoch(),
                        omap=omap_payload,
                        rmattrs=["tier.clean"] if autoclean else None,
                    ))
                except (OSError, ConnectionError):
                    tids.pop(tid, None)
            t = Transaction()
            t.try_create_collection(cid)
            t.touch(cid, msg.oid)  # omap on a fresh oid creates it
            self._apply_omap(t, cid, msg.oid, omap_payload)
            # stamp the object version: _check_dup's applied-resend
            # verification counts shards holding ver >= v (replicated
            # pools never generation-filter reads, so this is safe)
            t.setattr(cid, msg.oid, "ver", str(version).encode())
            if autoclean:
                self._txn_clear_clean(t, cid, msg.oid)
            self._log_txn(t, cid, pg, entry)
            self.store.queue_transaction(t)
            a, deposed, _f = self._collect_subop_acks(tids)
            acked = 1 + a
        if deposed and acked < pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=-116,
                               epoch=self.my_epoch(),
                               result={"deposed": True})
        if acked < pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=-11,
                               epoch=self.my_epoch(),
                               result={"applied": pg.version, "acked": acked,
                                       "error": "below min_size commits"})
        return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                           result={"version": pg.version})

    # .. object classes (replicated pools only, like omap) .................
    def _exec_op(self, pg, pool, acting, msg) -> MOSDOpReply:
        """`rados exec` — run a registered class method at the primary
        under the PG lock and commit its staged mutations as one
        replicated, logged transaction (reference: PrimaryLogPG
        CEPH_OSD_OP_CALL -> ClassHandler; src/cls).  The lock-scoped
        execute-then-commit is what makes cls ops (bucket-index updates,
        create guards, counters) immune to concurrent-writer races."""
        from .classes import ClassRegistry, ClsHandle

        cid = self._cid(pg.pgid, 0)
        args = msg.data or {}
        fn = ClassRegistry.instance().get(
            args.get("cls", ""), args.get("method", "")
        )
        if fn is None:
            return MOSDOpReply(
                tid=msg.tid, retval=-95, epoch=self.my_epoch(),
                result=f"no class method "
                       f"{args.get('cls')}.{args.get('method')}",
            )
        # pool-snapshot clone-on-write, same as the plain mutation path
        # (lines above in _execute_routed_op): a method MAY stage a data
        # write (hctx.write_full), and the clone must capture the head
        # BEFORE pg.lock — the write path's order is _clone_mutex then
        # pg.lock, and inverting it here would risk deadlock.  We cannot
        # yet know whether the method will touch data, so clone whenever
        # a snap is live: a clone of an omap-only exec is merely the
        # head's (correct) at-snap state, never wrong.
        live_max = max(pool.snaps, default=0)
        snap_seq = max(live_max, int(getattr(msg, "snap_seq", 0) or 0))
        head_existed = True
        if snap_seq and msg.oid and CLONE_SEP not in msg.oid:
            try:
                head_existed = self._maybe_clone(pg, pool, msg.oid, snap_seq)
            except Exception as e:
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result=f"snap clone failed: {e}",
                )
        with pg.lock:
            def read_data():
                try:
                    return self.store.read(cid, msg.oid)
                except (NotFound, KeyError):
                    return None

            def read_omap():
                try:
                    return self.store.omap_get(cid, msg.oid)
                except (NotFound, KeyError):
                    return {}

            hctx = ClsHandle(msg.oid, read_data, read_omap)
            try:
                retval, out = fn(hctx, args.get("in") or {})
            except Exception as e:
                self.cct.dout("osd", 0,
                              f"{self.whoami} cls method raised: {e!r}")
                return MOSDOpReply(tid=msg.tid, retval=-22,
                                   epoch=self.my_epoch(),
                                   result=f"cls method failed: {e}")
            if retval < 0 or not hctx.dirty:
                # aborted or read-only: nothing to commit or replicate
                return MOSDOpReply(tid=msg.tid, retval=retval,
                                   epoch=self.my_epoch(),
                                   result={"cls_out": out})
            omap_payload = None
            if hctx.staged_set or hctx.staged_rm:
                omap_payload = {
                    "set": {k: pack_data(v)
                            for k, v in hctx.staged_set.items()},
                    "rm": sorted(hctx.staged_rm),
                }
            wire_data = crc = osize = None
            if hctx.staged_data is not None:
                wire_data = pack_data(hctx.staged_data)
                crc = crc32c(hctx.staged_data)
                osize = len(hctx.staged_data)
            version = pg.version + 1
            entry = LogEntry(version, "modify", msg.oid,
                             reqid=getattr(msg, "reqid", None))
            autoclean = self._tier_autoclean(pool, msg.oid)
            tids: dict[int, int] = {}
            for shard, osd in enumerate(acting):
                if osd == self.id or osd < 0 or not self.osdmap.is_up(osd):
                    continue
                tid = self._next_tid()
                tids[tid] = shard
                try:
                    self._conn_to_osd(osd).send_message(MECSubOpWrite(
                        tid=tid, pgid=pg.pgid, oid=msg.oid, shard=0,
                        data=wire_data, crc=crc, osize=osize,
                        version=version, entry=entry.to_list(),
                        epoch=self.my_epoch(), omap=omap_payload,
                        rmattrs=["tier.clean"] if autoclean else None,
                    ))
                except (OSError, ConnectionError):
                    tids.pop(tid, None)
            t = Transaction()
            t.try_create_collection(cid)
            t.touch(cid, msg.oid)
            if hctx.staged_data is not None:
                t.write(cid, msg.oid, 0, hctx.staged_data)
                t.truncate(cid, msg.oid, len(hctx.staged_data))
                t.setattr(cid, msg.oid, "hinfo",
                          str(crc32c(hctx.staged_data)).encode())
                t.setattr(cid, msg.oid, "size",
                          str(len(hctx.staged_data)).encode())
            if omap_payload is not None:
                self._apply_omap(t, cid, msg.oid, omap_payload)
            t.setattr(cid, msg.oid, "ver", str(version).encode())
            if autoclean:
                self._txn_clear_clean(t, cid, msg.oid)
            self._log_txn(t, cid, pg, entry)
            self.store.queue_transaction(t)
            a, deposed, _f = self._collect_subop_acks(tids)
            acked = 1 + a
        if deposed and acked < pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=-116,
                               epoch=self.my_epoch(),
                               result={"deposed": True})
        if acked < pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=-11,
                               epoch=self.my_epoch(),
                               result={"applied": pg.version, "acked": acked,
                                       "error": "below min_size commits"})
        if snap_seq and not head_existed:
            # exec CREATED the object post-snap: mark it born so older
            # snap views keep it invisible (same contract as the plain
            # write path's _mark_born)
            try:
                self._mark_born(pg, pool, msg.oid, snap_seq)
            except Exception as e:
                return MOSDOpReply(
                    tid=msg.tid, retval=-5, epoch=self.my_epoch(),
                    result=f"snapborn mark failed: {e}",
                )
        return MOSDOpReply(tid=msg.tid, retval=retval,
                           epoch=self.my_epoch(), result={"cls_out": out})

    def _apply_omap(self, t: Transaction, cid: str, oid: str,
                    payload: dict) -> None:
        if payload.get("snapshot") is not None:
            # recovery push: the dict IS the whole omap
            t.omap_clear(cid, oid)
            t.omap_setkeys(cid, oid, {
                k: unpack_data(v) for k, v in payload["snapshot"].items()
            })
            return
        if payload.get("clear"):
            t.omap_clear(cid, oid)
        if payload.get("set"):
            t.omap_setkeys(cid, oid, {
                k: unpack_data(v) for k, v in payload["set"].items()
            })
        if payload.get("rm"):
            t.omap_rmkeys(cid, oid, payload["rm"])

    # .. watch / notify ....................................................
    def _watch_op(self, pg, pool, msg) -> MOSDOpReply:
        """Object watch/notify (reference: PrimaryLogPG watch/notify +
        MWatchNotify).  Watch state is primary-local and in-memory; the
        client's Objecter re-registers lingering watches after a map
        change (reference: linger ops re-sent by Objecter), which covers
        primary failover."""
        args = msg.data or {}
        key = (msg.pool, msg.oid)
        if msg.op == "watch":
            cookie = int(args.get("cookie") or 0)
            with self._watch_lock:
                self.watchers.setdefault(key, {})[cookie] = (
                    getattr(msg, "src", None))
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"cookie": cookie})
        if msg.op == "unwatch":
            cookie = int(args.get("cookie") or 0)
            with self._watch_lock:
                ws = self.watchers.get(key, {})
                ws.pop(cookie, None)
                if not ws:
                    self.watchers.pop(key, None)
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={})
        # notify: fan out to every watcher, collect acks with a timeout
        notify_id = self._next_tid()
        payload = args.get("payload")
        timeout = float(args.get("timeout") or 5.0)
        with self._watch_lock:
            targets = dict(self.watchers.get(key, {}))
        pending = {}
        dead = []
        unreachable = []
        for cookie, src in targets.items():
            conn = self._client_conns.get(src)
            if conn is None:
                # conn LRU-evicted or never seen: the watcher may be
                # alive and idle — report it missed, do NOT reap (only a
                # CONFIRMED-dead connection expires a watch)
                unreachable.append(cookie)
                continue
            try:
                conn.send_message(MWatchNotify(
                    notify_id=notify_id, pool=msg.pool, oid=msg.oid,
                    cookie=cookie, data=payload,
                ))
                pending[cookie] = src
            except (OSError, ConnectionError):
                dead.append(cookie)
        if dead:
            # a watcher whose connection is gone is expired (reference:
            # watch timeout reaps dead watchers); its client re-lingers
            # on the next map push if it is actually alive
            with self._watch_lock:
                ws = self.watchers.get(key, {})
                for cookie in dead:
                    ws.pop(cookie, None)
                if not ws:
                    self.watchers.pop(key, None)
        acked, missed = [], list(unreachable)
        deadline = time.monotonic() + timeout
        for cookie in pending:
            remain = max(0.0, deadline - time.monotonic())
            if self._wait_notify_ack(notify_id, cookie, remain):
                acked.append(cookie)
            else:
                missed.append(cookie)
        return MOSDOpReply(
            tid=msg.tid, retval=0, epoch=self.my_epoch(),
            result={"notify_id": notify_id, "acked": acked,
                    "missed": missed},
        )

    def _wait_notify_ack(self, notify_id: int, cookie: int,
                         timeout: float) -> bool:
        with self._watch_cond:
            return self._watch_cond.wait_for(
                lambda: (notify_id, cookie) in self._notify_acks,
                timeout=timeout,
            )

    # -- cache tiering (reference: PrimaryLogPG::maybe_handle_cache_detail
    # — promote_object / do_proxy_read / whiteouts — plus the TierAgent
    # flush/evict loop in PrimaryLogPG::agent_work) -----------------------
    #
    # State model (crash-safe by construction): a cache object with the
    # `tier.clean` user xattr is known flushed/promoted-identical to the
    # base copy and may be evicted; an object WITHOUT it is treated as
    # dirty and will be flushed.  Mutations remove the marker BEFORE the
    # data op and flush/promote set it AFTER the content settles, so a
    # crash at any point can only mislabel a clean object as dirty (a
    # harmless re-flush), never a dirty one as clean (which could evict
    # an unflushed write).  The reference carries these as object_info_t
    # FLAG_DIRTY/FLAG_WHITEOUT inside the op transaction; the xattr
    # spelling reuses this repo's replicated-xattr machinery instead.
    # `tier.whiteout` marks a deleted-in-cache stub whose flush deletes
    # the base object.  tier.* xattrs are internal metadata: visible in
    # getxattrs (documented), never copied to the base pool.

    def _tier_client_op(self, pool_id: int, oid: str, op: str,
                        data=None, off: int = 0, length: int = 0):
        """OSD-as-client op against another pool (promote reads, flush
        writes) — targets the named pool directly, the internal analog
        of CEPH_OSD_FLAG_IGNORE_OVERLAY.  Returns the reply or raises
        OSError on timeout/conn failure."""
        m = self.osdmap
        pool = m.pools.get(pool_id) if m else None
        if pool is None:
            raise OSError(f"tier op: no pool {pool_id}")
        ps = object_ps(oid, pool.pg_num)
        _a, primary = self._acting(pool_id, ps)
        if primary < 0:
            raise OSError(f"tier op: pg {pool_id}.{ps} has no primary")
        tid = self._next_tid()
        rep = self._forward_op(primary, MOSDOp(
            tid=tid, pool=pool_id, oid=oid, op=op, data=data,
            epoch=self.my_epoch(), off=off, length=length,
            reqid=f"tier.{self.id}.{tid}" if op in MUTATING_OPS else None,
        ))
        if rep is None:
            raise OSError(f"tier op {op} {oid!r}: no reply")
        return rep

    def _tier_autoclean(self, pool, oid: str) -> bool:
        """True when a mutation of `oid` must clear the tier.clean marker
        ATOMICALLY with its data op (advisor r4: a clean-flag check in the
        staging path races the flush's clean-mark — only a clear inside
        the mutation's own pg.lock transaction closes the window where
        dirty data gets labeled clean and evicted)."""
        if pool is None or pool.tier_of < 0 or pool.cache_mode == "none":
            return False
        return bool(oid) and CLONE_SEP not in oid and \
            not oid.startswith(("_", ":pg:"))

    def _txn_clear_clean(self, t: Transaction, cid: str, oid: str) -> None:
        """Append the primary-local tier.clean removal to a mutation's
        transaction (the replicas get theirs via the sub-op `rmattrs`)."""
        try:
            if "u_tier.clean" in self.store.getattrs(cid, oid):
                t.rmattr(cid, oid, "u_tier.clean")
        except (NotFound, KeyError):
            pass

    def _tier_flag(self, pg, oid: str, flag: str) -> bool:
        cid = self._cid(pg.pgid, 0)
        try:
            return self.store.getattr(cid, oid, f"u_tier.{flag}") == b"1"
        except (NotFound, KeyError):
            return False

    def _tier_mark(self, pg, acting, oid: str, flag: str,
                   value: bool) -> MOSDOpReply:
        """Set/clear a tier.* marker through the replicated xattr path so
        it survives primary failover."""
        return self._xattr_op(pg, acting, 0, MOSDOp(
            tid=self._next_tid(), pool=pg.pool_id, oid=oid, op="setxattr",
            data={f"tier.{flag}": pack_data(b"1") if value else None},
            epoch=self.my_epoch(),
        ))

    def _cache_tier_op(self, pg, pool, acting, ps, msg, _depth: int = 0):
        """Cache-pool front-end.  Returns a final MOSDOpReply, or None to
        fall through to normal execution (object staged in the cache).

        A promote that aborts because the object appeared concurrently
        (rc == 1, see _tier_promote's race contract) restarts the whole
        decision: the staged object changes every branch below."""
        base_id = pool.tier_of
        m = self.osdmap
        base_pool = m.pools.get(base_id) if m else None
        oid = msg.oid
        if (
            base_pool is None or not oid or CLONE_SEP in oid
            or oid.startswith(":pg:")
            or msg.op in ("list", "watch", "unwatch", "notify")
            or getattr(msg, "ps", None) is not None  # internal machinery
        ):
            return None

        def retry():
            if _depth >= 3:
                return MOSDOpReply(tid=msg.tid, retval=-11,
                                   epoch=self.my_epoch(),
                                   result="tier staging kept racing")
            return self._cache_tier_op(pg, pool, acting, ps, msg,
                                       _depth + 1)

        cid = self._cid(pg.pgid, 0)
        with pg.lock:
            present = self.store.exists(cid, oid)
            whiteout = present and self._tier_flag(pg, oid, "whiteout")

        if msg.op == "cache_flush":
            return self._tier_flush_object(pg, pool, acting, oid, msg.tid)
        if msg.op == "cache_evict":
            return self._tier_evict_object(pg, pool, acting, oid, msg.tid)

        mutating = msg.op in MUTATING_OPS
        if not mutating:
            # reads / stat / getxattrs / omap_get
            if whiteout:
                return MOSDOpReply(tid=msg.tid, retval=-2,
                                   epoch=self.my_epoch(),
                                   result="not found (whiteout)")
            if present:
                return None
            if pool.cache_mode == "readproxy":
                # proxy without promoting (reference: do_proxy_read)
                try:
                    rep = self._tier_client_op(
                        base_id, oid, msg.op, data=msg.data,
                        off=msg.off or 0, length=msg.length or 0,
                    )
                except OSError as e:
                    return MOSDOpReply(tid=msg.tid, retval=-11,
                                       epoch=self.my_epoch(),
                                       result=f"proxy read: {e}")
                return MOSDOpReply(tid=msg.tid, retval=rep.retval,
                                   epoch=self.my_epoch(), data=rep.data,
                                   result=rep.result)
            rc = self._tier_promote(pg, pool, acting, base_id, oid,
                                    mark_clean=True)
            if rc == 1:
                return retry()  # raced a write: re-evaluate the staging
            if rc == -2:
                return MOSDOpReply(tid=msg.tid, retval=-2,
                                   epoch=self.my_epoch(),
                                   result="not found")
            if rc != 0:
                return MOSDOpReply(tid=msg.tid, retval=-11,
                                   epoch=self.my_epoch(),
                                   result=f"promote failed ({rc})")
            return None  # promoted: serve locally

        # mutations (writeback; readproxy promotes writes too)
        if msg.op == "delete":
            if not present or whiteout:
                # nothing cached (or already whited out): existence is
                # decided by the base copy
                if whiteout:
                    return MOSDOpReply(tid=msg.tid, retval=-2,
                                       epoch=self.my_epoch(),
                                       result="not found (whiteout)")
                try:
                    st = self._tier_client_op(base_id, oid, "stat")
                except OSError as e:
                    return MOSDOpReply(tid=msg.tid, retval=-11,
                                       epoch=self.my_epoch(),
                                       result=f"tier stat: {e}")
                if st.retval != 0:
                    return MOSDOpReply(tid=msg.tid, retval=-2,
                                       epoch=self.my_epoch(),
                                       result="not found")
            # install the whiteout stub: empty object + markers; the
            # agent propagates the delete to the base and retires it
            wrep = self._replicated_op(pg, pool, acting, MOSDOp(
                tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                op="write_full", data=pack_data(b""),
                epoch=self.my_epoch(), reqid=getattr(msg, "reqid", None),
            ))
            if wrep.retval != 0:
                return MOSDOpReply(tid=msg.tid, retval=wrep.retval,
                                   epoch=self.my_epoch(), result=wrep.result)
            # the stub must shed the pre-delete user state THROUGH THE
            # REPLICATED paths (advisor r4, medium): a primary-local wipe
            # leaves replicas carrying stale xattrs/omap that resurrect
            # after failover, and a delete-then-recreate must never
            # resurrect pre-delete attrs into a later flush
            try:
                stale = {
                    n[2:]: None
                    for n in self.store.getattrs(cid, oid)
                    if n.startswith("u_") and not n[2:].startswith("tier.")
                }
            except (NotFound, KeyError):
                stale = {}
            if stale:
                xrep = self._xattr_op(pg, acting, 0, MOSDOp(
                    tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                    op="setxattr", data=stale, epoch=self.my_epoch(),
                ))
                if xrep.retval != 0:
                    return MOSDOpReply(tid=msg.tid, retval=xrep.retval,
                                       epoch=self.my_epoch(),
                                       result=xrep.result)
            orep = self._omap_op(pg, pool, acting, MOSDOp(
                tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                op="omap_clear", data={}, epoch=self.my_epoch(),
            ))
            if orep.retval != 0:
                return MOSDOpReply(tid=msg.tid, retval=orep.retval,
                                   epoch=self.my_epoch(), result=orep.result)
            mrep = self._tier_mark(pg, acting, oid, "whiteout", True)
            if mrep.retval != 0:
                return MOSDOpReply(tid=msg.tid, retval=mrep.retval,
                                   epoch=self.my_epoch(), result=mrep.result)
            self._tier_mark(pg, acting, oid, "clean", False)
            return MOSDOpReply(tid=msg.tid, retval=0,
                               epoch=self.my_epoch(), result={})

        if whiteout:
            # write onto a deleted object: never resurrect base bytes —
            # clear the markers and start from the empty stub.  The clear
            # must be DURABLE before the data op: a stale whiteout
            # surviving primary failover would later flush as a delete,
            # destroying the acknowledged write
            mrep = self._tier_mark(pg, acting, oid, "whiteout", False)
            if mrep.retval != 0:
                return MOSDOpReply(tid=msg.tid, retval=-11,
                                   epoch=self.my_epoch(),
                                   result="whiteout clear not durable")
            return None
        if present:
            # the clean-marker clear now rides the mutation's OWN
            # transaction (_tier_autoclean in the write_full / omap /
            # xattr / exec paths), atomically under the same pg.lock —
            # a separate staging clear here raced the flush's clean-mark
            # (advisor r4, medium: flush could label the object clean
            # AFTER this check but BEFORE the data op landed)
            return None
        # absent: partial mutations need the base content staged first;
        # full overwrites don't (reference: proxy/promote decision).  A
        # base miss (rc == -2) just falls through: the normal path gives
        # xattr ops their -2 and creates fresh objects for write/omap,
        # matching un-tiered pool semantics.
        if msg.op not in ("write_full",):
            rc = self._tier_promote(pg, pool, acting, base_id, oid,
                                    mark_clean=False)
            if rc == 1:
                return retry()  # raced a write: re-evaluate the staging
            if rc not in (0, -2):
                return MOSDOpReply(tid=msg.tid, retval=-11,
                                   epoch=self.my_epoch(),
                                   result=f"promote failed ({rc})")
        return None

    def _tier_promote(self, pg, pool, acting, base_id: int, oid: str,
                      mark_clean: bool) -> int:
        """Copy oid (data + user xattrs + omap) from the base pool into
        this cache PG (reference: PrimaryLogPG::promote_object).  Returns
        0, -2 (no base object), 1 (ABORTED: the object appeared locally
        while we read the base copy — the caller re-evaluates its staging
        decision), or a negative errno.

        Race contract (advisor r4, high): the base-pool reads run
        lock-free, but the local existence re-check and the staging
        writes run under pg.lock — a client write that staged fresh data
        concurrently either lands before our locked section (we see it
        and abort: promoting would overwrite acknowledged new data with
        stale base content) or serializes after it (its own transaction
        clears the clean marker we may set)."""
        try:
            rep = self._tier_client_op(base_id, oid, "read")
            if rep.retval == -2:
                return -2
            if rep.retval != 0:
                return rep.retval or -5
            xrep = self._tier_client_op(base_id, oid, "getxattrs")
            xattrs = dict(xrep.result or {}) if xrep.retval == 0 else {}
            orep = self._tier_client_op(base_id, oid, "omap_get")
            kv = dict((orep.result or {}).get("kv") or {}) \
                if orep.retval == 0 else {}
        except OSError:
            return -11
        cid = self._cid(pg.pgid, 0)
        with pg.lock:
            if self.store.exists(cid, oid):
                return 1  # raced a write: fresh data already staged
            wrep = self._replicated_op(pg, pool, acting, MOSDOp(
                tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                op="write_full", data=rep.data, epoch=self.my_epoch(),
            ))
            if wrep.retval != 0:
                return wrep.retval or -5
            if xattrs:
                self._xattr_op(pg, acting, 0, MOSDOp(
                    tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                    op="setxattr", data=xattrs, epoch=self.my_epoch(),
                ))
            if kv:
                self._omap_op(pg, pool, acting, MOSDOp(
                    tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                    op="omap_set", data={"keys": kv}, epoch=self.my_epoch(),
                ))
            if mark_clean:
                self._tier_mark(pg, acting, oid, "clean", True)
        self.logger.inc("tier_promote")
        return 0

    def _tier_flush_object(self, pg, pool, acting, oid: str,
                           tid: int) -> MOSDOpReply:
        """Flush one cache object to the base pool (reference:
        PrimaryLogPG::start_flush).  Whiteouts propagate the delete and
        retire the stub; dirty objects copy content and gain the clean
        marker — guarded by a version recheck so a write racing the
        flush re-dirties instead of being mislabeled clean."""
        base_id = pool.tier_of
        cid = self._cid(pg.pgid, 0)
        if not self.store.exists(cid, oid):
            return MOSDOpReply(tid=tid, retval=-2, epoch=self.my_epoch(),
                               result="not found")
        if self._tier_flag(pg, oid, "whiteout"):
            try:
                drep = self._tier_client_op(base_id, oid, "delete")
            except OSError as e:
                return MOSDOpReply(tid=tid, retval=-11,
                                   epoch=self.my_epoch(),
                                   result=f"flush delete: {e}")
            if drep.retval not in (0, -2):
                return MOSDOpReply(tid=tid, retval=drep.retval,
                                   epoch=self.my_epoch(), result=drep.result)
            # retire the stub under pg.lock, re-checking the marker: a
            # client write racing this flush clears the whiteout and
            # stages fresh data in the stub — deleting it then would lose
            # an acknowledged write (the re-dirtied object simply flushes
            # again on the next pass, recreating the base copy)
            with pg.lock:
                if not self._tier_flag(pg, oid, "whiteout"):
                    return MOSDOpReply(
                        tid=tid, retval=0, epoch=self.my_epoch(),
                        result={"flushed": "raced a rewrite; kept"})
                rrep = self._replicated_op(pg, pool, acting, MOSDOp(
                    tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                    op="delete", epoch=self.my_epoch(),
                ))
            return MOSDOpReply(tid=tid, retval=rrep.retval,
                               epoch=self.my_epoch(),
                               result={"flushed": "whiteout"})
        if self._tier_flag(pg, oid, "clean"):
            return MOSDOpReply(tid=tid, retval=0, epoch=self.my_epoch(),
                               result={"flushed": "already clean"})
        try:
            ver_before = self.store.getattr(cid, oid, "ver")
        except (NotFound, KeyError):
            ver_before = None
        data = bytes(self.store.read(cid, oid))
        xattrs = {
            n[2:]: pack_data(v)
            for n, v in self.store.getattrs(cid, oid).items()
            if n.startswith("u_") and not n[2:].startswith("tier.")
        }
        kv = self.store.omap_get(cid, oid)
        try:
            wrep = self._tier_client_op(base_id, oid, "write_full",
                                        data=pack_data(data))
            if wrep.retval != 0:
                return MOSDOpReply(tid=tid, retval=wrep.retval,
                                   epoch=self.my_epoch(), result=wrep.result)
            if xattrs:
                self._tier_client_op(base_id, oid, "setxattr", data=xattrs)
            if kv:
                self._tier_client_op(
                    base_id, oid, "omap_set",
                    data={"keys": {k: pack_data(v) for k, v in kv.items()}},
                )
        except OSError as e:
            return MOSDOpReply(tid=tid, retval=-11, epoch=self.my_epoch(),
                               result=f"flush write: {e}")
        with pg.lock:
            try:
                ver_now = self.store.getattr(cid, oid, "ver")
            except (NotFound, KeyError):
                ver_now = None
            if ver_now == ver_before:
                self._tier_mark(pg, acting, oid, "clean", True)
        self.logger.inc("tier_flush")
        return MOSDOpReply(tid=tid, retval=0, epoch=self.my_epoch(),
                           result={"flushed": len(data)})

    def _tier_evict_object(self, pg, pool, acting, oid: str,
                           tid: int) -> MOSDOpReply:
        """Drop a CLEAN cache copy (reference: PrimaryLogPG::_delete_oid
        under agent_maybe_evict); -EBUSY for dirty/whiteout objects."""
        cid = self._cid(pg.pgid, 0)
        with pg.lock:
            if not self.store.exists(cid, oid):
                return MOSDOpReply(tid=tid, retval=-2,
                                   epoch=self.my_epoch(),
                                   result="not found")
            if (
                not self._tier_flag(pg, oid, "clean")
                or self._tier_flag(pg, oid, "whiteout")
            ):
                return MOSDOpReply(tid=tid, retval=-16,
                                   epoch=self.my_epoch(),
                                   result="dirty: flush first")
            rrep = self._replicated_op(pg, pool, acting, MOSDOp(
                tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                op="delete", epoch=self.my_epoch(),
            ))
        if rrep.retval != 0:
            return MOSDOpReply(tid=tid, retval=rrep.retval,
                               epoch=self.my_epoch(), result=rrep.result)
        self.logger.inc("tier_evict")
        return MOSDOpReply(tid=tid, retval=0,
                           epoch=self.my_epoch(), result={"evicted": oid})

    def _tier_agent_pass(self) -> None:
        """Background flush/evict over primary cache-pool PGs (reference:
        the TierAgent woken by agent_choose_mode).  Flushes every dirty
        object and whiteout; evicts clean objects while the pool is over
        target_max_objects (eviction order is name-sorted — the
        reference ranks by hit_set temperature, out of scope here)."""
        m = self.osdmap
        if m is None:
            return
        for pool in list(m.pools.values()):
            # readproxy pools flush too: their writes stage dirty in the
            # cache exactly like writeback (only reads are proxied)
            if pool.tier_of < 0 or pool.cache_mode == "none":
                continue
            for ps in range(pool.pg_num):
                acting, primary = self._acting(pool.pool_id, ps)
                if primary != self.id:
                    continue
                pg = self._pg(pool.pool_id, ps)
                if pg.activated_interval != pg.interval_start:
                    continue
                cid = self._cid(pg.pgid, 0)
                try:
                    oids = [
                        o for o in self.store.list_objects(cid)
                        if not o.startswith("_") and CLONE_SEP not in o
                    ]
                except (NotFound, KeyError):
                    continue
                live = []
                for oid in sorted(oids):
                    if self._tier_flag(pg, oid, "whiteout") or \
                            not self._tier_flag(pg, oid, "clean"):
                        try:
                            self._tier_flush_object(
                                pg, pool, acting, oid, self._next_tid()
                            )
                        except Exception as e:
                            self.cct.dout(
                                "osd", 5,
                                f"{self.whoami} tier flush {oid}: {e!r}")
                    if self.store.exists(cid, oid):
                        live.append(oid)
                target = pool.target_max_objects
                if target and len(live) > max(0, target // pool.pg_num):
                    for oid in live[max(0, target // pool.pg_num):]:
                        try:
                            self._tier_evict_object(
                                pg, pool, acting, oid, self._next_tid()
                            )
                        except Exception:
                            pass

    # -- shard sub-ops -----------------------------------------------------
    def _handle_sub_write(self, conn, msg: MECSubOpWrite) -> None:
        pool_id, ps = msg.pgid.split(".")
        pg = self._pg(int(pool_id), int(ps))
        cid = self._cid(msg.pgid, msg.shard)
        retval = 0
        try:
            if (
                msg.epoch is not None
                and pg.interval_start
                and msg.epoch < pg.interval_start
            ):
                # sub-op from a PAST-interval primary (stale map racing
                # the change that re-elected this PG): refuse with the
                # DISTINCT -ESTALE code so the deposed sender knows to
                # step down rather than treat it as a flaky peer
                # (reference: ops tagged with an older
                # same_interval_since are dropped)
                try:
                    conn.send_message(
                        MECSubOpWriteReply(tid=msg.tid, pgid=msg.pgid,
                                           shard=msg.shard, retval=-116)
                    )
                except (OSError, ConnectionError):
                    pass
                return
            with pg.lock:
                entry_op = msg.entry[1] if msg.entry else None
                t = Transaction()
                t.try_create_collection(cid)
                if (
                    msg.data is not None
                    and getattr(msg, "mode", None) in ("range", "delta")
                ):
                    # partial-stripe RMW sub-op: splice (data shard) or
                    # GF-XOR (parity shard) into the stored chunk.  The
                    # per-object version guard (`over` -> `ver`) is what
                    # makes this safe: an RMW onto a STALE generation
                    # would fuse old and new stripes, and a REPLAYED RMW
                    # (dup/resend) would double-apply the delta.
                    stored_ver = self._stored_ver(cid, msg.oid)
                    if stored_ver == msg.version:
                        # already applied (idempotent replay): ack as-is
                        pass
                    elif (
                        getattr(msg, "over", None) is None
                        or stored_ver != msg.over
                        or msg.version != pg.version + 1
                    ):
                        raise IOError(
                            f"rmw v{msg.over}->v{msg.version} onto shard "
                            f"at obj v{stored_ver} pg v{pg.version}"
                        )
                    else:
                        seg = unpack_data(msg.data)
                        if crc32c(seg) != msg.crc:
                            raise IOError("rmw sub-op crc mismatch")
                        off = int(msg.off or 0)
                        try:
                            full = bytearray(self.store.read(cid, msg.oid))
                        except (NotFound, KeyError):
                            raise IOError("rmw target chunk missing on shard")
                        if off + len(seg) > len(full):
                            raise IOError("rmw beyond stored chunk")
                        # rot check BEFORE applying: stamping a fresh
                        # hinfo over a corrupt base would launder the rot
                        # past every later integrity check
                        try:
                            stored_h = int(
                                self.store.getattr(cid, msg.oid, "hinfo"))
                        except (NotFound, KeyError, ValueError):
                            stored_h = None
                        if (stored_h is not None
                                and crc32c(bytes(full)) != stored_h):
                            raise IOError("rmw base chunk failed hinfo")
                        if msg.mode == "delta":
                            seg = (
                                np.frombuffer(
                                    bytes(full[off:off + len(seg)]), np.uint8
                                )
                                ^ np.frombuffer(seg, np.uint8)
                            ).tobytes()
                        full[off:off + len(seg)] = seg
                        t.write(cid, msg.oid, off, seg)
                        t.setattr(cid, msg.oid, "hinfo",
                                  str(crc32c(bytes(full))).encode())
                        t.setattr(cid, msg.oid, "ver",
                                  str(msg.version).encode())
                        if msg.osize is not None:
                            t.setattr(cid, msg.oid, "size",
                                      str(msg.osize).encode())
                elif msg.data is not None:
                    chunk = unpack_data(msg.data)
                    if crc32c(chunk) != msg.crc:
                        raise IOError("chunk crc mismatch")
                    # generation-regression guard: a full-chunk push
                    # rebuilt from STALE sources (a donor that hasn't
                    # caught up across an acting permutation) must never
                    # overwrite a NEWER generation we hold — that is how
                    # an applied write gets rolled back cluster-wide.
                    # Equal/newer stamps apply (idempotent refresh /
                    # catch-up); wildcard pushes only land on chunks
                    # that carry no numeric stamp themselves.
                    stored_gen = self._stored_ver(cid, msg.oid)
                    push_gen = getattr(msg, "over", None)
                    if push_gen is None:
                        push_gen = msg.version
                    if stored_gen is not None and (
                        push_gen is None or push_gen < stored_gen
                    ):
                        raise IOError(
                            f"refusing generation regression "
                            f"v{push_gen} onto v{stored_gen}"
                        )
                    t.write(cid, msg.oid, 0, chunk)
                    t.truncate(cid, msg.oid, len(chunk))
                    t.setattr(cid, msg.oid, "hinfo", str(msg.crc).encode())
                    # full-chunk pushes stamp the chunk GENERATION: a
                    # recovery push carries the primary's stored stamp
                    # (`over`) since its bytes are rebuilt-current; a
                    # live write stamps its own version; a push that
                    # knows neither (backfill of a legacy object) stamps
                    # the wildcard so readers accept the bytes
                    gen = getattr(msg, "over", None)
                    if gen is None:
                        gen = msg.version
                    t.setattr(cid, msg.oid, "ver",
                              str(gen).encode() if gen else b"")
                    if msg.osize is not None:
                        t.setattr(cid, msg.oid, "size",
                                  str(msg.osize).encode())
                elif (
                    entry_op == "modify"
                    and msg.osize is not None
                    and msg.xattrs is None
                ):
                    # entry-only RMW companion (this shard's chunk bytes
                    # were untouched): keep the size xattr and object
                    # version current, but only if we actually hold the
                    # object — and only when our log is contiguous, else
                    # we'd stamp a version whose writes we missed.
                    # (`ver` is a CHUNK-GENERATION stamp: xattr-only
                    # pushes carry msg.xattrs and must not touch it —
                    # they don't change stripe bytes)
                    if msg.version is not None and msg.version == pg.version + 1:
                        try:
                            self.store.stat(cid, msg.oid)
                        except (NotFound, KeyError):
                            pass
                        else:
                            t.setattr(cid, msg.oid, "size",
                                      str(msg.osize).encode())
                            t.setattr(cid, msg.oid, "ver",
                                      str(msg.version).encode())
                elif entry_op in (None, "delete") and not msg.xattrs:
                    # data-less delete (live op or recovery replay)
                    try:
                        self.store.stat(cid, msg.oid)
                        t.remove(cid, msg.oid)
                    except (NotFound, KeyError):
                        pass
                # else: entry-only push ("modify" log replay / "clean"
                # seal / xattr-only update) — no data op
                if msg.xattrs is not None:
                    if msg.data is not None:
                        # riding a data push (recovery): the dict is a FULL
                        # snapshot — stale attrs a removal we missed must
                        # not survive
                        self._apply_xattr_updates(
                            t, cid, msg.oid, msg.xattrs, snapshot=True
                        )
                    else:
                        # live xattr-only update: apply ONLY if this shard
                        # holds the object; a shard that missed the write
                        # must not grow a phantom zero-length object
                        # (recovery pushes data + attrs together later)
                        try:
                            self.store.stat(cid, msg.oid)
                        except (NotFound, KeyError):
                            pass
                        else:
                            self._apply_xattr_updates(
                                t, cid, msg.oid, msg.xattrs
                            )
                if getattr(msg, "rmattrs", None):
                    # atomic-with-data attr removals (cache-tier clean
                    # clear riding a mutation); only if we hold the object
                    try:
                        existing = set(self.store.getattrs(cid, msg.oid))
                    except (NotFound, KeyError):
                        existing = set()
                    for name in msg.rmattrs:
                        if f"u_{name}" in existing:
                            t.rmattr(cid, msg.oid, f"u_{name}")
                if getattr(msg, "omap", None) is not None:
                    # live omap mutation or recovery snapshot: omap
                    # exists on replicated pools only; an omap op on a
                    # fresh oid creates the object (touch), matching the
                    # primary's transaction
                    t.touch(cid, msg.oid)
                    self._apply_omap(t, cid, msg.oid, msg.omap)
                    if (msg.data is None and msg.version is not None
                            and msg.version == pg.version + 1):
                        # live omap-only update on a log-contiguous
                        # shard: stamp the version for dup verification
                        t.setattr(cid, msg.oid, "ver",
                                  str(msg.version).encode())
                if (
                    msg.entry is not None
                    and msg.version is not None
                    and msg.version > pg.version
                ):
                    if entry_op == "clean":
                        # a clean that JUMPS our version means we were
                        # backfilled across a gap: seal an empty log window
                        # so covers() stays honest about what we can vouch
                        # for entry-by-entry
                        self._log_seal_txn(t, cid, pg, msg.version)
                    elif msg.version == pg.version + 1:
                        entry = LogEntry.from_list(msg.entry)
                        self._log_txn(t, cid, pg, entry)
                    # else: the entry JUMPS our version (we missed writes —
                    # e.g. a sub-write lost while the primary acked at
                    # min_size).  Apply the data but refuse the log append:
                    # advancing head across a hole would make this shard
                    # report itself clean at a version whose intermediate
                    # objects it does not hold.  Our stale version makes
                    # the primary's next recovery tick replay the gap.
                self.store.queue_transaction(t)
        except Exception as e:
            self.cct.dout("osd", 0, f"{self.whoami} sub_write failed: {e!r}")
            retval = -5
        else:
            self.logger.inc("subop_w")
        try:
            conn.send_message(
                MECSubOpWriteReply(tid=msg.tid, pgid=msg.pgid,
                                   shard=msg.shard, retval=retval)
            )
        except (OSError, ConnectionError):
            pass

    def _handle_sub_read(self, conn, msg: MECSubOpRead) -> None:
        cid = self._cid(msg.pgid, msg.shard)
        try:
            if msg.offsets == []:
                # metadata-only probe: existence + size/xattrs, no body
                self.store.stat(cid, msg.oid)
                data = b""
            elif msg.offsets:
                # ranged reads feed RMW old-byte fetches and CLAY repair:
                # verify the WHOLE chunk's hinfo first — serving rotted
                # bytes here would poison a parity delta with a fresh CRC
                # stamped over it (no rot check could catch it later)
                whole = self.store.read(cid, msg.oid)
                try:
                    stored = int(self.store.getattr(cid, msg.oid, "hinfo"))
                except (NotFound, KeyError, ValueError):
                    stored = None
                if stored is not None and crc32c(whole) != stored:
                    self.cct.dout(
                        "osd", 0,
                        f"{self.whoami} hinfo mismatch on ranged read "
                        f"{msg.pgid}/{msg.oid} shard {msg.shard}",
                    )
                    raise NotFound(msg.oid)
                parts = []
                for off, ln in msg.offsets:
                    if ln == -1:
                        parts.append(whole)
                    else:
                        parts.append(whole[off:off + ln])
                data = b"".join(parts)
            else:
                data = self.store.read(cid, msg.oid)
                # full-chunk read: verify at-rest integrity against the
                # stored hinfo CRC before serving — a rotted chunk must
                # read as MISSING so the primary reconstructs instead of
                # decoding garbage (reference: ECBackend checks
                # ECUtil::HashInfo on read, -EIO on mismatch)
                try:
                    stored = int(self.store.getattr(cid, msg.oid, "hinfo"))
                except (NotFound, KeyError, ValueError):
                    stored = None
                if stored is not None and crc32c(data) != stored:
                    self.cct.dout(
                        "osd", 0,
                        f"{self.whoami} hinfo mismatch on read "
                        f"{msg.pgid}/{msg.oid} shard {msg.shard}",
                    )
                    raise NotFound(msg.oid)
            try:
                size = int(self.store.getattr(cid, msg.oid, "size"))
            except (NotFound, KeyError):
                size = None
            try:
                user = {
                    n[2:]: pack_data(v)
                    for n, v in self.store.getattrs(cid, msg.oid).items()
                    if n.startswith("u_")
                }
            except (NotFound, KeyError):
                user = None
            reply = MECSubOpReadReply(
                tid=msg.tid, pgid=msg.pgid, oid=msg.oid, shard=msg.shard,
                retval=0, data=pack_data(data), size=size, xattrs=user,
                ver=self._stored_ver(cid, msg.oid),
            )
        except (NotFound, KeyError):
            reply = MECSubOpReadReply(
                tid=msg.tid, pgid=msg.pgid, oid=msg.oid, shard=msg.shard,
                retval=-2, data=None, size=None, xattrs=None, ver=None,
            )
        try:
            conn.send_message(reply)
        except (OSError, ConnectionError):
            pass

    def _handle_pg_query(self, conn, msg: MPGQuery) -> None:
        pool_id, ps = msg.pgid.split(".")
        pg = self._pg(int(pool_id), int(ps))
        cid = self._cid(msg.pgid, msg.shard)
        oids = []
        try:
            oids = sorted(
                o for o in self.store.list_objects(cid)
                if not o.startswith("_")
            )
        except (NotFound, KeyError):
            pass
        try:
            conn.send_message(
                MPGNotify(tid=msg.tid, pgid=msg.pgid, shard=msg.shard,
                          version=pg.version, log_start=pg.log.tail,
                          oids=oids, last_epoch=pg.last_map_epoch)
            )
        except (OSError, ConnectionError):
            pass

    def _handle_pg_clean(self, msg: MPGClean) -> None:
        """Primary says the PG went clean at `epoch` (the
        last_epoch_clean role): advance the persisted rebuild floor and
        drop local interval history — settled intervals must never
        re-block a future peering round.  A clean claim from a PAST
        interval is ignored (a deposed primary cannot retro-settle
        history it no longer owns)."""
        pool_id, ps = msg.pgid.split(".")
        pg = self._pg(int(pool_id), int(ps))
        with pg.lock:
            if msg.epoch < pg.interval_start:
                return
            pg.last_map_epoch = max(pg.last_map_epoch, int(msg.epoch))
            pg.past_intervals.clear()
            pg.intervals_rebuilt = False
            self._save_intervals(pg)

    # -- scrub (reference: src/osd/scrubber — deep scrub subset) ----------
    def _local_scrub_map(self, cid: str) -> dict:
        """ScrubMap of one shard collection: oid -> [computed_crc,
        stored_crc_or_None, size] (reference: PGBackend::be_scan_list)."""
        objects: dict[str, list] = {}
        try:
            oids = self.store.list_objects(cid)
        except (NotFound, KeyError):
            return objects
        for oid in oids:
            if oid.startswith("_"):
                continue
            try:
                data = self.store.read(cid, oid)
            except (NotFound, KeyError):
                continue
            try:
                stored = int(self.store.getattr(cid, oid, "hinfo"))
            except (NotFound, KeyError, ValueError):
                stored = None
            objects[oid] = [crc32c(data), stored, len(data)]
        return objects

    def _replicated_authoritative(
        self, pg, maps: dict, acting: list[int], oid: str, bad_shard: int
    ) -> tuple[bytes | None, int]:
        """Authoritative copy for a replicated repair: any replica whose
        scrub entry is self-consistent (computed == stored digest), the
        primary's preferred (reference: be_select_auth_object)."""
        candidates = sorted(
            maps,
            key=lambda s: (acting[s] != self.id, s),  # self first
        )
        for s in candidates:
            if s == bad_shard:
                continue
            ent = maps[s].get(oid)
            if ent is None or (ent[1] is not None and ent[0] != ent[1]):
                continue
            osd = acting[s]
            if osd == self.id:
                try:
                    data = self.store.read(self._cid(pg.pgid, 0), oid)
                    return bytes(data), len(data)
                except (NotFound, KeyError):
                    continue
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpRead(tid=tid, pgid=pg.pgid, oid=oid, shard=0,
                                 offsets=None, epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=5.0)
            if rep is not None and rep.retval == 0:
                data = unpack_data(rep.data)
                return data, len(data)
        return None, 0

    def _handle_scrub_shard(self, conn, msg: MScrubShard) -> None:
        try:
            conn.send_message(
                MScrubShardReply(
                    tid=msg.tid, pgid=msg.pgid, shard=msg.shard,
                    objects=self._local_scrub_map(
                        self._cid(msg.pgid, msg.shard)
                    ),
                )
            )
        except (OSError, ConnectionError):
            pass

    def scrub_pg(self, pool_id: int, ps: int, repair: bool = True) -> dict:
        """Deep scrub one PG from its primary: collect every shard's
        ScrubMap, flag shards whose at-rest bytes rotted under their own
        digest or that miss objects others hold, and (repair=True) rebuild
        those shards from the surviving ones (reference:
        PrimaryLogPG::scrub_compare_maps + repair_object)."""
        m = self.osdmap
        pool = m.pools.get(pool_id) if m else None
        if pool is None:
            raise KeyError(f"no pool {pool_id}")
        acting, primary = self._acting(pool_id, ps)
        if primary != self.id:
            raise RuntimeError(f"not primary for {pool_id}.{ps}")
        pg = self._pg(pool_id, ps)
        is_ec = pool.type == PG_POOL_ERASURE
        codec = self._codec_for_pool(pool) if is_ec else None
        # map collection runs UNLOCKED (writes proceed; a racing write can
        # only produce a false positive whose "repair" re-pushes current,
        # consistent bytes).  pg.lock is taken per-object for repairs, so
        # a slow shard never blocks client I/O for the whole scrub.
        maps: dict[int, dict] = {}
        tids: dict[int, int] = {}
        for shard, osd in enumerate(acting):
            store_shard = shard if is_ec else 0
            if osd < 0 or not m.is_up(osd):
                continue
            if osd == self.id:
                maps[shard] = self._local_scrub_map(
                    self._cid(pg.pgid, store_shard)
                )
                continue
            tid = self._next_tid()
            tids[tid] = shard
            try:
                self._conn_to_osd(osd).send_message(
                    MScrubShard(tid=tid, pgid=pg.pgid, shard=store_shard,
                                epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                tids.pop(tid, None)
        for tid, shard in tids.items():
            rep = self._wait_reply(tid, timeout=10.0)
            if rep is not None:
                maps[shard] = rep.objects or {}

        all_oids: set[str] = set()
        for sm in maps.values():
            all_oids |= set(sm)
        # objects whose FINAL log entry is a delete: a shard still holding
        # one is stale (its delete sub-op was lost) — flag the holder, and
        # never let "missing" on up-to-date shards resurrect the object
        _newest, log_deleted = pg.log.missing_since(0)
        my_shard = next((s for s in maps if acting[s] == self.id), None)
        errors: list[dict] = []
        for oid in sorted(all_oids):
            if oid in log_deleted:
                for shard, sm in maps.items():
                    if oid in sm:
                        errors.append(
                            {"oid": oid, "shard": shard,
                             "error": "stale_deleted"}
                        )
                continue
            # authoritative digest for cross-copy comparison (replicated):
            # a SELF-CONSISTENT copy, the primary's preferred (reference:
            # be_select_auth_object) — never a copy that fails its own
            # digest, so primary bit-rot cannot propagate
            auth_crc = None
            if not is_ec:
                order = sorted(
                    maps, key=lambda s: (s != my_shard, s)
                )
                for s in order:
                    ent = maps[s].get(oid)
                    if ent is None:
                        continue
                    if ent[1] is None or ent[0] == ent[1]:
                        auth_crc = ent[0]
                        break
            for shard, sm in maps.items():
                ent = sm.get(oid)
                if ent is None:
                    errors.append(
                        {"oid": oid, "shard": shard, "error": "missing"}
                    )
                elif ent[1] is not None and ent[0] != ent[1]:
                    # at-rest rot under the shard's own digest (EC chunks
                    # and, with hinfo now stamped everywhere, replicas)
                    errors.append(
                        {"oid": oid, "shard": shard,
                         "error": "data_digest_mismatch"}
                    )
                elif (
                    not is_ec
                    and auth_crc is not None
                    and ent[0] != auth_crc
                ):
                    errors.append(
                        {"oid": oid, "shard": shard,
                         "error": "data_digest_mismatch"}
                    )
            self.logger.inc("scrubs")
            self.logger.inc("scrub_errors", len(errors))
        repaired = 0
        if repair and errors:
            # shards known-bad per oid: their chunks must not feed a
            # rebuild (decoding from a rotted chunk would launder the
            # corruption into a fresh self-consistent digest)
            bad_by_oid: dict[str, set[int]] = {}
            for err in errors:
                bad_by_oid.setdefault(err["oid"], set()).add(err["shard"])
            for err in errors:
                shard = err["shard"]
                osd = acting[shard]
                store_shard = shard if is_ec else 0
                with pg.lock:  # per-object: writes proceed between repairs
                    if err["error"] == "stale_deleted":
                        if osd == self.id:
                            cid = self._cid(pg.pgid, store_shard)
                            t = Transaction()
                            try:
                                self.store.stat(cid, err["oid"])
                                t.remove(cid, err["oid"])
                                self.store.queue_transaction(t)
                                repaired += 1
                            except (NotFound, KeyError):
                                pass
                        elif self._push_sub_write(
                            pg, osd, store_shard, err["oid"], None, None,
                            None,
                        ):
                            repaired += 1
                        continue
                    if is_ec:
                        chunk, size = self._rebuild_shard_chunk(
                            pg, codec, acting, err["oid"], shard, True,
                            exclude=bad_by_oid.get(err["oid"], set()),
                        )
                    else:
                        chunk, size = self._replicated_authoritative(
                            pg, maps, acting, err["oid"], bad_shard=shard
                        )
                    if chunk is None:
                        continue
                    if osd == self.id:
                        cid = self._cid(pg.pgid, store_shard)
                        t = Transaction()
                        t.try_create_collection(cid)
                        t.write(cid, err["oid"], 0, chunk)
                        t.truncate(cid, err["oid"], len(chunk))
                        t.setattr(cid, err["oid"], "hinfo",
                                  str(crc32c(chunk)).encode())
                        t.setattr(cid, err["oid"], "size",
                                  str(size).encode())
                        self.store.queue_transaction(t)
                        repaired += 1
                    elif self._push_sub_write(
                        pg, osd, store_shard, err["oid"], chunk, None,
                        [0, "modify", err["oid"]], osize=size,
                        src_cid=self._cid(
                            pg.pgid,
                            acting.index(self.id) if is_ec else 0),
                    ):
                        repaired += 1
            self.logger.inc("scrub_repairs", repaired)
        return {
            "pgid": pg.pgid,
            "shards": len(maps),
            "objects": len(all_oids),
            "errors": errors,
            "repaired": repaired if repair else 0,
        }

    # -- heartbeats + recovery tick ---------------------------------------
    def _tick_loop(self) -> None:
        interval = 1.0
        last_hb = 0.0
        last_mgr = 0.0
        while not self._stop.is_set():
            self._recovery_wakeup.wait(timeout=interval)
            self._recovery_wakeup.clear()
            if self._stop.is_set():
                return
            now = time.monotonic()
            try:
                if now - last_hb >= 2.0:
                    last_hb = now
                    self._heartbeat()
                if now - last_mgr >= self.cct.conf.get("mgr_report_interval"):
                    last_mgr = now
                    self._mgr_report()
                # recovery rides the mClock queue as background work so
                # client ops keep their reservation during big recoveries
                if not self._recovery_inflight:
                    self._recovery_inflight = True
                    self.scheduler.enqueue(
                        "background_recovery", self._recover_all_work
                    )
                if not self._split_inflight:
                    self._split_inflight = True
                    self.scheduler.enqueue(
                        "background_recovery", self._split_pass_work
                    )
                self._maybe_schedule_scrub(now)
            except Exception as e:
                self.cct.dout("osd", 0, f"{self.whoami} tick failed: {e!r}")

    def _recover_all_work(self) -> None:
        try:
            self._recover_all()
        finally:
            self._recovery_inflight = False

    # -- PG split migration (pg_num increase) ------------------------------
    def _split_pass_work(self) -> None:
        try:
            self._split_pass()
            self._snaptrim_pass()
            self._tier_agent_pass()
        finally:
            self._split_inflight = False

    def _split_pass(self) -> None:
        """Migrate objects stranded in pre-split PGs (reference: PG split —
        OSD::split_pgs + backfill; here the old-PG primary rewrites each
        misplaced object through the normal client-op path to its
        post-split PG, then deletes the old copy).

        Eventually consistent: the pass re-runs every tick until each
        primary PG has been scanned clean under the current pg_num, so an
        OSD that was down during the split finishes the job when it
        returns.  Window semantics: until an object is migrated, clients
        on the new map read -ENOENT from the post-split PG (the reference
        covers this window with pg history + peering; SURVEY's data plane
        accepts the brief window)."""
        m = self.osdmap
        if m is None:
            return
        for pgid, pg in list(self.pgs.items()):
            if self._stop.is_set():
                return
            pool = m.pools.get(pg.pool_id)
            if pool is None or pg.split_scanned >= pool.pg_num:
                continue
            _acting, primary = self._acting(pg.pool_id, pg.ps)
            if primary != self.id:
                continue  # re-checked next pass (primary may change)
            try:
                self._split_migrate_pg(pg, pool)
                pg.split_scanned = pool.pg_num
            except Exception as e:
                self.cct.dout(
                    "osd", 1, f"{self.whoami} split pass {pgid}: {e!r}"
                )

    def _split_migrate_pg(self, pg, pool) -> None:
        # raw store listing: snapshot clones are hidden from the client
        # `list` op but must migrate with their head
        acting, _p = self._acting(pg.pool_id, pg.ps)
        if self.id not in acting:
            return
        try:
            names = self.store.list_objects(
                self._primary_cid(pg, pool, acting)
            )
        except (NotFound, KeyError):
            return
        for oid in sorted(names):
            if oid.startswith("_"):
                continue
            head = oid.split(CLONE_SEP, 1)[0]
            new_ps = object_ps(head, pool.pg_num)
            if new_ps != pg.ps:
                self._migrate_object(pg, pool, oid, new_ps)

    def _forward_op(self, target: int, msg: MOSDOp):
        """Execute an op locally when this OSD is the target primary, else
        ship it and wait (the OSD acting as its own Objecter)."""
        if target == self.id:
            return self._execute_client_op(msg)
        conn = self._conn_to_osd(target)
        conn.send_message(msg)
        return self._wait_reply(msg.tid, timeout=15.0)

    def _migrate_object(self, pg, pool, oid: str, new_ps: int) -> None:
        """write-to-new-PG before delete-from-old: a crash mid-migration
        leaves a duplicate (invisible: lookups hash to the new PG), never
        a loss.

        Lost-update guard: a client on the new map may have ALREADY
        written the object into its post-split PG; the stale pre-split
        copy must not clobber it, so the destination is stat'd first and
        a hit just drops the old copy.  (A write landing between the stat
        and our write is the residual window; the reference closes it
        with peering's authoritative log — out of scope here and noted.)
        """
        e = self.my_epoch()
        _a, new_primary = self._acting(pg.pool_id, new_ps)
        # every dest op carries the explicit post-split ps: snapshot-clone
        # names would hash elsewhere (placement follows their HEAD object)
        st = self._forward_op(new_primary, MOSDOp(
            tid=self._next_tid(), pool=pg.pool_id, oid=oid, op="stat",
            epoch=e, ps=new_ps,
        ))
        if st is not None and st.retval == 0:
            # newer post-split copy exists: just retire the stale one
            d = self._execute_client_op(MOSDOp(
                tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                op="delete", epoch=e, ps=pg.ps,
            ))
            if d.retval != 0:
                raise RuntimeError(f"split retire {oid}: {d.result}")
            return
        r = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pg.pool_id, oid=oid, op="read",
            epoch=e, ps=pg.ps, off=0, length=0,
        ))
        if r.retval != 0:
            raise RuntimeError(f"split read {oid}: {r.result}")
        xr = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pg.pool_id, oid=oid,
            op="getxattrs", epoch=e, ps=pg.ps,
        ))
        xattrs = xr.result if xr.retval == 0 else None
        w = self._forward_op(new_primary, MOSDOp(
            tid=self._next_tid(), pool=pg.pool_id, oid=oid,
            op="write_full", data=r.data, epoch=e, ps=new_ps,
        ))
        if w is None or w.retval != 0:
            raise RuntimeError(
                f"split write {oid}: {w.result if w else 'timeout'}"
            )
        if xattrs:
            xw = self._forward_op(new_primary, MOSDOp(
                tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                op="setxattr", data=xattrs, epoch=e, ps=new_ps,
            ))
            if xw is None or xw.retval != 0:
                raise RuntimeError(
                    f"split xattrs {oid}: {xw.result if xw else 'timeout'}"
                )
        d = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pg.pool_id, oid=oid, op="delete",
            epoch=e, ps=pg.ps,
        ))
        if d.retval != 0:
            raise RuntimeError(f"split delete {oid}: {d.result}")
        self.cct.dout(
            "osd", 10,
            f"{self.whoami} split: migrated {oid} "
            f"{pg.pool_id}.{pg.ps} -> {pg.pool_id}.{new_ps}",
        )

    def _maybe_schedule_scrub(self, now: float) -> None:
        """Periodic deep scrub of primary PGs (reference: OSD::sched_scrub;
        osd_deep_scrub_interval 0 disables — tests drive scrub_pg
        directly)."""
        interval = self.cct.conf.get("osd_deep_scrub_interval")
        if not interval or now - self._last_scrub < interval:
            return
        self._last_scrub = now
        m = self.osdmap
        if m is None:
            return
        for pool_id, pool in m.pools.items():
            for ps in range(pool.pg_num):
                try:
                    _acting, primary = self._acting(pool_id, ps)
                except KeyError:
                    continue
                if primary != self.id:
                    continue
                pgid = f"{pool_id}.{ps}"
                if pgid in self._scrubs_queued:
                    continue  # scrubs outlasting the interval must not pile
                self._scrubs_queued.add(pgid)

                def scrub_work(pid=pool_id, s=ps, key=pgid):
                    try:
                        self.scrub_pg(pid, s)
                    finally:
                        self._scrubs_queued.discard(key)

                self.scheduler.enqueue("background_scrub", scrub_work)

    def _mgr_report(self) -> None:
        """Stream a perf snapshot to the mgr (reference: MgrClient sending
        MMgrReport on its tick)."""
        addr = self.cct.conf.get("mgr_addr")
        if not addr:
            return
        from ..mgr.messages import MMgrReport

        host, _, port = addr.rpartition(":")
        with self._pgs_lock:
            num_pgs = len(self.pgs)
        # the store scan runs UNLOCKED: heartbeats/recovery/map-apply all
        # contend on _pgs_lock, and an O(objects) walk per report tick
        # must not delay them toward the failure-report threshold
        num_objects = 0
        pool_bytes: dict[int, int] = {}
        try:
            coll_bytes = self.store.collections_bytes()  # one index pass
        except Exception:
            coll_bytes = {}
        for cid in self.store.list_collections():
            pool_id = None
            if "." in cid:
                try:
                    pool_id = int(cid.split(".", 1)[0])
                except ValueError:
                    pool_id = None
            try:
                num_objects += sum(
                    1 for o in self.store.list_objects(cid)
                    if not o.startswith("_")
                )
            except Exception:
                continue
            if pool_id is not None:
                pool_bytes[pool_id] = (
                    pool_bytes.get(pool_id, 0) + coll_bytes.get(cid, 0)
                )
        self.logger.set("numpg", num_pgs)
        try:
            self.messenger.connect((host, int(port))).send_message(
                MMgrReport(
                    daemon=self.whoami,
                    counters=self.cct.perf.dump(),
                    epoch=self.my_epoch(),
                    stats={"num_pgs": num_pgs, "num_objects": num_objects,
                           "pool_bytes": {
                               str(k): v for k, v in pool_bytes.items()
                           }},
                )
            )
        except (OSError, ConnectionError, ValueError):
            pass  # mgr down: retry next interval

    def _heartbeat(self) -> None:
        """Ping peers sharing PGs with us (reference: OSD::heartbeat);
        after 3 silent intervals report the peer to the mon (§5.3)."""
        m = self.osdmap
        if m is None:
            return
        peers: set[int] = set()
        with self._pgs_lock:
            pgs = list(self.pgs.values())
        for pg in pgs:
            try:
                acting, _ = self._acting(pg.pool_id, pg.ps)
            except KeyError:
                continue
            peers |= {o for o in acting if o >= 0 and o != self.id}
        for osd in peers:
            if not m.is_up(osd):
                continue
            prev = self._hb_failures.get(osd, 0)
            try:
                self._conn_to_osd(osd).send_message(
                    MOSDPingMsg(op="ping", osd=self.id, epoch=self.my_epoch())
                )
                self._hb_failures[osd] = prev + 1
            except (OSError, ConnectionError):
                self._hb_failures[osd] = prev + 1
            if self._hb_failures.get(osd, 0) >= 3:
                self.mc.report_failure(osd, failed_for=6.0)
                # restart the count: re-report only after another 3 silent
                # intervals, not on every subsequent tick
                self._hb_failures.pop(osd, None)

    # -- recovery (peering-lite, primary only) ----------------------------
    def _recover_all(self) -> None:
        m = self.osdmap
        if m is None:
            return
        # discover PGs I'm primary for (incl. ones with no local data yet)
        for pool_id, pool in m.pools.items():
            for ps in range(pool.pg_num):
                try:
                    acting, primary = self._acting(pool_id, ps)
                except KeyError:
                    continue
                if primary != self.id or self.id not in acting:
                    continue
                pg = self._pg(pool_id, ps)
                # NO pg.lock here: _recover_pg's pull phase waits on the
                # donor's sub-writes, which our dispatch thread can only
                # apply after taking pg.lock — holding it across the pull
                # self-deadlocks.  _recover_pg locks its push phase.
                try:
                    self._recover_pg(pg, pool, acting)
                except Exception as e:
                    self.cct.dout(
                        "osd", 1,
                        f"{self.whoami} recover {pg.pgid}: {e!r}",
                    )

    def _rebuild_intervals_from_maps(self, pg: PGState, start: int,
                                     until: int | None = None) -> None:
        """Reconstruct interval history from the mon's stored maps
        (reference: PastIntervals::check_new_interval walked over past
        OSDMaps via OSDService::get_map).  A revived OSD's in-memory
        tracking saw nothing while it was down, and a freshly-assigned
        primary only started recording at its own PG creation; the maps
        saw everything.  Rebuilds the closures over [start, until) and
        PREPENDS them to whatever in-memory history already exists."""
        from .past_intervals import PastIntervals

        cur = self.my_epoch()
        until = cur if until is None else min(until, cur)
        start = max(1, start)
        if until - start > 512:
            start = until - 512  # bound mon fetches on huge gaps
        # batched fetch: ~8 round trips for the full 512-epoch bound
        # instead of one command per epoch (review r4)
        fetched: dict[int, dict] = {}
        e = start
        while e <= until:
            if self.osdmap is not None and e == self.osdmap.epoch:
                e += 1
                continue
            try:
                rv, res = self.mc.command(
                    {"prefix": "osd getmaps", "first": e, "last": until},
                    timeout=10.0,
                )
            except (OSError, ConnectionError):
                return  # mon unreachable: retry next pass
            if rv != 0:
                return
            fetched.update(
                {int(k): v for k, v in res.get("maps", {}).items()}
            )
            e = int(res.get("last", e)) + 1
        rebuilt = PastIntervals()
        prev = None
        prev_ua = None
        first = start
        for e in range(start, until + 1):
            if self.osdmap is not None and e == self.osdmap.epoch:
                m = self.osdmap
            else:
                j = fetched.get(e)
                if j is None:
                    continue  # epoch gap (paxos-trimmed): skip
                m = OSDMap.from_json(j)
            try:
                ua = m.pg_to_up_acting_osds(pg.pool_id, pg.ps)
            except Exception:
                prev, prev_ua = m, None
                continue
            if prev_ua is not None and (prev_ua[2], prev_ua[3]) != \
                    (ua[2], ua[3]):
                pool = prev.pools.get(pg.pool_id)
                went_rw = (
                    prev_ua[3] >= 0
                    and pool is not None
                    and sum(1 for a in prev_ua[2] if a >= 0) >= pool.min_size
                )
                rebuilt.add(
                    first=first, last=m.epoch - 1,
                    up=prev_ua[0], acting=prev_ua[2], primary=prev_ua[3],
                    maybe_went_rw=went_rw,
                )
                first = m.epoch
            prev, prev_ua = m, ua
        pg.intervals_rebuilt = True
        if rebuilt:
            from .past_intervals import MAX_INTERVALS

            # keep the NEWEST MAX_INTERVALS — direct assignment must not
            # bypass add()'s growth cap (review r4)
            pg.past_intervals.intervals = (
                rebuilt.intervals + pg.past_intervals.intervals
            )[-MAX_INTERVALS:]
            self.cct.dout(
                "osd", 1,
                f"{self.whoami} {pg.pgid} rebuilt "
                f"{len(rebuilt.intervals)} past interval(s) from maps "
                f"[{start},{until}]",
            )
            self._save_intervals(pg)

    def _recover_pg(self, pg: PGState, pool, acting: list[int]) -> None:
        is_ec = pool.type == PG_POOL_ERASURE
        codec = self._codec_for_pool(pool) if is_ec else None
        # one query round: peer versions + object lists drive the
        # authoritative-log pull, the per-peer classification, and
        # delete propagation
        peers: dict[tuple[int, int], tuple[int, list]] = {}
        peer_epochs: list[int] = []
        for shard, osd in enumerate(acting):
            if osd < 0 or osd == self.id or not self.osdmap.is_up(osd):
                continue
            # replicated replicas all store in the s0 collection; only EC
            # shards have per-shard collections
            store_shard = shard if is_ec else 0
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(
                    MPGQuery(tid=tid, pgid=pg.pgid, shard=store_shard,
                             epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=5.0)
            if rep is None or rep.version is None:
                continue
            peers[(shard, osd)] = (rep.version, rep.oids or [])
            e = getattr(rep, "last_epoch", None)
            if e:
                peer_epochs.append(int(e))
        interval_at_entry = pg.interval_start
        # history rebuild (reference: pg_history_t carried in notifies +
        # PastIntervals built over past OSDMaps): when this primary has
        # no interval history but the PG demonstrably has a past — its
        # own or any peer's last-write epoch predates the current
        # interval — fetch the intervening maps from the mon and
        # reconstruct the closed intervals before judging anything.
        # Covers both the revived stale OSD (its own epoch is old) and
        # the freshly-assigned empty primary (a peer's epoch is old) —
        # even one that already recorded SOME closures of its own: the
        # rebuild fills the prefix its in-memory tracking predates.
        known = [e for e in ([pg.last_map_epoch] + peer_epochs) if e]
        hist_floor = (
            pg.past_intervals.intervals[0]["first"]
            if pg.past_intervals else pg.interval_start
        )
        if (
            not pg.intervals_rebuilt
            and known
            and min(known) < hist_floor
        ):
            self._rebuild_intervals_from_maps(
                pg, start=min(known), until=hist_floor
            )
        # choose_acting beyond the acting set (reference: build_prior +
        # choose_acting over PastIntervals): members of past rw
        # intervals may hold a log NEWER than anything the current
        # acting set has — query them too, bounded by the history
        strays: dict[tuple[int, int], int] = {}
        queried = {self.id} | {osd for (_s, osd) in peers}
        prior = pg.past_intervals.query_candidates(
            exclude={-1, self.id} | {o for o in acting if o >= 0},
            is_up=self.osdmap.is_up,
        )
        for osd, p_shard in prior.items():
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(
                    MPGQuery(tid=tid, pgid=pg.pgid,
                             shard=p_shard if is_ec else 0,
                             epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=5.0)
            if rep is None or rep.version is None:
                continue
            queried.add(osd)
            strays[(p_shard, osd)] = rep.version
        # build_prior activation block: a past rw interval NONE of whose
        # members answered may hold the authoritative log — activating
        # anyway could serve a stale/forked history (the exact failure
        # generation floors cannot see).  Stay inactive and retry.
        blocked = pg.past_intervals.blocked_by(queried)
        if blocked:
            iv = blocked[0]
            self.cct.dout(
                "osd", 1,
                f"{self.whoami} {pg.pgid} peering blocked: interval "
                f"[{iv['first']},{iv['last']}] acting {iv['acting']} "
                f"went rw and no member is reachable",
            )
            return
        # phase 0 — adopt the authoritative log (reference: peering's
        # choose_acting/authoritative-log step): a primary revived after
        # missing writes must catch ITSELF up first, else it would mint
        # duplicate versions on the next write and wrongly judge
        # ahead-peers clean (wait_clean compares against the primary).
        # Runs WITHOUT pg.lock: the donor's catch-up arrives as
        # MECSubOpWrites our dispatch thread applies under that lock.
        ahead = {k: v for k, (v, _o) in peers.items() if v > pg.version}
        stray_newest = max(strays.values(), default=0)
        if stray_newest > max([pg.version, *ahead.values()]):
            if is_ec:
                # an EC stray proves newer writes exist, but a non-acting
                # donor cannot push shard-correct chunks (the donor path
                # reads by its acting index) — stay INACTIVE rather than
                # activate on a log we know is stale; the PG heals when
                # the stray rejoins acting or an acting member catches up
                self.cct.dout(
                    "osd", 1,
                    f"{self.whoami} {pg.pgid} stale vs stray holders "
                    f"(v{stray_newest} > v{pg.version}); deferring "
                    f"activation",
                )
                return
            # replicated: the past-interval holder IS the authoritative
            # log donor even though it is not acting (choose_acting
            # electing a stray; every replica is shard 0, so the pull
            # path needs no shard translation)
            ahead = {
                k: v for k, v in strays.items() if v == stray_newest
            }
        if ahead:
            (_b_shard, b_osd), _bv = max(ahead.items(), key=lambda kv: kv[1])
            my_shard = acting.index(self.id) if is_ec else 0
            try:
                my_oids = [
                    o for o in self.store.list_objects(
                        self._cid(pg.pgid, my_shard))
                    if not o.startswith("_")
                ]
            except (NotFound, KeyError):
                my_oids = []
            tid = self._next_tid()
            try:
                self._conn_to_osd(b_osd).send_message(MPGPull(
                    tid=tid, pgid=pg.pgid, shard=my_shard,
                    from_version=pg.version, epoch=self.my_epoch(),
                    have_oids=my_oids,
                ))
                rep = self._wait_reply(tid, timeout=30.0)
            except (OSError, ConnectionError):
                rep = None
            if rep is not None and rep.retval == 0:
                self.cct.dout(
                    "osd", 1,
                    f"{self.whoami} pulled {pg.pgid} forward to "
                    f"v{pg.version} from osd.{b_osd}",
                )
            else:
                return  # retry next tick; judging peers now would be wrong
        # peered: no peer is ahead (or we just adopted the ahead log) —
        # this primary may now serve ops for the current interval
        pg.activated_interval = interval_at_entry
        if pg.version == 0:
            return  # nothing written yet
        my_shard = acting.index(self.id) if is_ec else 0
        my_cid = self._cid(pg.pgid, my_shard)

        def _my_oids() -> set:
            try:
                return {
                    o for o in self.store.list_objects(my_cid)
                    if not o.startswith("_")
                }
            except (NotFound, KeyError):
                return set()

        my_oids = _my_oids()
        # phase 0.5 — SELF role-heal: an acting permutation can hand this
        # primary a shard role it never held; every peer below is judged
        # against MY collection, so an empty one would read as
        # everything-clean while the primary serves nothing.  Pull full
        # content from an up-to-date peer — the donor's backfill push
        # carries data + xattrs + omap and deletes my stale extras
        # (reference: the primary recovers itself first in
        # PeeringState::activate / recovery_state).
        peer_union: set = set()
        for (_v, oids) in peers.values():
            peer_union.update(oids)
        if peer_union - my_oids:
            donor = next(
                (osd for (shard, osd), (v, _o) in peers.items()
                 if v >= pg.version),
                None,
            )
            if donor is not None:
                self.cct.dout(
                    "osd", 1,
                    f"{self.whoami} self role-heal {pg.pgid} shard "
                    f"{my_shard}: {len(peer_union - my_oids)} objects "
                    f"from osd.{donor}",
                )
                tid = self._next_tid()
                try:
                    self._conn_to_osd(donor).send_message(MPGPull(
                        tid=tid, pgid=pg.pgid, shard=my_shard,
                        from_version=0, epoch=self.my_epoch(),
                        have_oids=sorted(my_oids),
                    ))
                    self._wait_reply(tid, timeout=30.0)
                except (OSError, ConnectionError):
                    pass
                my_oids = _my_oids()
        # push phase: serialize vs concurrent client writes on this PG
        all_clean = True
        with pg.lock:
            for (shard, osd), (peer_ver, peer_oids) in peers.items():
                role_missing = my_oids - set(peer_oids)
                if peer_ver >= pg.version and not role_missing:
                    continue  # clean
                all_clean = False
                if peer_ver >= pg.version:
                    # version-current but the SHARD ROLE's objects are
                    # absent: an acting-set permutation (OSD out -> CRUSH
                    # reshuffle) handed this OSD a shard it never held —
                    # the per-PG version cannot see that, only the
                    # contents comparison can.  Rebuild its new role's
                    # chunks (and retire any stale leftovers in that
                    # collection from an older interval).
                    self.cct.dout(
                        "osd", 1,
                        f"{self.whoami} role-backfill {pg.pgid} shard "
                        f"{shard} osd.{osd}: {len(role_missing)} objects",
                    )
                    self._push_objects(
                        pg, codec, acting, shard if is_ec else 0, osd,
                        {o: None for o in sorted(role_missing)},
                        set(peer_oids) - my_oids, is_ec,
                    )
                else:
                    self._push_missing(
                        pg, codec, acting, shard if is_ec else 0, osd,
                        peer_ver, is_ec, peer_oids,
                    )
        # prune the interval history once the PG is CLEAN in the current
        # interval (reference: last_epoch_clean).  "Clean" demands a
        # FULL acting set in which every member answered and needed no
        # push — a degraded PG keeps its history: those unheard members
        # are exactly what the history exists to track (review r4).
        # The clean point is BROADCAST to the acting replicas (MPGClean)
        # so their persisted rebuild floors advance too — otherwise a
        # later primary rebuilding from a replica's stale last-write
        # epoch would resurrect already-settled intervals whose members
        # are long gone and block activation forever (review r4).
        acting_members = {o for o in acting if o >= 0 and o != self.id}
        if (
            all_clean
            and all(o >= 0 for o in acting)
            and acting_members <= {osd for (_s, osd) in peers}
            and (pg.past_intervals
                 or pg.clean_broadcast_interval != interval_at_entry)
        ):
            epoch = self.my_epoch()
            pg.past_intervals.clear()
            pg.last_map_epoch = max(pg.last_map_epoch, epoch)
            pg.intervals_rebuilt = False
            pg.clean_broadcast_interval = interval_at_entry
            self._save_intervals(pg)
            for shard, osd in enumerate(acting):
                if osd < 0 or osd == self.id or not self.osdmap.is_up(osd):
                    continue
                try:
                    self._conn_to_osd(osd).send_message(MPGClean(
                        pgid=pg.pgid, shard=shard if is_ec else 0,
                        epoch=epoch,
                    ))
                except (OSError, ConnectionError):
                    pass  # replica re-learns at its next clean pass

    def _push_missing(self, pg, codec, acting, dest_shard, dest_osd,
                      from_version, is_ec, dest_oids) -> bool:
        """Classify delta vs backfill, push, seal — shared by the primary
        push loop and the pull donor.  Counters are started/completed
        pairs: stat_delta_recoveries / stat_backfills count rounds
        STARTED (race-free for observers — an ack lost after the peer
        applied would leave a completed-only counter at zero), the
        *_completed twins count fully acked rounds."""
        my_shard = acting.index(self.id) if is_ec else 0
        if pg.log.covers(from_version):
            self.cct.dout(
                "osd", 1,
                f"{self.whoami} delta-recovery {pg.pgid} "
                f"shard {dest_shard} osd.{dest_osd} from v{from_version}",
            )
            pg.stat_delta_recoveries = getattr(
                pg, "stat_delta_recoveries", 0) + 1
            ok = self._push_log_delta(
                pg, codec, acting, dest_shard, dest_osd, from_version, is_ec
            )
            if ok:
                self._bump_peer_version(pg, dest_shard, dest_osd, pg.version)
                pg.stat_delta_completed = getattr(
                    pg, "stat_delta_completed", 0) + 1
            return ok
        # log too old: full backfill of this shard.  Versions are
        # unknowable per object (trimmed), so chunks are pushed
        # unversioned and the final sync entry seals the version.  The
        # target's extra objects (deleted here after its log horizon)
        # get data-less deletes — a survivors-only push would resurrect
        # deletions when the target is later trusted.
        try:
            oids = [
                o for o in self.store.list_objects(
                    self._cid(pg.pgid, my_shard))
                if not o.startswith("_")
            ]
        except (NotFound, KeyError):
            oids = []
        deleted = set(dest_oids or []) - set(oids)
        self.cct.dout(
            "osd", 1,
            f"{self.whoami} backfill {pg.pgid} shard {dest_shard} "
            f"osd.{dest_osd}: {len(oids)} objects, "
            f"{len(deleted)} deletions",
        )
        pg.stat_backfills = getattr(pg, "stat_backfills", 0) + 1
        ok = self._push_objects(
            pg, codec, acting, dest_shard, dest_osd,
            {o: None for o in oids}, deleted, is_ec,
        )
        if ok:
            self._bump_peer_version(pg, dest_shard, dest_osd, pg.version)
            pg.stat_backfill_completed = getattr(
                pg, "stat_backfill_completed", 0) + 1
        return ok

    def _handle_pg_pull(self, conn, msg: MPGPull) -> None:
        """An ahead peer serving a stale primary's catch-up request: push
        my log delta (or full objects + deletions when my log was
        trimmed) to the requester, then seal its version (the
        authoritative-log donor role in peering).  Runs under MY pg.lock
        so a concurrent write cannot advance the version mid-push and
        let the seal vouch for entries never sent; the requester holds
        no lock while waiting, so there is no cross-OSD lock cycle."""
        retval = -5
        try:
            pool_id, ps = msg.pgid.split(".")
            pg = self._pg(int(pool_id), int(ps))
            pool = self.osdmap.pools.get(int(pool_id))
            requester = (
                int(msg.src.split(".", 1)[1])
                if msg.src.startswith("osd.") else None
            )
            if pool is None or requester is None:
                raise ValueError(f"bad pull {msg.src} {msg.pgid}")
            acting, _p = self._acting(int(pool_id), int(ps))
            is_ec = pool.type == PG_POOL_ERASURE
            codec = self._codec_for_pool(pool) if is_ec else None
            from_v = int(msg.from_version or 0)
            with pg.lock:
                if pg.version <= from_v:
                    retval = 0  # nothing newer here
                else:
                    ok = self._push_missing(
                        pg, codec, acting, msg.shard, requester, from_v,
                        is_ec, msg.have_oids,
                    )
                    retval = 0 if ok else -5
        except Exception as e:
            self.cct.dout(
                "osd", 0, f"{self.whoami} pg pull failed: {e!r}"
            )
        try:
            conn.send_message(MPGPullReply(
                tid=msg.tid, pgid=msg.pgid, shard=msg.shard, retval=retval
            ))
        except (OSError, ConnectionError):
            pass

    def _push_sub_write(self, pg, osd, shard, oid, data, version, entry,
                        src_cid: str | None = None,
                        osize: int | None = None) -> bool:
        """One recovery push; True iff the peer acked it (retval 0).
        Data pushes copy the object's user xattrs from `src_cid` (the
        primary's own shard collection) so a recovered shard can answer
        getxattrs after a primary move.  They also carry the primary's
        stored chunk-generation stamp (`over`): the pushed bytes are
        rebuilt-CURRENT, and stamping the log-entry version instead
        would diverge from undisturbed shards whenever the log advanced
        through xattr-only modifies (which don't change stripe bytes)."""
        xattrs = None
        gen = None
        omap = None
        if data is not None and src_cid is not None:
            gen = self._stored_ver(src_cid, oid)
            try:
                mine = self.store.getattrs(src_cid, oid)
            except (NotFound, KeyError):
                mine = {}
            # always a dict (may be empty): the receiver treats it as the
            # FULL snapshot, clearing stale attrs a removal left behind
            xattrs = {
                n[2:]: pack_data(v)
                for n, v in mine.items() if n.startswith("u_")
            }
            try:
                kv = self.store.omap_get(src_cid, oid)
            except (NotFound, KeyError):
                kv = {}
            # omap recovered as a full snapshot, like the xattrs — sent
            # even when empty so a replica's stale keys are cleared
            omap = {"snapshot": {k: pack_data(v) for k, v in kv.items()}}
        tid = self._next_tid()
        try:
            self._conn_to_osd(osd).send_message(
                MECSubOpWrite(
                    tid=tid, pgid=pg.pgid, oid=oid, shard=shard,
                    data=pack_data(data) if data is not None else None,
                    crc=crc32c(data) if data is not None else None,
                    version=version, entry=entry, epoch=self.my_epoch(),
                    xattrs=xattrs, over=gen, osize=osize, omap=omap,
                )
            )
        except (OSError, ConnectionError):
            return False
        rep = self._wait_reply(tid, timeout=5.0)
        return rep is not None and rep.retval == 0

    def _push_log_delta(self, pg, codec, acting, shard, osd,
                        peer_version: int, is_ec: bool) -> bool:
        """Delta recovery: replay the FULL entry stream since the peer's
        version, in order, so the peer's pg_log stays contiguous and its
        covers() answer stays honest if it later becomes primary
        (reference: PGLog merge + pg_missing_t-driven recover_object).

        Data rides only the newest modify of each object; earlier modifies
        and deletes replay as log-only / delete pushes.  Returns True only
        if every push acked, so the caller never marks the peer clean past
        data it does not hold."""
        newest, _deleted = pg.log.missing_since(peer_version)
        my_cid = self._cid(
            pg.pgid, acting.index(self.id) if is_ec else 0
        )
        for e in pg.log.entries_since(peer_version):
            if e.op == "delete":
                ok = self._push_sub_write(
                    pg, osd, shard, e.oid, None, e.version, e.to_list()
                )
            elif e.op in ("modify", "attr") and newest.get(e.oid) == e.version:
                chunk, size = self._rebuild_shard_chunk(
                    pg, codec, acting, e.oid, shard, is_ec
                )
                if chunk is None:
                    # UNFOUND right now (reference: missing_loc unfound
                    # set): park THIS object but keep recovering the
                    # rest — one unrecoverable object must not wedge
                    # the whole peer's recovery.  The entry still
                    # replays (log stays contiguous); the object stays
                    # missing on the peer exactly as it is everywhere
                    # else, and a later tick retries when a source
                    # resurfaces.
                    self.cct.dout(
                        "osd", 1,
                        f"{self.whoami} recovery: {pg.pgid}/{e.oid} "
                        f"unfound, parking",
                    )
                    ok = self._push_sub_write(
                        pg, osd, shard, e.oid, None, e.version,
                        e.to_list(),
                    )
                    if not ok:
                        return False
                    continue
                ok = self._push_sub_write(
                    pg, osd, shard, e.oid, chunk, e.version,
                    e.to_list(), src_cid=my_cid, osize=size,
                )
                self.logger.inc("recovery_ops")
            else:
                # superseded modify / clean marker: log-entry-only replay
                ok = self._push_sub_write(
                    pg, osd, shard, e.oid, None, e.version, e.to_list()
                )
            if not ok:
                return False
        return True

    def _push_objects(self, pg, codec, acting, shard, osd,
                      newest: dict[str, int | None], deleted: set[str],
                      is_ec: bool) -> bool:
        """Backfill push: chunk data for every object, unversioned (the
        trimmed log cannot vouch for per-object versions); the final
        "clean" seal establishes the peer's version and empty log window.
        The push still carries the object size (osize) so the peer can
        answer stat/padding-strip."""
        for oid in sorted(deleted):
            if not self._push_sub_write(pg, osd, shard, oid, None, None, None):
                return False
        my_cid = self._cid(
            pg.pgid, acting.index(self.id) if is_ec else 0
        )
        all_ok = True
        for oid in sorted(newest, key=lambda o: (newest[o] or 0, o)):
            chunk, size = self._rebuild_shard_chunk(
                pg, codec, acting, oid, shard, is_ec
            )
            if chunk is None:
                # unfound: park this object, recover the rest (see
                # _push_log_delta); all_ok=False keeps the peer unsealed
                # so later ticks retry
                all_ok = False
                continue
            version = newest[oid]
            entry = [version or 0, "modify", oid]
            if not self._push_sub_write(
                pg, osd, shard, oid, chunk, version, entry, src_cid=my_cid,
                osize=size,
            ):
                all_ok = False
        return all_ok

    def _bump_peer_version(self, pg, shard, osd, version: int) -> None:
        """Final version/log sync after successful pushes: a data-less
        "clean" entry (ignored by missing_since) seals the peer at the
        primary's version."""
        tid = self._next_tid()
        try:
            self._conn_to_osd(osd).send_message(
                MECSubOpWrite(
                    tid=tid, pgid=pg.pgid, oid="", shard=shard,
                    data=None, crc=None, version=version,
                    entry=[version, "clean", ""],
                    epoch=self.my_epoch(),
                )
            )
            self._wait_reply(tid, timeout=5.0)
        except (OSError, ConnectionError):
            pass

    def _rebuild_shard_chunk(
        self, pg, codec, acting, oid: str, shard: int, is_ec: bool,
        exclude: set[int] | None = None,
    ) -> tuple[bytes | None, int]:
        """Recompute shard `shard`'s bytes for oid (reference:
        ECBackend::recover_object — read k chunks, re-encode).  `exclude`
        names additional shards whose data must not feed the rebuild
        (scrub-flagged rot)."""
        my_shard = acting.index(self.id)
        if not is_ec:
            try:
                data = self.store.read(self._cid(pg.pgid, 0), oid)
                return data, len(data)
            except (NotFound, KeyError):
                return None, 0
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        # include the DEST shard in the gather: the receiver lacks its
        # chunk, but the exact chunk may survive as a stray on a previous
        # holder (acting permutations) — using it directly also rescues
        # objects written degraded at exactly min_size, where fewer than
        # k OTHER chunks exist and decode alone could never recover
        want = set(range(n)) - (exclude or set())
        sizes: dict[int, int] = {}
        vers: dict[int, int | None] = {}
        floor = pg.log.obj_newest.get(oid)
        got = self._gather_chunks(pg, codec, acting, oid, want, sizes=sizes,
                                  vers=vers, stray=True, floor=floor)
        # never rebuild from a MIX of stripe generations, nor from one
        # the log proves is below the newest write
        got = _current_generation(got, vers, floor)
        if shard in got:
            try:
                size = int(self.store.getattr(
                    self._cid(pg.pgid, acting.index(self.id)), oid, "size"))
            except (NotFound, KeyError, ValueError):
                size = sizes.get(shard, next(iter(sizes.values()), 0))
            return bytes(got[shard]), size
        if len(got) < k:
            return None, 0
        try:
            size = int(self.store.getattr(
                self._cid(pg.pgid, my_shard), oid, "size"))
        except (NotFound, KeyError, ValueError):
            # our own xattr is gone (we may be the shard being repaired):
            # any healthy peer's size xattr is authoritative
            size = next(iter(sizes.values()), 0)
        chunks = {s: np.frombuffer(b, np.uint8) for s, b in got.items()}
        dec = codec.decode(
            {shard}, chunks, len(next(iter(chunks.values())))
        )
        return np.asarray(dec[shard], np.uint8).tobytes(), size
