"""Object metadata surfaces shared by both backends: user xattrs, omap, object classes, watch/notify (reference: PrimaryLogPG::do_osd_ops attr/omap/cls/watch cases).

Split out of osd/daemon.py (round-4 verdict item #6) — the methods
are verbatim; `OSD` composes every mixin, so cross-mixin calls (e.g.
the tier front-end invoking the replicated backend) resolve on self.
"""
from __future__ import annotations


import time

import numpy as np

from ..common.crc32c import crc32c
from ..common.tracer import TRACER, trace_now
from ..store.object_store import NotFound, Transaction
from .messages import (
    MECSubOpRead,
    MECSubOpWrite,
    MOSDOpReply,
    MPGQuery,
    MWatchNotify,
    pack_data,
    unpack_data,
)
from ..osd.osdmap import PG_POOL_ERASURE
from .pg import CLONE_SEP
from .pg_log import LogEntry


class ObjectOpsMixin:
    # .. user xattrs (both pool types) .....................................
    def _xattr_op(self, pg, acting, my_shard, msg) -> MOSDOpReply:
        """librados xattr surface (reference: rados_setxattr/getxattrs).
        User attrs live as `u_<name>` on every shard so any future primary
        answers; updates append a pg_log entry so recovery replays them."""
        cid = self._cid(pg.pgid, my_shard)
        if msg.op == "getxattrs":
            try:
                attrs = {
                    n[2:]: pack_data(v)
                    for n, v in self.store.getattrs(cid, msg.oid).items()
                    if n.startswith("u_")
                }
            except (NotFound, KeyError):
                # degraded primary (remap before recovery): any shard that
                # holds the object carries the same user xattrs
                attrs = self._probe_peer_xattrs(pg, acting, msg.oid)
                if attrs is None:
                    return MOSDOpReply(
                        tid=msg.tid, retval=-2, epoch=self.my_epoch(),
                        result="not found",
                    )
            return MOSDOpReply(
                tid=msg.tid, retval=0, epoch=self.my_epoch(), result=attrs
            )
        updates = msg.data or {}
        pool = self.osdmap.pools.get(pg.pool_id)
        # user-xattr content flushes to the base pool: a cache-pool user
        # setxattr re-dirties the object atomically (merged into the SAME
        # update set / sub-ops) and stamps `ver` so the flush's version
        # recheck also sees xattr-only mutations.  Tier-marker updates
        # (tier.*) are the dirty-tracking machinery itself and must not
        # self-trigger.
        user_mutation = any(not n.startswith("tier.") for n in updates)
        stamp_ver = False
        if (user_mutation and self._tier_autoclean(pool, msg.oid)
                and "tier.clean" not in updates):
            updates = dict(updates)
            updates["tier.clean"] = None
            stamp_ver = True
        with pg.lock:
            try:
                self.store.stat(cid, msg.oid)
            except (NotFound, KeyError):
                # no local copy: object missing cluster-wide (-2, final)
                # vs degraded primary pending recovery (-11, retryable)
                if self._probe_peer_xattrs(pg, acting, msg.oid) is None:
                    return MOSDOpReply(
                        tid=msg.tid, retval=-2, epoch=self.my_epoch(),
                        result="not found",
                    )
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result="object not recovered here yet",
                )
            version = pg.version + 1
            entry = LogEntry(version, "attr", msg.oid)
            tids: dict[int, int] = {}
            for shard, osd in enumerate(acting):
                if osd == self.id or osd < 0 or not self.osdmap.is_up(osd):
                    continue
                tid = self._next_tid()
                tids[tid] = shard
                try:
                    self._conn_to_osd(osd).send_message(
                        MECSubOpWrite(
                            tid=tid, pgid=pg.pgid, oid=msg.oid,
                            shard=shard if self._is_ec_pg(pg) else 0,
                            data=None, crc=None, version=version,
                            entry=entry.to_list(), epoch=self.my_epoch(),
                            xattrs=updates,
                        )
                    )
                except (OSError, ConnectionError):
                    tids.pop(tid, None)
            t = Transaction()
            self._apply_xattr_updates(t, cid, msg.oid, updates)
            if stamp_ver:
                t.setattr(cid, msg.oid, "ver", str(version).encode())
            self._log_txn(t, cid, pg, entry)
            self.store.queue_transaction(t)
            self._read_cache_invalidate(pg.pgid, msg.oid)
            a, deposed, _f = self._collect_subop_acks(tids)
            acked = 1 + a
        if deposed and (pool is None or acked < pool.min_size):
            return MOSDOpReply(tid=msg.tid, retval=-116,
                               epoch=self.my_epoch(),
                               result={"deposed": True})
        # same durability bar as write_full: the update must be on enough
        # shards to survive (reference: xattr ops ride the same repop)
        if pool is not None and acked < pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=-11,
                               epoch=self.my_epoch(),
                               result=f"only {acked} shard commits")
        return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                           result={"version": pg.version})

    def _apply_xattr_updates(self, t: Transaction, cid: str, oid: str,
                             updates: dict, snapshot: bool = False) -> None:
        """Apply user-xattr updates {name: b64|None} to a transaction;
        snapshot=True means `updates` is the complete set (recovery) and
        any other u_* attr must go."""
        try:
            existing = {
                n[2:] for n in self.store.getattrs(cid, oid)
                if n.startswith("u_")
            }
        except (NotFound, KeyError):
            existing = set()
        for name, val in updates.items():
            if val is None:
                if name in existing:
                    t.rmattr(cid, oid, f"u_{name}")
            else:
                t.setattr(cid, oid, f"u_{name}", unpack_data(val))
        if snapshot:
            for name in existing - set(updates):
                t.rmattr(cid, oid, f"u_{name}")

    def _probe_peer_xattrs(self, pg, acting, oid: str) -> dict | None:
        """User xattrs for oid from the FRESHEST up shard (degraded
        getxattrs).  Peers are ordered by their pg_log version so a
        just-revived stale shard cannot answer with pre-update attrs;
        metadata-only reads (offsets=[]) keep the object body off the
        wire."""
        is_ec = self._is_ec_pg(pg)
        peers = []  # (version, shard, osd)
        for shard, osd in enumerate(acting):
            if osd < 0 or osd == self.id or not self.osdmap.is_up(osd):
                continue
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(
                    MPGQuery(tid=tid, pgid=pg.pgid,
                             shard=shard if is_ec else 0,
                             epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=5.0)
            peers.append(
                ((rep.version if rep is not None else 0) or 0, shard, osd)
            )
        for _v, shard, osd in sorted(peers, reverse=True):
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpRead(
                        tid=tid, pgid=pg.pgid, oid=oid,
                        shard=shard if is_ec else 0,
                        offsets=[], epoch=self.my_epoch(),
                    )
                )
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=5.0)
            if rep is not None and rep.retval == 0:
                return rep.xattrs or {}
        return None

    def _is_ec_pg(self, pg) -> bool:
        pool = self.osdmap.pools.get(pg.pool_id) if self.osdmap else None
        return bool(pool and pool.type == PG_POOL_ERASURE)

    def _ec_write(self, pg, pool, codec, acting, my_shard, msg, data) -> MOSDOpReply:
        n = codec.get_chunk_count()
        # the parity matmul coalesces with concurrent ops' stripes in
        # the write batcher (ec_backend._ec_encode); everything after —
        # version assignment, sub-op fan-out, ack accounting — is
        # strictly per-op, so batching never changes semantics
        enc = self._ec_encode(codec, data)
        version = pg.version + 1
        # entry rides a 4th element (object size) so every shard can answer
        # size/stat even after the primary moves
        entry = LogEntry(version, "modify", msg.oid,
                         reqid=getattr(msg, "reqid", None))
        wire_entry = entry.to_list()
        tids: dict[int, int] = {}
        # subop span opens BEFORE the fan-out so each MECSubOpWrite can
        # carry its id as parent — the replica commit joins THIS node
        sub_span = TRACER.begin(self._op_trace_ctx(), "subop",
                                entity=self.whoami) if TRACER.enabled \
            else None
        t_sub0 = sub_span.t0 if sub_span is not None else trace_now()
        for shard, osd in enumerate(acting):
            if shard == my_shard or osd < 0:
                continue
            if not self.osdmap.is_up(osd):
                continue
            chunk = np.asarray(enc[shard], np.uint8).tobytes()
            tid = self._next_tid()
            tids[tid] = shard
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpWrite(
                        tid=tid, pgid=pg.pgid, oid=msg.oid, shard=shard,
                        data=pack_data(chunk), crc=crc32c(chunk),
                        version=version, entry=wire_entry,
                        epoch=self.my_epoch(), osize=len(data),
                        trace_id=(sub_span.trace_id
                                  if sub_span is not None else None),
                        parent_span=(sub_span.span_id
                                     if sub_span is not None else None),
                    )
                )
            except (OSError, ConnectionError):
                tids.pop(tid, None)
                self.mc.report_failure(osd)
        # local shard commit (chunk + log in one transaction)
        cid = self._cid(pg.pgid, my_shard)
        chunk = np.asarray(enc[my_shard], np.uint8).tobytes()
        t = Transaction()
        t.try_create_collection(cid)
        t.write(cid, msg.oid, 0, chunk)
        t.truncate(cid, msg.oid, len(chunk))
        t.setattr(cid, msg.oid, "hinfo", str(crc32c(chunk)).encode())
        t.setattr(cid, msg.oid, "size", str(len(data)).encode())
        t.setattr(cid, msg.oid, "ver", str(version).encode())
        self._log_txn(t, cid, pg, entry)
        t_c0 = trace_now()
        self.store.queue_transaction(t)
        self._read_cache_invalidate(pg.pgid, msg.oid)
        self._op_stage("commit", t_c0, trace_now(), version=version)
        a, deposed, failed = self._collect_subop_acks(tids, acting)
        self._op_stage("subop", t_sub0, trace_now(), span=sub_span,
                       fanout=len(tids), acked=a)
        acked = 1 + a
        for osd in failed:
            self.mc.report_failure(osd)
        if deposed and acked < pool.min_size:
            # deposed mid-op below quorum: the local apply is a FORK in a
            # dead interval — never acked, never answered as a dup
            # (_record_reqid marks the reqid "forked" so the resend
            # re-executes on the real primary).  At >= min_size the op
            # is durable in THIS interval despite the stray -116 (e.g. a
            # peer that just rebooted): ack it normally below.
            return MOSDOpReply(tid=msg.tid, retval=-116,
                               epoch=self.my_epoch(),
                               result={"deposed": True})
        # degraded-write policy: ack at min_size commits.  Shards that
        # missed the write are reported to the mon and filled by delta
        # recovery off the pg_log (reference: ECBackend requires min_size
        # acting shards; recovery completes the stripe)
        if acked >= pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"version": pg.version, "acked": acked})
        # structured under-ack refusal: the op IS applied+logged locally;
        # "applied" lets dup detection refuse re-execution on the resend
        return MOSDOpReply(tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                           result={"applied": pg.version, "acked": acked,
                                   "error": "below min_size commits"})

    # .. omap (replicated pools only, like the reference) ..................
    def _omap_op(self, pg, pool, acting, msg) -> MOSDOpReply:
        """librados omap surface (reference: rados_omap_get_vals /
        omap_set / omap_rm_keys / omap_clear, executed by
        PrimaryLogPG::do_osd_ops OMAP* cases).  Key-value pairs ride the
        object; mutations replicate and log exactly like xattr updates,
        and recovery pushes carry a full omap snapshot."""
        cid = self._cid(pg.pgid, 0)
        args = msg.data or {}
        if msg.op == "omap_get":
            try:
                self.store.stat(cid, msg.oid)
            except (NotFound, KeyError):
                return MOSDOpReply(tid=msg.tid, retval=-2,
                                   epoch=self.my_epoch(), result="not found")
            kv = self.store.omap_get(cid, msg.oid)
            want = args.get("keys")
            if want is not None:
                kv = {k: v for k, v in kv.items() if k in want}
            else:
                after = args.get("after") or ""
                maxn = int(args.get("max") or 0)
                keys = sorted(k for k in kv if k > after)
                if maxn:
                    keys = keys[:maxn]
                kv = {k: kv[k] for k in keys}
            return MOSDOpReply(
                tid=msg.tid, retval=0, epoch=self.my_epoch(),
                result={"kv": {k: pack_data(v) for k, v in kv.items()}},
            )
        # mutations
        omap_payload = None
        if msg.op == "omap_set":
            omap_payload = {"set": args.get("keys") or {}}
        elif msg.op == "omap_rm":
            omap_payload = {"rm": list(args.get("keys") or [])}
        elif msg.op == "omap_clear":
            omap_payload = {"clear": True}
        else:
            return MOSDOpReply(tid=msg.tid, retval=-22,
                               epoch=self.my_epoch(),
                               result=f"bad op {msg.op}")
        # omap content flushes to the base pool too: the clean clear must
        # be atomic with the mutation exactly like the data path
        autoclean = self._tier_autoclean(pool, msg.oid)
        with pg.lock:
            version = pg.version + 1
            entry = LogEntry(version, "modify", msg.oid,
                             reqid=getattr(msg, "reqid", None))
            tids: dict[int, int] = {}
            for shard, osd in enumerate(acting):
                if osd == self.id or osd < 0 or not self.osdmap.is_up(osd):
                    continue
                tid = self._next_tid()
                tids[tid] = shard
                try:
                    self._conn_to_osd(osd).send_message(MECSubOpWrite(
                        tid=tid, pgid=pg.pgid, oid=msg.oid, shard=0,
                        data=None, crc=None, version=version,
                        entry=entry.to_list(), epoch=self.my_epoch(),
                        omap=omap_payload,
                        rmattrs=["tier.clean"] if autoclean else None,
                    ))
                except (OSError, ConnectionError):
                    tids.pop(tid, None)
            t = Transaction()
            t.try_create_collection(cid)
            t.touch(cid, msg.oid)  # omap on a fresh oid creates it
            self._apply_omap(t, cid, msg.oid, omap_payload)
            # stamp the object version: _check_dup's applied-resend
            # verification counts shards holding ver >= v (replicated
            # pools never generation-filter reads, so this is safe)
            t.setattr(cid, msg.oid, "ver", str(version).encode())
            if autoclean:
                self._txn_clear_clean(t, cid, msg.oid)
            self._log_txn(t, cid, pg, entry)
            self.store.queue_transaction(t)
            a, deposed, _f = self._collect_subop_acks(tids)
            acked = 1 + a
        if deposed and acked < pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=-116,
                               epoch=self.my_epoch(),
                               result={"deposed": True})
        if acked < pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=-11,
                               epoch=self.my_epoch(),
                               result={"applied": pg.version, "acked": acked,
                                       "error": "below min_size commits"})
        return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                           result={"version": pg.version})

    # .. object classes (replicated pools only, like omap) .................
    def _exec_op(self, pg, pool, acting, msg) -> MOSDOpReply:
        """`rados exec` — run a registered class method at the primary
        under the PG lock and commit its staged mutations as one
        replicated, logged transaction (reference: PrimaryLogPG
        CEPH_OSD_OP_CALL -> ClassHandler; src/cls).  The lock-scoped
        execute-then-commit is what makes cls ops (bucket-index updates,
        create guards, counters) immune to concurrent-writer races."""
        from .classes import ClassRegistry, ClsHandle

        cid = self._cid(pg.pgid, 0)
        args = msg.data or {}
        fn = ClassRegistry.instance().get(
            args.get("cls", ""), args.get("method", "")
        )
        if fn is None:
            return MOSDOpReply(
                tid=msg.tid, retval=-95, epoch=self.my_epoch(),
                result=f"no class method "
                       f"{args.get('cls')}.{args.get('method')}",
            )
        # pool-snapshot clone-on-write, same as the plain mutation path
        # (lines above in _execute_routed_op): a method MAY stage a data
        # write (hctx.write_full), and the clone must capture the head
        # BEFORE pg.lock — the write path's order is _clone_mutex then
        # pg.lock, and inverting it here would risk deadlock.  We cannot
        # yet know whether the method will touch data, so clone whenever
        # a snap is live: a clone of an omap-only exec is merely the
        # head's (correct) at-snap state, never wrong.
        live_max = max(pool.snaps, default=0)
        snap_seq = max(live_max, int(getattr(msg, "snap_seq", 0) or 0))
        head_existed = True
        if snap_seq and msg.oid and CLONE_SEP not in msg.oid:
            try:
                head_existed = self._maybe_clone(pg, pool, msg.oid, snap_seq)
            except Exception as e:
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result=f"snap clone failed: {e}",
                )
        with pg.lock:
            def read_data():
                try:
                    return self.store.read(cid, msg.oid)
                except (NotFound, KeyError):
                    return None

            def read_omap():
                try:
                    return self.store.omap_get(cid, msg.oid)
                except (NotFound, KeyError):
                    return {}

            hctx = ClsHandle(msg.oid, read_data, read_omap)
            try:
                retval, out = fn(hctx, args.get("in") or {})
            except Exception as e:
                self.cct.dout("osd", 0,
                              f"{self.whoami} cls method raised: {e!r}")
                return MOSDOpReply(tid=msg.tid, retval=-22,
                                   epoch=self.my_epoch(),
                                   result=f"cls method failed: {e}")
            if retval < 0 or not hctx.dirty:
                # aborted or read-only: nothing to commit or replicate
                return MOSDOpReply(tid=msg.tid, retval=retval,
                                   epoch=self.my_epoch(),
                                   result={"cls_out": out})
            omap_payload = None
            if hctx.staged_set or hctx.staged_rm:
                omap_payload = {
                    "set": {k: pack_data(v)
                            for k, v in hctx.staged_set.items()},
                    "rm": sorted(hctx.staged_rm),
                }
            wire_data = crc = osize = None
            if hctx.staged_data is not None:
                wire_data = pack_data(hctx.staged_data)
                crc = crc32c(hctx.staged_data)
                osize = len(hctx.staged_data)
            version = pg.version + 1
            entry = LogEntry(version, "modify", msg.oid,
                             reqid=getattr(msg, "reqid", None))
            autoclean = self._tier_autoclean(pool, msg.oid)
            tids: dict[int, int] = {}
            for shard, osd in enumerate(acting):
                if osd == self.id or osd < 0 or not self.osdmap.is_up(osd):
                    continue
                tid = self._next_tid()
                tids[tid] = shard
                try:
                    self._conn_to_osd(osd).send_message(MECSubOpWrite(
                        tid=tid, pgid=pg.pgid, oid=msg.oid, shard=0,
                        data=wire_data, crc=crc, osize=osize,
                        version=version, entry=entry.to_list(),
                        epoch=self.my_epoch(), omap=omap_payload,
                        rmattrs=["tier.clean"] if autoclean else None,
                    ))
                except (OSError, ConnectionError):
                    tids.pop(tid, None)
            t = Transaction()
            t.try_create_collection(cid)
            t.touch(cid, msg.oid)
            if hctx.staged_data is not None:
                t.write(cid, msg.oid, 0, hctx.staged_data)
                t.truncate(cid, msg.oid, len(hctx.staged_data))
                t.setattr(cid, msg.oid, "hinfo",
                          str(crc32c(hctx.staged_data)).encode())
                t.setattr(cid, msg.oid, "size",
                          str(len(hctx.staged_data)).encode())
            if omap_payload is not None:
                self._apply_omap(t, cid, msg.oid, omap_payload)
            t.setattr(cid, msg.oid, "ver", str(version).encode())
            if autoclean:
                self._txn_clear_clean(t, cid, msg.oid)
            self._log_txn(t, cid, pg, entry)
            self.store.queue_transaction(t)
            a, deposed, _f = self._collect_subop_acks(tids)
            acked = 1 + a
        if deposed and acked < pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=-116,
                               epoch=self.my_epoch(),
                               result={"deposed": True})
        if acked < pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=-11,
                               epoch=self.my_epoch(),
                               result={"applied": pg.version, "acked": acked,
                                       "error": "below min_size commits"})
        if snap_seq and not head_existed:
            # exec CREATED the object post-snap: mark it born so older
            # snap views keep it invisible (same contract as the plain
            # write path's _mark_born)
            try:
                self._mark_born(pg, pool, msg.oid, snap_seq)
            except Exception as e:
                return MOSDOpReply(
                    tid=msg.tid, retval=-5, epoch=self.my_epoch(),
                    result=f"snapborn mark failed: {e}",
                )
        return MOSDOpReply(tid=msg.tid, retval=retval,
                           epoch=self.my_epoch(), result={"cls_out": out})

    def _apply_omap(self, t: Transaction, cid: str, oid: str,
                    payload: dict) -> None:
        if payload.get("snapshot") is not None:
            # recovery push: the dict IS the whole omap
            t.omap_clear(cid, oid)
            t.omap_setkeys(cid, oid, {
                k: unpack_data(v) for k, v in payload["snapshot"].items()
            })
            return
        if payload.get("clear"):
            t.omap_clear(cid, oid)
        if payload.get("set"):
            t.omap_setkeys(cid, oid, {
                k: unpack_data(v) for k, v in payload["set"].items()
            })
        if payload.get("rm"):
            t.omap_rmkeys(cid, oid, payload["rm"])

    # .. watch / notify ....................................................
    def _watch_op(self, pg, pool, msg) -> MOSDOpReply:
        """Object watch/notify (reference: PrimaryLogPG watch/notify +
        MWatchNotify).  Watch state is primary-local and in-memory; the
        client's Objecter re-registers lingering watches after a map
        change (reference: linger ops re-sent by Objecter), which covers
        primary failover."""
        args = msg.data or {}
        key = (msg.pool, msg.oid)
        if msg.op == "watch":
            cookie = int(args.get("cookie") or 0)
            with self._watch_lock:
                self.watchers.setdefault(key, {})[cookie] = (
                    getattr(msg, "src", None))
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"cookie": cookie})
        if msg.op == "unwatch":
            cookie = int(args.get("cookie") or 0)
            with self._watch_lock:
                ws = self.watchers.get(key, {})
                ws.pop(cookie, None)
                if not ws:
                    self.watchers.pop(key, None)
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={})
        # notify: fan out to every watcher, collect acks with a timeout
        notify_id = self._next_tid()
        payload = args.get("payload")
        timeout = float(args.get("timeout") or 5.0)
        with self._watch_lock:
            targets = dict(self.watchers.get(key, {}))
        pending = {}
        dead = []
        unreachable = []
        for cookie, src in targets.items():
            conn = self._client_conns.get(src)
            if conn is None:
                # conn LRU-evicted or never seen: the watcher may be
                # alive and idle — report it missed, do NOT reap (only a
                # CONFIRMED-dead connection expires a watch)
                unreachable.append(cookie)
                continue
            try:
                conn.send_message(MWatchNotify(
                    notify_id=notify_id, pool=msg.pool, oid=msg.oid,
                    cookie=cookie, data=payload,
                ))
                pending[cookie] = src
            except (OSError, ConnectionError):
                dead.append(cookie)
        if dead:
            # a watcher whose connection is gone is expired (reference:
            # watch timeout reaps dead watchers); its client re-lingers
            # on the next map push if it is actually alive
            with self._watch_lock:
                ws = self.watchers.get(key, {})
                for cookie in dead:
                    ws.pop(cookie, None)
                if not ws:
                    self.watchers.pop(key, None)
        acked, missed = [], list(unreachable)
        deadline = time.monotonic() + timeout
        for cookie in pending:
            remain = max(0.0, deadline - time.monotonic())
            if self._wait_notify_ack(notify_id, cookie, remain):
                acked.append(cookie)
            else:
                missed.append(cookie)
        return MOSDOpReply(
            tid=msg.tid, retval=0, epoch=self.my_epoch(),
            result={"notify_id": notify_id, "acked": acked,
                    "missed": missed},
        )

    def _wait_notify_ack(self, notify_id: int, cookie: int,
                         timeout: float) -> bool:
        with self._watch_cond:
            return self._watch_cond.wait_for(
                lambda: (notify_id, cookie) in self._notify_acks,
                timeout=timeout,
            )

