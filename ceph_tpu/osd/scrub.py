"""Scrub orchestration and shard digests (reference: src/osd/scrubber, deep-scrub subset).

Split out of osd/daemon.py (round-4 verdict item #6) — the methods
are verbatim; `OSD` composes every mixin, so cross-mixin calls (e.g.
the tier front-end invoking the replicated backend) resolve on self.
"""
from __future__ import annotations




from ..common.crc32c import crc32c
from ..common.failpoint import FailpointCrash, FailpointError, failpoint
from ..common.tracer import TRACER, op_trace, set_op_trace, trace_now
from ..store.object_store import NotFound, Transaction
from .messages import (
    MECSubOpRead,
    MScrubShard,
    MScrubShardReply,
    unpack_data,
)
from ..osd.osdmap import PG_POOL_ERASURE


class ScrubMixin:
    # -- scrub (reference: src/osd/scrubber — deep scrub subset) ----------
    def _local_scrub_map(self, cid: str) -> dict:
        """ScrubMap of one shard collection: oid -> [computed_crc,
        stored_crc_or_None, size] (reference: PGBackend::be_scan_list)."""
        objects: dict[str, list] = {}
        try:
            oids = self.store.list_objects(cid)
        except (NotFound, KeyError):
            return objects
        for oid in oids:
            if oid.startswith("_"):
                continue
            try:
                data = self.store.read(cid, oid)
            except (NotFound, KeyError):
                continue
            try:
                stored = int(self.store.getattr(cid, oid, "hinfo"))
            except (NotFound, KeyError, ValueError):
                stored = None
            objects[oid] = [crc32c(data), stored, len(data)]
        return objects

    def _replicated_authoritative(
        self, pg, maps: dict, acting: list[int], oid: str, bad_shard: int
    ) -> tuple[bytes | None, int]:
        """Authoritative copy for a replicated repair: any replica whose
        scrub entry is self-consistent (computed == stored digest), the
        primary's preferred (reference: be_select_auth_object)."""
        candidates = sorted(
            maps,
            key=lambda s: (acting[s] != self.id, s),  # self first
        )
        for s in candidates:
            if s == bad_shard:
                continue
            ent = maps[s].get(oid)
            if ent is None or (ent[1] is not None and ent[0] != ent[1]):
                continue
            osd = acting[s]
            if osd == self.id:
                try:
                    data = self.store.read(self._cid(pg.pgid, 0), oid)
                    return bytes(data), len(data)
                except (NotFound, KeyError):
                    continue
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpRead(tid=tid, pgid=pg.pgid, oid=oid, shard=0,
                                 offsets=None, epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=5.0)
            if rep is not None and rep.retval == 0:
                data = unpack_data(rep.data)
                return data, len(data)
        return None, 0

    def _handle_scrub_shard(self, conn, msg: MScrubShard) -> None:
        try:
            # "osd.scrub.shard": an error action makes this shard go
            # silent — the primary scrubs with the maps it can gather
            failpoint("osd.scrub.shard", cct=self.cct,
                      entity=self.whoami, pgid=msg.pgid, shard=msg.shard)
        except FailpointCrash:
            raise
        except FailpointError:
            return
        try:
            conn.send_message(
                MScrubShardReply(
                    tid=msg.tid, pgid=msg.pgid, shard=msg.shard,
                    objects=self._local_scrub_map(
                        self._cid(msg.pgid, msg.shard)
                    ),
                )
            )
        except (OSError, ConnectionError):
            pass

    def scrub_pg(self, pool_id: int, ps: int, repair: bool = True) -> dict:
        """cephheal wrapper around _scrub_pg_inner: one scrub = one
        traceable, TrackedOp-registered background op (src="scrub"),
        with the same head-coin-flip + tail-provisional trace contract
        client ops get — a slow scrub keeps its tree at sampling=0 and
        shows up in dump_historic_slow_ops."""
        # "osd.scrub.start": error aborts the scrub before any shard map
        # is collected; delay stretches the scrub window
        failpoint("osd.scrub.start", cct=self.cct, entity=self.whoami,
                  pgid=f"{pool_id}.{ps}")
        ctx = self._bg_trace_ctx()
        root = None
        if ctx is not None:
            root = TRACER.begin(ctx, "scrub", entity=self.whoami,
                                pgid=f"{pool_id}.{ps}", repair=repair)
        tracked = self.op_tracker.create(
            f"scrub({pool_id}.{ps})", src="scrub")
        tracked.trace_id = ctx.trace_id if ctx is not None else None
        # save/restore the op-trace state: a scrub driven through the
        # client `scrub` op runs on an op thread that already carries
        # the client op's state
        prev = op_trace()
        set_op_trace({
            "ctx": root.ctx() if root is not None else ctx,
            "tracked": tracked,
        })
        try:
            result = self._scrub_pg_inner(pool_id, ps, repair)
            TRACER.end(root, errors=len(result.get("errors") or ()),
                       repaired=result.get("repaired", 0))
            root = None
            return result
        finally:
            set_op_trace(prev)
            TRACER.end(root)  # error path: close unconditionally
            tracked.finish()
            if TRACER.enabled and tracked.trace_id is not None:
                self._bg_tail_verdict(tracked)

    def _scrub_pg_inner(self, pool_id: int, ps: int,
                        repair: bool = True) -> dict:
        """Deep scrub one PG from its primary: collect every shard's
        ScrubMap, flag shards whose at-rest bytes rotted under their own
        digest or that miss objects others hold, and (repair=True) rebuild
        those shards from the surviving ones (reference:
        PrimaryLogPG::scrub_compare_maps + repair_object)."""
        m = self.osdmap
        pool = m.pools.get(pool_id) if m else None
        if pool is None:
            raise KeyError(f"no pool {pool_id}")
        acting, primary = self._acting(pool_id, ps)
        if primary != self.id:
            raise RuntimeError(f"not primary for {pool_id}.{ps}")
        pg = self._pg(pool_id, ps)
        is_ec = pool.type == PG_POOL_ERASURE
        codec = self._codec_for_pool(pool) if is_ec else None
        # map collection runs UNLOCKED (writes proceed; a racing write can
        # only produce a false positive whose "repair" re-pushes current,
        # consistent bytes).  pg.lock is taken per-object for repairs, so
        # a slow shard never blocks client I/O for the whole scrub.
        t_read0 = trace_now()
        maps: dict[int, dict] = {}
        tids: dict[int, int] = {}
        for shard, osd in enumerate(acting):
            store_shard = shard if is_ec else 0
            if osd < 0 or not m.is_up(osd):
                continue
            if osd == self.id:
                maps[shard] = self._local_scrub_map(
                    self._cid(pg.pgid, store_shard)
                )
                continue
            tid = self._next_tid()
            tids[tid] = shard
            try:
                self._conn_to_osd(osd).send_message(
                    MScrubShard(tid=tid, pgid=pg.pgid, shard=store_shard,
                                epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                tids.pop(tid, None)
        for tid, shard in tids.items():
            rep = self._wait_reply(tid, timeout=10.0)
            if rep is not None:
                maps[shard] = rep.objects or {}
        self._bg_stage("scrub_read", t_read0, trace_now(),
                       shards=len(maps))

        t_cmp0 = trace_now()
        all_oids: set[str] = set()
        for sm in maps.values():
            all_oids |= set(sm)
        # objects whose FINAL log entry is a delete: a shard still holding
        # one is stale (its delete sub-op was lost) — flag the holder, and
        # never let "missing" on up-to-date shards resurrect the object
        _newest, log_deleted = pg.log.missing_since(0)
        my_shard = next((s for s in maps if acting[s] == self.id), None)
        errors: list[dict] = []
        for oid in sorted(all_oids):
            if oid in log_deleted:
                for shard, sm in maps.items():
                    if oid in sm:
                        errors.append(
                            {"oid": oid, "shard": shard,
                             "error": "stale_deleted"}
                        )
                continue
            # authoritative digest for cross-copy comparison (replicated):
            # a SELF-CONSISTENT copy, the primary's preferred (reference:
            # be_select_auth_object) — never a copy that fails its own
            # digest, so primary bit-rot cannot propagate
            auth_crc = None
            if not is_ec:
                order = sorted(
                    maps, key=lambda s: (s != my_shard, s)
                )
                for s in order:
                    ent = maps[s].get(oid)
                    if ent is None:
                        continue
                    if ent[1] is None or ent[0] == ent[1]:
                        auth_crc = ent[0]
                        break
            for shard, sm in maps.items():
                ent = sm.get(oid)
                if ent is None:
                    errors.append(
                        {"oid": oid, "shard": shard, "error": "missing"}
                    )
                elif ent[1] is not None and ent[0] != ent[1]:
                    # at-rest rot under the shard's own digest (EC chunks
                    # and, with hinfo now stamped everywhere, replicas)
                    errors.append(
                        {"oid": oid, "shard": shard,
                         "error": "data_digest_mismatch"}
                    )
                elif (
                    not is_ec
                    and auth_crc is not None
                    and ent[0] != auth_crc
                ):
                    errors.append(
                        {"oid": oid, "shard": shard,
                         "error": "data_digest_mismatch"}
                    )
            self.logger.inc("scrubs")
            self.logger.inc("scrub_errors", len(errors))
        self._bg_stage("scrub_compare", t_cmp0, trace_now(),
                       objects=len(all_oids), errors=len(errors))
        repaired = 0
        if repair and errors:
            t_rep0 = trace_now()
            # shards known-bad per oid: their chunks must not feed a
            # rebuild (decoding from a rotted chunk would launder the
            # corruption into a fresh self-consistent digest)
            bad_by_oid: dict[str, set[int]] = {}
            for err in errors:
                bad_by_oid.setdefault(err["oid"], set()).add(err["shard"])
            for err in errors:
                shard = err["shard"]
                osd = acting[shard]
                store_shard = shard if is_ec else 0
                with pg.lock:  # per-object: writes proceed between repairs
                    if err["error"] == "stale_deleted":
                        if osd == self.id:
                            cid = self._cid(pg.pgid, store_shard)
                            t = Transaction()
                            try:
                                self.store.stat(cid, err["oid"])
                                t.remove(cid, err["oid"])
                                self.store.queue_transaction(t)
                                repaired += 1
                            except (NotFound, KeyError):
                                pass
                        elif self._push_sub_write(
                            pg, osd, store_shard, err["oid"], None, None,
                            None,
                        ):
                            repaired += 1
                        continue
                    if is_ec:
                        chunk, size = self._rebuild_shard_chunk(
                            pg, codec, acting, err["oid"], shard, True,
                            exclude=bad_by_oid.get(err["oid"], set()),
                        )
                    else:
                        chunk, size = self._replicated_authoritative(
                            pg, maps, acting, err["oid"], bad_shard=shard
                        )
                    if chunk is None:
                        continue
                    if osd == self.id:
                        cid = self._cid(pg.pgid, store_shard)
                        t = Transaction()
                        t.try_create_collection(cid)
                        t.write(cid, err["oid"], 0, chunk)
                        t.truncate(cid, err["oid"], len(chunk))
                        t.setattr(cid, err["oid"], "hinfo",
                                  str(crc32c(chunk)).encode())
                        t.setattr(cid, err["oid"], "size",
                                  str(size).encode())
                        self.store.queue_transaction(t)
                        repaired += 1
                    elif self._push_sub_write(
                        pg, osd, store_shard, err["oid"], chunk, None,
                        [0, "modify", err["oid"]], osize=size,
                        src_cid=self._cid(
                            pg.pgid,
                            acting.index(self.id) if is_ec else 0),
                    ):
                        repaired += 1
            self.logger.inc("scrub_repairs", repaired)
            self._bg_stage("scrub_repair", t_rep0, trace_now(),
                           repaired=repaired, errors=len(errors))
        return {
            "pgid": pg.pgid,
            "shards": len(maps),
            "objects": len(all_oids),
            "errors": errors,
            "repaired": repaired if repair else 0,
        }

