"""mClock op scheduler — QoS-tagged dispatch (reference:
src/osd/scheduler/mClockScheduler.{h,cc} wrapping the dmclock library;
SURVEY.md §2.3).

Each op class holds (reservation, weight, limit) in ops/sec.  Ops get
three tags at enqueue:

    R = max(now, prev_R + 1/reservation)   # guaranteed minimum
    L = max(now, prev_L + 1/limit)         # hard ceiling
    P = max(prev_P, now) + 1/weight        # proportional share

Dequeue (mClock's two phases): first any class whose R tag is due — pick
the earliest R (reservations are guarantees, served before everything);
otherwise among classes whose L tag is due pick the earliest P tag
(weighted fair sharing under the ceiling).  If nothing is eligible the
caller sleeps until the earliest tag matures — including the RESERVATION
tag of a limit-gated class, since a due reservation is served regardless
of the ceiling (a limit-gated class's wake used to consider only its L
tag, so reservations were honored only at the caller's poll cadence).

The OSD instantiates the reference's three classes — client,
background_recovery, background_scrub — and (cephqos) grows the client
side DYNAMICALLY: one class per (client entity, pool) identity, keyed by
the cephmeter accounting labels.  Dynamic classes are bounded
(``max_dynamic``): registering one past the bound retires the
least-recently-enqueued dynamic class into the ``_default_`` catch-all —
its queued ops are spliced into ``_default_`` in arrival order and its
served/wait stats fold into a ``_retired_`` aggregate, so work and
counts are conserved, only per-client attribution is lost (the same
fold rule as the accounting table's ``_other_``).  A retired client
that returns simply re-registers with fresh tags (dmclock's idle-client
tag reset).  The mgr's QoS controller retunes per-class params at
runtime via :meth:`set_params` (docs/qos.md).

Observability: every class keeps queue depth, served-op count, and a
log2 wait histogram (enqueue -> dequeue).  :class:`SchedulerPerf`
duck-types ``PerfCounters`` so one ``cct.perf.add`` exports the rows as
labeled prometheus series (``ceph_mclock_*{qclass=...}``) through the
existing perf dump -> MMgrReport pipeline, with the exposition-time
``_fold_labeled_rows`` cardinality guard — exactly the cephmeter
precedent.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from ..common.io_accounting import _hist_add, _hist_merge, _new_hist
from ..common.lockdep import make_lock

#: the catch-all class retired dynamic clients fold into (and the class
#: ops of an unknown dynamic identity land in)
DEFAULT_CLASS = "_default_"
#: the labeled row every retired class's stats fold into
RETIRED_KEY = "_retired_"


@dataclass(frozen=True)
class QoSParams:
    """reference: dmclock ClientInfo (reservation, weight, limit);
    0 = none (no floor / no ceiling)."""

    reservation: float = 0.0
    weight: float = 1.0
    limit: float = 0.0


@dataclass
class _ClassState:
    params: QoSParams
    queue: list = field(default_factory=list)  # FIFO of (seq, enq_ts, item)
    r_tag: float = 0.0
    p_tag: float = 0.0
    l_tag: float = 0.0
    dynamic: bool = False
    served: int = 0
    wait: dict = field(default_factory=_new_hist)  # enqueue->dequeue seconds


class MClockScheduler:
    def __init__(self, classes: dict[str, QoSParams],
                 clock=time.monotonic, max_dynamic: int = 0,
                 dynamic_params: QoSParams | None = None,
                 client_slots: int = 0):
        """``classes`` are the static classes (never retired).  When
        ``max_dynamic`` > 0 the per-client side is armed: a
        ``_default_`` catch-all is created and :meth:`client_class`
        registers/touches per-client classes under the bound.

        ``client_slots`` (> 0) bounds concurrent DYNAMIC-class op
        executions: a dynamic pick takes a slot ATOMICALLY with the
        dequeue (under the scheduler lock — no double-grant between
        two workers), and while all slots are busy dynamic classes
        are ineligible, so mClock's tags decide who runs next when
        the daemon is saturated — without the bound, an unbounded
        execution pool drains the queue instantly and the tags order
        nothing.  The executor MUST call :meth:`client_op_done` when
        a dynamic-class op finishes.  Static classes (background
        work, the internal "client" class forwarded OSD-to-OSD ops
        ride) are exempt, which keeps cross-OSD op forwarding
        deadlock-free.  0 = unbounded."""
        self._classes = {
            name: _ClassState(params) for name, params in classes.items()
        }
        self._clock = clock
        self._seq = 0
        self._lock = make_lock("osd::mclock")
        self._cond = threading.Condition(self._lock)
        self._stopped = False
        self.client_slots = max(0, int(client_slots))
        self._slots_busy = 0  # dynamic-class ops executing, under _lock
        self.max_dynamic = max(0, int(max_dynamic))
        self._dynamic_params = dynamic_params or QoSParams(weight=1.0)
        # LRU over dynamic classes: key -> None, oldest-touched first
        self._lru: OrderedDict[str, None] = OrderedDict()
        self._retired = 0
        self._retired_served = 0
        self._retired_wait = _new_hist()
        if self.max_dynamic > 0:
            st = _ClassState(self._dynamic_params)
            st.dynamic = True  # catch-all renders with the dynamic rows
            self._classes[DEFAULT_CLASS] = st

    # -- dynamic per-client classes (cephqos) -------------------------------
    def client_class(self, key: str) -> str:
        """Class name to enqueue a client op under: registers ``key`` as
        a dynamic class (LRU-retiring past the bound) and touches its
        LRU slot.  With the dynamic side unarmed returns the key's
        class only if it already exists statically, else ``client``."""
        with self._lock:
            if self.max_dynamic <= 0:
                return "client" if "client" in self._classes else key
            st = self._classes.get(key)
            if st is not None and st.dynamic and key != DEFAULT_CLASS:
                self._lru.move_to_end(key)
                return key
            if st is not None:
                return key  # a static name: never dynamic-register it
            self._register_dynamic_locked(key, self._dynamic_params)
            return key

    def _register_dynamic_locked(self, key: str, params: QoSParams) -> None:
        while len(self._lru) >= self.max_dynamic:
            # cephstorm: retiring the raw LRU head evicted classes with
            # QUEUED ops while idle (empty-queue) classes survived —
            # under hundreds of identities every eviction spliced live
            # work into _default_ and unattributed it (retirement
            # thrash).  Prefer the oldest-touched EMPTY class; only when
            # every dynamic class holds work does the true LRU head go.
            victim = next(
                (k for k in self._lru if not self._classes[k].queue),
                None,
            )
            self._retire_locked(
                victim if victim is not None else next(iter(self._lru)))
        st = _ClassState(params)
        st.dynamic = True
        now = self._clock()
        st.r_tag = st.p_tag = st.l_tag = now  # fresh cadence (idle reset)
        self._classes[key] = st
        self._lru[key] = None

    def _retire_locked(self, key: str) -> None:
        """Fold one dynamic class into the catch-all: queued ops splice
        into ``_default_`` in arrival (seq) order, stats fold into the
        ``_retired_`` aggregate — work and counts are conserved."""
        st = self._classes.pop(key)
        self._lru.pop(key, None)
        dflt = self._classes[DEFAULT_CLASS]
        if st.queue:
            was_empty = not dflt.queue
            dflt.queue = sorted(dflt.queue + st.queue)
            if was_empty:
                self._idle_reset_locked(dflt, self._clock())
            self._cond.notify_all()
        self._retired += 1
        self._retired_served += st.served
        _hist_merge(self._retired_wait, st.wait)

    def set_params(self, name: str, params: QoSParams,
                   register: bool = True) -> bool:
        """Retune one class's (reservation, weight, limit) — the QoS
        controller's scheduler-side knob.  With ``register`` (the
        default), unknown names register as dynamic classes (bounded,
        LRU like client_class); the OSD's controller-push handler
        passes ``register=False`` because the controller fans the SAME
        class map to every OSD — registering identities this OSD never
        serves would LRU-thrash its genuinely active classes.  Tags
        reset to now: a class whose old params left far-future tags
        must pick up the new cadence immediately, not after the stale
        tags drain."""
        if params.weight <= 0:
            raise ValueError(f"class {name!r}: weight must be > 0")
        with self._lock:
            st = self._classes.get(name)
            if st is None:
                if not register or self.max_dynamic <= 0:
                    return False
                self._register_dynamic_locked(name, params)
                return True
            st.params = params
            now = self._clock()
            st.r_tag = st.p_tag = st.l_tag = now
            self._cond.notify_all()
            return True

    @staticmethod
    def _idle_reset_locked(st: _ClassState, now: float) -> None:
        """A class going non-empty resets its cadence to "now" (dmclock's
        idle-client tag reset) — tags advance per dequeue otherwise."""
        p = st.params
        if p.reservation:
            st.r_tag = max(st.r_tag, now)
        if p.limit:
            st.l_tag = max(st.l_tag, now)
        st.p_tag = max(st.p_tag, now)

    # -- producer ----------------------------------------------------------
    def enqueue(self, cls: str, item) -> None:
        now = self._clock()
        with self._lock:
            st = self._classes.get(cls)
            if st is None:
                if self.max_dynamic > 0:
                    # a class retired between client_class() and here
                    # (or a controller-side name): the catch-all takes it
                    st = self._classes[DEFAULT_CLASS]
                else:
                    raise KeyError(cls)
            empty = not st.queue
            self._seq += 1
            st.queue.append((self._seq, now, item))
            if empty:
                self._idle_reset_locked(st, now)
            self._cond.notify()

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._cond.notify_all()

    def client_op_done(self) -> None:
        """Return a dynamic-class op's execution slot (the executor's
        half of the ``client_slots`` contract) and wake the sleeper so
        gated classes re-enter eligibility."""
        with self._lock:
            if self._slots_busy > 0:
                self._slots_busy -= 1
            self._cond.notify_all()

    # -- consumer ----------------------------------------------------------
    def _pick_locked(self, now: float):
        """(cls, item) of the next eligible op, or (None, wake_at)."""
        best_r = None  # (r_tag, name)
        best_p = None  # (p_tag, name)
        wake = None
        gate_open = (self.client_slots <= 0
                     or self._slots_busy < self.client_slots)
        for name, st in self._classes.items():
            if not st.queue:
                continue
            if st.dynamic and not gate_open:
                # client-op slots exhausted: dynamic classes wait for a
                # client_op_done() wakeup; background/static stay
                # eligible
                continue
            p = st.params
            if p.reservation and st.r_tag <= now:
                if best_r is None or st.r_tag < best_r[0]:
                    best_r = (st.r_tag, name)
                continue  # reservation-phase candidates skip P
            if p.limit and st.l_tag > now:
                wake = st.l_tag if wake is None else min(wake, st.l_tag)
                if p.reservation:
                    # a due reservation beats the ceiling (the R branch
                    # above ignores limit), so the sleeper must wake at
                    # r_tag too — else reservations of limit-gated
                    # classes are honored only at the poll cadence
                    wake = min(wake, st.r_tag)
                continue
            if best_p is None or st.p_tag < best_p[0]:
                best_p = (st.p_tag, name)
            if p.reservation:
                wake = st.r_tag if wake is None else min(wake, st.r_tag)
        name = best_r[1] if best_r is not None else (
            best_p[1] if best_p is not None else None
        )
        if name is None:
            return None, wake
        st = self._classes[name]
        _, enq_ts, item = st.queue.pop(0)
        st.served += 1
        _hist_add(st.wait, max(0.0, now - enq_ts))
        if st.dynamic and self.client_slots > 0:
            # slot taken atomically with the pick (no worker race)
            self._slots_busy += 1
        p = st.params
        if p.reservation:
            st.r_tag = max(now, st.r_tag) + 1.0 / p.reservation
        if p.limit:
            st.l_tag = max(now, st.l_tag) + 1.0 / p.limit
        st.p_tag = max(now, st.p_tag) + 1.0 / p.weight
        return (name, item), None

    def dequeue(self, timeout: float | None = None):
        """Blocking pop -> (class, item) or None on stop/timeout."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._lock:
            while not self._stopped:
                picked, wake = self._pick_locked(self._clock())
                if picked is not None:
                    return picked
                now = self._clock()
                waits = [w - now for w in (wake,) if w is not None]
                if deadline is not None:
                    if now >= deadline:
                        return None
                    waits.append(deadline - now)
                self._cond.wait(timeout=min(waits) if waits else None)
            return None

    def qlen(self) -> int:
        with self._lock:
            return sum(len(st.queue) for st in self._classes.values())

    # -- introspection (dump_op_queue / SchedulerPerf) ----------------------
    def dump(self) -> dict:
        """Per-class snapshot: depth, served, wait histogram, params —
        the ``dump_op_queue`` admin command's payload."""
        with self._lock:
            classes = {}
            for name, st in self._classes.items():
                classes[name] = {
                    "depth": len(st.queue),
                    "served": st.served,
                    "dynamic": st.dynamic,
                    "reservation": st.params.reservation,
                    "weight": st.params.weight,
                    "limit": st.params.limit,
                    "wait": {"count": st.wait["count"],
                             "sum": st.wait["sum"],
                             "buckets": list(st.wait["buckets"])},
                }
            return {
                "classes": classes,
                "dynamic_classes": len(self._lru),
                "max_dynamic": self.max_dynamic,
                "client_slots": self.client_slots,
                "slots_busy": self._slots_busy,
                "retired": self._retired,
                "retired_served": self._retired_served,
                "retired_wait": {
                    "count": self._retired_wait["count"],
                    "sum": self._retired_wait["sum"],
                    "buckets": list(self._retired_wait["buckets"]),
                },
            }


class SchedulerPerf:
    """PerfCounters duck type over one scheduler's per-class stats:
    ``cct.perf.add(SchedulerPerf(sched))`` rides the labeled-rows
    branch of the perf dump -> MMgrReport -> prometheus pipeline
    (``ceph_mclock_depth{ceph_daemon,qclass}`` and friends), bounded by
    max_dynamic here and ``_fold_labeled_rows`` at exposition."""

    def __init__(self, sched: MClockScheduler, name: str = "mclock"):
        self.name = name
        self._sched = sched

    def dump(self) -> dict:
        snap = self._sched.dump()
        rows = []
        for cname, c in sorted(snap["classes"].items()):
            rows.append({
                "labels": {"qclass": cname},
                "depth": c["depth"],
                "served": c["served"],
                "reservation": c["reservation"],
                "weight": c["weight"],
                "limit": c["limit"],
                "wait": c["wait"],
            })
        if snap["retired_served"] or snap["retired"]:
            rows.append({
                "labels": {"qclass": RETIRED_KEY},
                "depth": 0,
                "served": snap["retired_served"],
                "reservation": 0.0, "weight": 0.0, "limit": 0.0,
                "wait": snap["retired_wait"],
            })
        return {
            "per_class": {"__labeled__": True, "rows": rows},
            "queue_len": sum(
                c["depth"] for c in snap["classes"].values()),
            "dynamic_classes": snap["dynamic_classes"],
            "retired": snap["retired"],
        }

    def schema(self) -> dict:
        return {
            "per_class": {
                "type": "labeled",
                "description": "per-QoS-class mClock scheduler rows "
                               "(bounded dynamic classes + _retired_ "
                               "fold; docs/qos.md)"},
            "depth": {"type": "gauge",
                      "description": "ops queued in this QoS class"},
            "served": {"type": "u64",
                       "description": "ops dequeued from this QoS class"},
            "reservation": {"type": "gauge",
                            "description": "class reservation (ops/s; "
                                           "0 = no floor)"},
            "weight": {"type": "gauge",
                       "description": "class proportional-share weight"},
            "limit": {"type": "gauge",
                      "description": "class limit (ops/s; 0 = no "
                                     "ceiling)"},
            "wait": {"type": "histogram",
                     "description": "enqueue -> dequeue wait per class"},
            "queue_len": {"type": "gauge",
                          "description": "total ops queued across "
                                         "classes"},
            "dynamic_classes": {"type": "gauge",
                                "description": "live per-client QoS "
                                               "classes"},
            "retired": {"type": "u64",
                        "description": "dynamic classes LRU-folded into "
                                       "_default_/_retired_"},
        }
