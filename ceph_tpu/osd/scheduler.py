"""mClock op scheduler — QoS-tagged dispatch (reference:
src/osd/scheduler/mClockScheduler.{h,cc} wrapping the dmclock library;
SURVEY.md §2.3).

Each op class holds (reservation, weight, limit) in ops/sec.  Ops get
three tags at enqueue:

    R = max(now, prev_R + 1/reservation)   # guaranteed minimum
    L = max(now, prev_L + 1/limit)         # hard ceiling
    P = max(prev_P, now) + 1/weight        # proportional share

Dequeue (mClock's two phases): first any class whose R tag is due — pick
the earliest R (reservations are guarantees, served before everything);
otherwise among classes whose L tag is due pick the earliest P tag
(weighted fair sharing under the ceiling).  If nothing is eligible the
caller sleeps until the earliest tag matures.

The OSD instantiates the reference's three classes — client,
background_recovery, background_scrub — so client I/O keeps its floor
while recovery/scrub make progress without starving it.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..common.lockdep import make_lock


@dataclass(frozen=True)
class QoSParams:
    """reference: dmclock ClientInfo (reservation, weight, limit);
    0 = none (no floor / no ceiling)."""

    reservation: float = 0.0
    weight: float = 1.0
    limit: float = 0.0


@dataclass
class _ClassState:
    params: QoSParams
    queue: list = field(default_factory=list)  # FIFO of (seq, item)
    r_tag: float = 0.0
    p_tag: float = 0.0
    l_tag: float = 0.0


class MClockScheduler:
    def __init__(self, classes: dict[str, QoSParams],
                 clock=time.monotonic):
        self._classes = {
            name: _ClassState(params) for name, params in classes.items()
        }
        self._clock = clock
        self._seq = 0
        self._lock = make_lock("osd::mclock")
        self._cond = threading.Condition(self._lock)
        self._stopped = False

    # -- producer ----------------------------------------------------------
    def enqueue(self, cls: str, item) -> None:
        now = self._clock()
        with self._lock:
            st = self._classes[cls]
            empty = not st.queue
            self._seq += 1
            st.queue.append((self._seq, item))
            if empty:
                # tags advance per dequeue; a class going idle resets its
                # cadence to "now" (dmclock's idle-client tag reset)
                p = st.params
                if p.reservation:
                    st.r_tag = max(st.r_tag, now)
                if p.limit:
                    st.l_tag = max(st.l_tag, now)
                st.p_tag = max(st.p_tag, now)
            self._cond.notify()

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._cond.notify_all()

    # -- consumer ----------------------------------------------------------
    def _pick_locked(self, now: float):
        """(cls, item) of the next eligible op, or (None, wake_at)."""
        best_r = None  # (r_tag, name)
        best_p = None  # (p_tag, name)
        wake = None
        for name, st in self._classes.items():
            if not st.queue:
                continue
            p = st.params
            if p.reservation and st.r_tag <= now:
                if best_r is None or st.r_tag < best_r[0]:
                    best_r = (st.r_tag, name)
                continue  # reservation-phase candidates skip P
            if p.limit and st.l_tag > now:
                wake = st.l_tag if wake is None else min(wake, st.l_tag)
                continue
            if best_p is None or st.p_tag < best_p[0]:
                best_p = (st.p_tag, name)
            if p.reservation:
                wake = st.r_tag if wake is None else min(wake, st.r_tag)
        name = best_r[1] if best_r is not None else (
            best_p[1] if best_p is not None else None
        )
        if name is None:
            return None, wake
        st = self._classes[name]
        _, item = st.queue.pop(0)
        p = st.params
        if p.reservation:
            st.r_tag = max(now, st.r_tag) + 1.0 / p.reservation
        if p.limit:
            st.l_tag = max(now, st.l_tag) + 1.0 / p.limit
        st.p_tag = max(now, st.p_tag) + 1.0 / p.weight
        return (name, item), None

    def dequeue(self, timeout: float | None = None):
        """Blocking pop -> (class, item) or None on stop/timeout."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._lock:
            while not self._stopped:
                picked, wake = self._pick_locked(self._clock())
                if picked is not None:
                    return picked
                now = self._clock()
                waits = [w - now for w in (wake,) if w is not None]
                if deadline is not None:
                    if now >= deadline:
                        return None
                    waits.append(deadline - now)
                self._cond.wait(timeout=min(waits) if waits else None)
            return None

    def qlen(self) -> int:
        with self._lock:
            return sum(len(st.queue) for st in self._classes.values())
