"""Block allocator binding (reference: src/os/bluestore/Allocator.h and
its Bitmap/Avl implementations; SURVEY.md §2.4 "allocators").

Uses the native next-fit bitmap allocator (native/allocator.cc) via
ctypes when the oracle .so is built, else a pure-Python bitmap with the
same behavior.  Extents are (start_block, n_blocks) runs.
"""
from __future__ import annotations

import ctypes
import os
from ..common.lockdep import make_lock

_LIB = None


def _load_lib():
    global _LIB
    if _LIB is not None:
        return _LIB
    try:
        # native_oracle's loader rebuilds the .so when sources are newer,
        # so a stale library predating allocator.cc gets refreshed instead
        # of failing symbol lookup
        from ..native_oracle import _lib as _oracle_lib

        lib = _oracle_lib()
        lib.ctpu_alloc_create.restype = ctypes.c_void_p
        lib.ctpu_alloc_create.argtypes = [ctypes.c_uint64]
        lib.ctpu_alloc_destroy.argtypes = [ctypes.c_void_p]
        lib.ctpu_alloc_free_blocks.restype = ctypes.c_uint64
        lib.ctpu_alloc_free_blocks.argtypes = [ctypes.c_void_p]
        lib.ctpu_alloc_mark.restype = ctypes.c_int
        lib.ctpu_alloc_mark.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int
        ]
        lib.ctpu_alloc_allocate.restype = ctypes.c_int
        lib.ctpu_alloc_allocate.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
        ]
        _LIB = lib
    except (OSError, AttributeError, RuntimeError, ImportError):
        # missing .so, failed build, or a lib without the ctpu_alloc_*
        # symbols: fall back to the Python allocator
        _LIB = False
    return _LIB


class AllocError(RuntimeError):
    pass


class NativeBitmapAllocator:
    """ctypes wrapper over native/allocator.cc."""

    MAX_EXTENTS = 512

    def __init__(self, n_blocks: int):
        lib = _load_lib()
        if not lib:
            raise AllocError("native allocator unavailable")
        self._lib = lib
        self._h = lib.ctpu_alloc_create(n_blocks)
        if not self._h:
            raise AllocError("allocator create failed")
        self.n_blocks = n_blocks
        self._lock = make_lock("store::alloc")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.ctpu_alloc_destroy(h)
            self._h = None

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return int(self._lib.ctpu_alloc_free_blocks(self._h))

    def mark_used(self, start: int, length: int) -> None:
        with self._lock:
            if self._lib.ctpu_alloc_mark(self._h, start, length, 0) != 0:
                raise AllocError(f"mark_used({start},{length}) out of range")

    def release(self, start: int, length: int) -> None:
        with self._lock:
            if self._lib.ctpu_alloc_mark(self._h, start, length, 1) != 0:
                raise AllocError(f"release({start},{length}) out of range")

    def allocate(self, want: int) -> list[tuple[int, int]]:
        out = (ctypes.c_uint64 * (2 * self.MAX_EXTENTS))()
        with self._lock:
            n = self._lib.ctpu_alloc_allocate(
                self._h, want, out, self.MAX_EXTENTS
            )
        if n < 0:
            raise AllocError(f"cannot allocate {want} blocks")
        return [(int(out[2 * i]), int(out[2 * i + 1])) for i in range(n)]


class PyBitmapAllocator:
    """Pure-Python next-fit bitmap with the native allocator's contract,
    including the MAX_EXTENTS fragmentation budget (so workloads pass or
    fail identically whichever implementation is loaded)."""

    MAX_EXTENTS = NativeBitmapAllocator.MAX_EXTENTS

    def __init__(self, n_blocks: int):
        self.n_blocks = n_blocks
        self._free = bytearray(b"\x01") * n_blocks if n_blocks else bytearray()
        self._n_free = n_blocks
        self._cursor = 0
        self._lock = make_lock("store::alloc")

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return self._n_free

    def _mark_locked(self, start: int, length: int, free: bool) -> None:
        if start + length > self.n_blocks:
            raise AllocError(f"extent ({start},{length}) out of range")
        v = 1 if free else 0
        for i in range(start, start + length):
            if self._free[i] != v:
                self._free[i] = v
                self._n_free += 1 if free else -1

    def mark_used(self, start: int, length: int) -> None:
        with self._lock:
            self._mark_locked(start, length, False)

    def release(self, start: int, length: int) -> None:
        with self._lock:
            self._mark_locked(start, length, True)

    def allocate(self, want: int) -> list[tuple[int, int]]:
        with self._lock:
            if want == 0:
                return []
            if want > self._n_free:
                raise AllocError(f"cannot allocate {want} blocks")
            out: list[tuple[int, int]] = []
            got = 0
            pos = self._cursor % self.n_blocks
            scanned = 0
            while got < want and scanned < self.n_blocks:
                while scanned < self.n_blocks and not self._free[pos]:
                    pos += 1
                    scanned += 1
                    if pos >= self.n_blocks:
                        pos = 0
                if scanned >= self.n_blocks:
                    break
                run_start, run_len = pos, 0
                while (
                    scanned < self.n_blocks and got + run_len < want
                    and pos < self.n_blocks and self._free[pos]
                ):
                    run_len += 1
                    pos += 1
                    scanned += 1
                if run_len:
                    if len(out) >= self.MAX_EXTENTS:
                        raise AllocError(
                            f"allocation of {want} blocks exceeds the "
                            f"{self.MAX_EXTENTS}-extent budget"
                        )
                    out.append((run_start, run_len))
                    got += run_len
                if pos >= self.n_blocks:
                    pos = 0
            if got < want:
                raise AllocError(f"cannot allocate {want} blocks")
            for s, n in out:
                self._mark_locked(s, n, False)
            self._cursor = pos
            return out


def make_allocator(n_blocks: int):
    """Native when built, Python otherwise (same contract either way)."""
    try:
        return NativeBitmapAllocator(n_blocks)
    except AllocError:
        return PyBitmapAllocator(n_blocks)
