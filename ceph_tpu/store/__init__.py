"""ceph_tpu.store — local object storage (reference: src/os, src/kv;
SURVEY.md §2.4).

ObjectStore is the transactional object API the OSD data plane writes
through (reference: src/os/ObjectStore.h :: queue_transaction /
Transaction).  Backends:

- MemStore: in-RAM, the unit-test backend (reference: src/os/memstore).
- KStore: crash-safe file-backed store — every Transaction becomes one
  atomic, crc-protected WAL batch in a log-structured KV (reference role:
  BlueStore's RocksDB-WAL commit path, src/os/bluestore; the KV design is
  the analog of src/kv/RocksDBStore over BlueFS).

Collections are PGs, exactly as in the reference.
"""
from .kv import KeyValueDB, LogKV
from .object_store import (
    Collection,
    NotFound,
    ObjectStore,
    StoreError,
    Transaction,
    create_store,
)
from .memstore import MemStore
from .kstore import KStore

__all__ = [
    "Collection",
    "KStore",
    "KeyValueDB",
    "LogKV",
    "MemStore",
    "NotFound",
    "ObjectStore",
    "StoreError",
    "Transaction",
    "create_store",
]
