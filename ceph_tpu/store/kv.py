"""Log-structured KV store with a crc-protected WAL (reference role:
src/kv/RocksDBStore.{h,cc} over BlueFS — the metadata/commit engine under
BlueStore and the MonitorDBStore; SURVEY.md §2.4, §5.4).

Design: an append-only WAL of batches.  Each batch is
    [u32 len][u32 crc32c(payload)][payload]
where payload encodes the (set/rm) ops.  A batch is durable once the record
is written (+fsync when sync=True); recovery replays the WAL in order and
stops at the first torn/corrupt record — exactly the RocksDB WAL contract
that gives the reference its all-or-nothing transaction semantics.
`compact()` writes a snapshot of the live map and truncates the WAL
(RocksDB's memtable flush analog, radically simplified).
"""
from __future__ import annotations

import os
import struct

from ..common.buffer import BufferList, BufferListIterator
from ..common.lockdep import make_lock
from ..common.crc32c import crc32c

_OP_SET = 1
_OP_RM = 2

_SNAP_MAGIC = b"ctpu-kv-snap-v1\n"


class KeyValueDB:
    """Transactional KV contract (reference: src/kv/KeyValueDB.h)."""

    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def set(self, key: str, value: bytes, sync: bool = False) -> None:
        self.submit_batch([(_OP_SET, key, bytes(value))], sync=sync)

    def rm(self, key: str, sync: bool = False) -> None:
        self.submit_batch([(_OP_RM, key, b"")], sync=sync)

    def submit_batch(self, ops, sync: bool = False) -> None:
        """ops: list of (op, key, value); atomic."""
        raise NotImplementedError

    def iterate(self, prefix: str = ""):
        raise NotImplementedError


class MemKV(KeyValueDB):
    """In-RAM KV for disk-less daemons in tests (MemStore's analog at the
    KV layer)."""

    def __init__(self):
        self._map: dict[str, bytes] = {}
        self._lock = make_lock("store::kv")

    def submit_batch(self, ops, sync: bool = False) -> None:
        if isinstance(ops, Batch):
            ops = ops.ops
        with self._lock:
            for op, key, value in ops:
                if op == _OP_SET:
                    self._map[key] = bytes(value)
                else:
                    self._map.pop(key, None)

    def get(self, key: str) -> bytes | None:
        with self._lock:
            return self._map.get(key)

    def iterate(self, prefix: str = ""):
        with self._lock:
            keys = sorted(k for k in self._map if k.startswith(prefix))
        for k in keys:
            v = self.get(k)
            if v is not None:
                yield k, v

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def close(self) -> None:
        pass


class Batch:
    """Write batch builder (reference: KeyValueDB::Transaction)."""

    def __init__(self):
        self.ops: list[tuple[int, str, bytes]] = []

    def set(self, key: str, value: bytes) -> "Batch":
        self.ops.append((_OP_SET, key, bytes(value)))
        return self

    def rm(self, key: str) -> "Batch":
        self.ops.append((_OP_RM, key, b""))
        return self


class LogKV(KeyValueDB):
    """WAL + snapshot file pair in a directory."""

    def __init__(self, path: str, sync_default: bool = True,
                 compact_threshold: int = 64 << 20,
                 readonly: bool = False):
        """readonly: pure inspection open (kvstore-tool role) — never
        creates the directory, never truncates a torn WAL tail (the torn
        record is evidence on a corrupt store), never opens the WAL for
        append; submit_batch refuses."""
        self.path = path
        self.sync_default = sync_default
        self.compact_threshold = compact_threshold
        self.readonly = readonly
        self._map: dict[str, bytes] = {}
        self._lock = make_lock("store::kv")
        self._wal = None
        if not readonly:
            os.makedirs(path, exist_ok=True)
        self._snap_path = os.path.join(path, "snapshot")
        self._wal_path = os.path.join(path, "wal")
        self._recover()

    # -- recovery ---------------------------------------------------------
    def _recover(self) -> None:
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                raw = f.read()
            if not raw.startswith(_SNAP_MAGIC):
                raise IOError(f"{self._snap_path}: bad snapshot magic")
            body = raw[len(_SNAP_MAGIC):]
            (crc,) = struct.unpack("<I", body[:4])
            payload = body[4:]
            if crc32c(payload) != crc:
                raise IOError(f"{self._snap_path}: snapshot crc mismatch")
            it = BufferListIterator(payload)
            for _ in range(it.get_u32()):
                k = it.get_str()
                self._map[k] = it.get_str_bytes()
        if os.path.exists(self._wal_path):
            with open(self._wal_path, "rb") as f:
                wal = f.read()
            pos = 0
            while pos + 8 <= len(wal):
                length, crc = struct.unpack_from("<II", wal, pos)
                payload = wal[pos + 8 : pos + 8 + length]
                if len(payload) < length or crc32c(payload) != crc:
                    break  # torn tail: last batch never committed
                self._replay(payload)
                pos += 8 + length
            if pos < len(wal) and not self.readonly:
                # drop the torn tail so future appends start at a clean
                # record boundary (RocksDB recycles the WAL the same way)
                with open(self._wal_path, "r+b") as f:
                    f.truncate(pos)
        if not self.readonly:
            self._wal = open(self._wal_path, "ab")

    def _replay(self, payload: bytes) -> None:
        it = BufferListIterator(payload)
        for _ in range(it.get_u32()):
            op = it.get_u8()
            key = it.get_str()
            val = it.get_str_bytes()
            if op == _OP_SET:
                self._map[key] = val
            else:
                self._map.pop(key, None)

    # -- writes -----------------------------------------------------------
    def submit_batch(self, ops, sync: bool | None = None) -> None:
        if self.readonly:
            raise IOError("read-only KV open refuses writes")
        if isinstance(ops, Batch):
            ops = ops.ops
        sync = self.sync_default if sync is None else sync
        bl = BufferList()
        bl.append_u32(len(ops))
        for op, key, value in ops:
            bl.append_u8(op)
            bl.append_str(key)
            bl.append_str(value)
        payload = bytes(bl)
        record = struct.pack("<II", len(payload), crc32c(payload)) + payload
        with self._lock:
            self._wal.write(record)
            self._wal.flush()
            if sync:
                os.fsync(self._wal.fileno())
            self._replay(payload)
            if self._wal.tell() >= self.compact_threshold:
                self._compact_locked()

    # -- reads ------------------------------------------------------------
    def get(self, key: str) -> bytes | None:
        with self._lock:
            return self._map.get(key)

    def iterate(self, prefix: str = ""):
        with self._lock:
            keys = sorted(k for k in self._map if k.startswith(prefix))
        for k in keys:
            v = self.get(k)
            if v is not None:
                yield k, v

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    # -- maintenance ------------------------------------------------------
    def compact(self) -> None:
        with self._lock:
            self._compact_locked()

    def _compact_locked(self) -> None:
        bl = BufferList()
        bl.append_u32(len(self._map))
        for k in sorted(self._map):
            bl.append_str(k)
            bl.append_str(self._map[k])
        payload = bytes(bl)
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_SNAP_MAGIC + struct.pack("<I", crc32c(payload)) + payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        self._wal.close()
        self._wal = open(self._wal_path, "wb")  # truncate

    def close(self) -> None:
        with self._lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None
