"""KStore — crash-safe file-backed ObjectStore over LogKV (reference role:
src/os/bluestore/BlueStore.{h,cc}'s commit path: every Transaction becomes
one atomic KV WAL batch, fsync'd before the commit callback fires, replayed
on mount; SURVEY.md §2.4, §5.4 "BlueStore transactions: all-or-nothing
commit via RocksDB WAL").

State model: the live {cid: Collection} image is in RAM (objects here are
metadata+data values, not a block device); the KV holds the authoritative
absolute state — per-object data/xattr/omap keys — so WAL replay is
idempotent.  A Transaction is applied to the RAM image first (validating,
all-or-nothing), then persisted as one batch of absolute post-state values.
"""
from __future__ import annotations

from ..common.lockdep import make_lock
from typing import Callable

from .kv import Batch, LogKV
from .memstore import MemStore
from .object_store import Collection, NotFound, Object, Transaction

_SEP = "\x00"


def _dkey(cid: str, oid: str) -> str:
    return f"D{_SEP}{cid}{_SEP}{oid}"


def _akey(cid: str, oid: str, name: str) -> str:
    return f"A{_SEP}{cid}{_SEP}{oid}{_SEP}{name}"


def _okey(cid: str, oid: str, key: str) -> str:
    return f"O{_SEP}{cid}{_SEP}{oid}{_SEP}{key}"


def _ckey(cid: str) -> str:
    return f"C{_SEP}{cid}"


def _zkey(cid: str, oid: str) -> str:
    """Compressed-data twin of _dkey (value: b"<algo>\\x00" + blob)."""
    return f"Z{_SEP}{cid}{_SEP}{oid}"


class KStore(MemStore):
    """MemStore's read paths + apply loop, with a durable KV underneath."""

    def __init__(self, path: str, sync: bool = True,
                 compression: str = "none"):
        super().__init__()
        self.path = path
        self._kv = LogKV(path, sync_default=sync)
        self._mounted = False
        self._io_lock = make_lock("store::kstore_io")
        # at-rest object-data compression (reference: bluestore_compression
        # — data only, stored iff it actually shrinks; xattr/omap stay raw)
        self._compressor = None
        if compression and compression != "none":
            from ..compressor import Compressor

            self._compressor = Compressor.create(compression)

    # -- lifecycle --------------------------------------------------------
    def mount(self) -> None:
        """Rebuild the RAM image from the KV (replays the WAL internally)."""
        with self._io_lock:
            colls: dict[str, Collection] = {}
            for key, _ in self._kv.iterate(f"C{_SEP}"):
                colls[key.split(_SEP, 1)[1]] = Collection()
            for key, val in self._kv.iterate(f"D{_SEP}"):
                _, cid, oid = key.split(_SEP, 2)
                colls[cid].objects[oid] = Object(data=bytearray(val))
            decompressors: dict[str, object] = {}
            for key, val in self._kv.iterate(f"Z{_SEP}"):
                _, cid, oid = key.split(_SEP, 2)
                algo, _, blob = bytes(val).partition(b"\x00")
                name = algo.decode()
                comp = decompressors.get(name)
                if comp is None:
                    from ..compressor import Compressor

                    comp = decompressors[name] = Compressor.create(name)
                colls[cid].objects[oid] = Object(
                    data=bytearray(comp.decompress(blob))
                )
            for key, val in self._kv.iterate(f"A{_SEP}"):
                _, cid, oid, name = key.split(_SEP, 3)
                colls[cid].objects[oid].xattrs[name] = val
            for key, val in self._kv.iterate(f"O{_SEP}"):
                _, cid, oid, okey = key.split(_SEP, 3)
                colls[cid].objects[oid].omap[okey] = val
            self._colls = colls
            self._mounted = True

    def umount(self) -> None:
        with self._io_lock:
            self._kv.close()
            self._mounted = False

    def compact(self) -> None:
        self._kv.compact()

    # -- writes -----------------------------------------------------------
    def queue_transaction(
        self, t: Transaction, on_commit: Callable[[], None] | None = None
    ) -> None:
        # torn-write injection: before = nothing durable, after = the WAL
        # batch committed but the caller sees a failure (the crash shapes
        # WAL replay and dup detection must absorb)
        self._fp_hit("osd.store.write_before_commit")
        with self._io_lock, self._lock:
            before_colls = set(self._colls)
            touched = {(op.cid, op.oid) for op in t.ops if op.oid} | {
                (op.dest_cid, op.dest_oid) for op in t.ops if op.dest_oid
            }
            # stale xattr/omap key names come from the pre-apply RAM image
            # (no KV scans — LogKV.iterate sorts the whole keyspace)
            stale: dict[tuple[str, str], tuple[set[str], set[str]]] = {}
            for cid, oid in touched:
                c = self._colls.get(cid)
                o = c.objects.get(oid) if c else None
                stale[(cid, oid)] = (
                    (set(o.xattrs), set(o.omap)) if o else (set(), set())
                )
            self.apply_atomic(self._colls, t)
            batch = Batch()
            for cid in before_colls - set(self._colls):
                batch.rm(_ckey(cid))
            for cid in set(self._colls) - before_colls:
                batch.set(_ckey(cid), b"")
            for cid, oid in sorted(touched):
                # clear any stale keys for the object, then write absolute
                # post-state — makes the batch idempotent under replay
                batch.rm(_dkey(cid, oid))
                batch.rm(_zkey(cid, oid))
                old_xattrs, old_omap = stale[(cid, oid)]
                for name in old_xattrs:
                    batch.rm(_akey(cid, oid, name))
                for key in old_omap:
                    batch.rm(_okey(cid, oid, key))
                c = self._colls.get(cid)
                o = c.objects.get(oid) if c else None
                if o is not None:
                    raw = bytes(o.data)
                    blob = None
                    if self._compressor is not None and raw:
                        z = self._compressor.compress(raw)
                        if len(z) < len(raw):  # store compressed iff it wins
                            blob = (
                                self._compressor.NAME.encode() + b"\x00" + z
                            )
                    if blob is not None:
                        batch.set(_zkey(cid, oid), blob)
                    else:
                        batch.set(_dkey(cid, oid), raw)
                    for name, val in o.xattrs.items():
                        batch.set(_akey(cid, oid, name), val)
                    for key, val in o.omap.items():
                        batch.set(_okey(cid, oid, key), val)
            self._kv.submit_batch(batch)
        self._fp_hit("osd.store.write_after_commit")
        if on_commit:
            on_commit()

    # -- fsck (reference: BlueStore::fsck — mount-time consistency) -------
    def fsck(self) -> list[str]:
        errors = []
        with self._io_lock:
            seen_colls = {
                key.split(_SEP, 1)[1] for key, _ in self._kv.iterate(f"C{_SEP}")
            }
            for kind in ("D", "Z"):
                for key, _ in self._kv.iterate(f"{kind}{_SEP}"):
                    _, cid, _oid = key.split(_SEP, 2)
                    if cid not in seen_colls:
                        errors.append(
                            f"object key {key!r} in missing collection"
                        )
            for kind in ("A", "O"):
                for key, _ in self._kv.iterate(f"{kind}{_SEP}"):
                    _, cid, oid, _rest = key.split(_SEP, 3)
                    if (
                        self._kv.get(_dkey(cid, oid)) is None
                        and self._kv.get(_zkey(cid, oid)) is None
                    ):
                        errors.append(f"{key!r} has no object data key")
        return errors


class FileStore(KStore):
    """Alias retained for the `objectstore = filestore` config spelling."""
