"""BlueStore-analog ObjectStore: raw block-device file + allocator + KV
metadata (reference: src/os/bluestore/BlueStore.{h,cc} — KernelDevice +
BitmapAllocator + RocksDB onodes; SURVEY.md §2.4).

Structure mirrors the reference's split:

- **Block device**: one flat file carved into `block_size` blocks
  (KernelDevice role).  Object payloads live in allocated extents.
- **Allocator**: next-fit bitmap (native C++ via ctypes, Python
  fallback) — see alloc.py.  The freelist is NOT persisted: it is
  rebuilt on mount by walking the onodes, exactly the invariant
  BlueStore's fsck enforces (allocated == referenced).
- **KV metadata**: onodes (size, inline-or-extents, per-extent crc32c),
  xattrs, omap, collections in the WAL'd LogKV (the RocksDB role).

Commit path (copy-on-write, the crash-safety scheme):
 1. materialize post-state of touched objects in RAM (all-or-nothing);
 2. write changed data to FRESHLY allocated extents + fdatasync the
    device — old extents are untouched;
 3. commit ONE atomic KV batch switching onodes to the new extents;
 4. release the old extents back to the in-RAM allocator.
A crash between 2 and 3 leaks the new extents only until the next mount
rebuild; a crash after 3 leaks nothing.  Data writes of objects below
`inline_threshold` live inside the onode value (BlueStore's small-blob /
deferred-write spirit: tiny writes ride the KV WAL, not the device).

fsck(): extent range/overlap audit + (deep) per-extent crc verify, with
leaked-block accounting — the ceph-bluestore-tool fsck role.
"""
from __future__ import annotations

import base64
import json
import os
from typing import Callable

from ..common.crc32c import crc32c
from ..common.lockdep import make_lock
from .alloc import make_allocator
from .kv import Batch, LogKV
from .object_store import (
    NotFound,
    ObjectStore,
    OP_COLL_MOVE_RENAME,
    OP_MKCOLL,
    OP_OMAP_CLEAR,
    OP_OMAP_RMKEYS,
    OP_OMAP_SETKEYS,
    OP_REMOVE,
    OP_RMATTR,
    OP_RMCOLL,
    OP_SETATTR,
    OP_TOUCH,
    OP_TRY_MKCOLL,
    OP_TRUNCATE,
    OP_WRITE,
    OP_ZERO,
    StoreError,
    Transaction,
)

_SEP = "\x00"


def _nkey(cid: str, oid: str) -> str:
    return f"N{_SEP}{cid}{_SEP}{oid}"


def _akey(cid: str, oid: str, name: str) -> str:
    return f"A{_SEP}{cid}{_SEP}{oid}{_SEP}{name}"


def _okey(cid: str, oid: str, key: str) -> str:
    return f"O{_SEP}{cid}{_SEP}{oid}{_SEP}{key}"


def _ckey(cid: str) -> str:
    return f"C{_SEP}{cid}"


class Onode:
    """Per-object metadata (reference: BlueStore::Onode).  Data is either
    inline bytes or a list of device extents with per-extent crc32c."""

    __slots__ = ("size", "inline", "extents", "crcs", "xattrs", "omap",
                 "comp", "clen")

    def __init__(self):
        self.size = 0
        self.inline: bytes | None = b""
        self.extents: list[tuple[int, int]] = []
        self.crcs: list[int] = []
        self.xattrs: dict[str, bytes] = {}
        self.omap: dict[str, bytes] = {}
        # at-rest compression (reference: bluestore_compression blobs):
        # comp = algorithm name when the extents hold a COMPRESSED blob
        # of clen stored bytes decompressing to `size` logical bytes
        self.comp: str | None = None
        self.clen = 0

    def stored_len(self) -> int:
        """Bytes actually on the device (compressed or raw)."""
        return self.clen if self.comp else self.size

    def encode(self) -> bytes:
        d = {
            "size": self.size,
            "inline": (
                base64.b64encode(self.inline).decode()
                if self.inline is not None else None
            ),
            "extents": self.extents,
            "crcs": self.crcs,
        }
        if self.comp:
            d["comp"] = self.comp
            d["clen"] = self.clen
        return json.dumps(d).encode()

    @classmethod
    def decode(cls, raw: bytes) -> "Onode":
        d = json.loads(raw)
        o = cls()
        o.size = d["size"]
        o.inline = (
            base64.b64decode(d["inline"]) if d["inline"] is not None else None
        )
        o.extents = [tuple(e) for e in d["extents"]]
        o.crcs = list(d["crcs"])
        o.comp = d.get("comp")
        o.clen = d.get("clen", 0)
        return o


class BlueStore(ObjectStore):
    def __init__(
        self,
        path: str,
        device_size: int = 1 << 30,
        block_size: int = 4096,
        inline_threshold: int = 4096,
        sync: bool = True,
        checksum: bool = True,
        compression: str = "none",
    ):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.block_size = block_size
        self.inline_threshold = inline_threshold
        self.checksum = checksum
        # at-rest data compression (reference: bluestore_compression —
        # whole-blob, kept only when it actually shrinks; metadata and
        # inline blobs stay raw)
        self._comp_name = compression if compression != "none" else None
        self._compressor = None
        if self._comp_name:
            from ..compressor import Compressor

            self._compressor = Compressor.create(self._comp_name)
        self._kv = None
        self._dev_path = os.path.join(path, "block")
        self._dev = None
        self._sync = sync
        self.n_blocks = device_size // block_size
        self._alloc = None
        self._colls: set[str] = set()
        self._onodes: dict[tuple[str, str], Onode] = {}
        self._lock = make_lock("store::bluestore")
        self._mounted = False
        self.mount()

    # -- device ------------------------------------------------------------
    def _dev_write(self, extents, data: bytes) -> list[int]:
        """Scatter `data` across `extents`; returns per-extent crc32c."""
        crcs = []
        off = 0
        for start, n in extents:
            part = data[off : off + n * self.block_size]
            self._dev.seek(start * self.block_size)
            self._dev.write(part)
            crcs.append(crc32c(part))
            off += n * self.block_size
        return crcs

    def _dev_read(self, onode: Onode, verify: bool | None = None) -> bytes:
        if onode.inline is not None:
            return onode.inline[: onode.size]
        parts = []
        for i, (start, n) in enumerate(onode.extents):
            self._dev.seek(start * self.block_size)
            part = self._dev.read(n * self.block_size)
            if (self.checksum if verify is None else verify) and \
                    i < len(onode.crcs):
                # the final extent's stored bytes may be shorter than the
                # block-rounded read when the device tail was never written
                part = part[: self._part_len(onode, i)]
                if crc32c(part) != onode.crcs[i]:
                    raise StoreError(
                        f"crc mismatch on extent {i} ({start},{n})"
                    )
            parts.append(part)
        stored = b"".join(parts)[: onode.stored_len()]
        if onode.comp:
            stored = self._decompressor(onode.comp).decompress(stored)
        return stored[: onode.size]

    def _decompressor(self, name: str):
        """Cached per-algorithm decompressor (a store reads objects
        compressed under any past knob setting, not just its own)."""
        if name == self._comp_name and self._compressor is not None:
            return self._compressor
        cache = getattr(self, "_decompressors", None)
        if cache is None:
            cache = self._decompressors = {}
        comp = cache.get(name)
        if comp is None:
            from ..compressor import Compressor

            comp = cache[name] = Compressor.create(name)
        return comp

    def _part_len(self, onode: Onode, i: int) -> int:
        """Bytes of payload stored in extent i (last extent may be
        partial); compressed blobs measure by their STORED length."""
        before = sum(
            n * self.block_size for _, n in onode.extents[:i]
        )
        return min(
            onode.extents[i][1] * self.block_size,
            max(0, onode.stored_len() - before),
        )

    # -- mount / freelist rebuild -----------------------------------------
    def mount(self) -> None:
        with self._lock:
            if self._mounted:
                return
            self._kv = LogKV(
                os.path.join(self.path, "kv"), sync_default=self._sync
            )
            if not os.path.exists(self._dev_path):
                with open(self._dev_path, "wb") as f:
                    f.truncate(self.n_blocks * self.block_size)
            self._dev = open(self._dev_path, "r+b")
            self._alloc = make_allocator(self.n_blocks)
            self._colls = {
                k.split(_SEP, 1)[1] for k, _ in self._kv.iterate("C" + _SEP)
            }
            self._onodes = {}
            for k, v in self._kv.iterate("N" + _SEP):
                _, cid, oid = k.split(_SEP, 2)
                onode = Onode.decode(v)
                self._onodes[(cid, oid)] = onode
                for start, n in onode.extents:
                    self._alloc.mark_used(start, n)
            for k, v in self._kv.iterate("A" + _SEP):
                _, cid, oid, name = k.split(_SEP, 3)
                o = self._onodes.get((cid, oid))
                if o is not None:
                    o.xattrs[name] = v
            for k, v in self._kv.iterate("O" + _SEP):
                _, cid, oid, key = k.split(_SEP, 3)
                o = self._onodes.get((cid, oid))
                if o is not None:
                    o.omap[key] = v
            self._mounted = True

    def umount(self) -> None:
        with self._lock:
            if not self._mounted:
                return
            self._kv.close()
            self._kv = None
            self._dev.close()
            self._dev = None
            self._mounted = False

    # -- transaction apply -------------------------------------------------
    def queue_transaction(
        self, t: Transaction, on_commit: Callable[[], None] | None = None
    ) -> None:
        # torn-write injection: see MemStore.queue_transaction
        self._fp_hit("osd.store.write_before_commit")
        with self._lock:
            self._apply_txn(t)
        self._fp_hit("osd.store.write_after_commit")
        if on_commit is not None:
            on_commit()

    def _materialize(self, staged, cid, oid, create=False):
        """Post-state working copy of an object for this transaction.

        Data bytes are LAZY: metadata-only ops (xattr/omap/touch) must not
        pay a device read + crc verify of a possibly-large payload, so
        st["data"] stays None until `_data()` is called by an op that
        actually edits bytes; st["size"] is valid either way."""
        key = (cid, oid)
        if key in staged:
            st = staged[key]
            if st is None and not create:
                raise NotFound(f"object {cid}/{oid}")
            if st is None:
                staged[key] = st = {
                    "data": bytearray(), "size": 0, "xattrs": {},
                    "omap": {}, "dirty_data": True, "key": key,
                }
            return st
        onode = self._onodes.get(key)
        if onode is None:
            if not create:
                raise NotFound(f"object {cid}/{oid}")
            staged[key] = st = {
                "data": bytearray(), "size": 0, "xattrs": {}, "omap": {},
                "dirty_data": True, "key": key,
            }
            return st
        staged[key] = st = {
            "data": None, "size": onode.size,
            "xattrs": dict(onode.xattrs),
            "omap": dict(onode.omap),
            "dirty_data": False, "key": key,
        }
        return st

    def _data(self, st) -> bytearray:
        """Materialize the staged object's bytes (device read on first
        data-touching op)."""
        if st["data"] is None:
            onode = self._onodes.get(st["key"])
            st["data"] = bytearray(
                self._dev_read(onode) if onode is not None else b""
            )
        return st["data"]

    def _require_coll(self, colls, cid):
        if cid not in colls:
            raise NotFound(f"collection {cid}")

    def _apply_txn(self, t: Transaction) -> None:
        # phase 1: compute post-state in RAM (all-or-nothing on error)
        colls = set(self._colls)
        staged: dict[tuple[str, str], dict | None] = {}
        for op in t.ops:
            if op.op == OP_MKCOLL:
                if op.cid in colls:
                    raise StoreError(f"collection {op.cid} exists")
                colls.add(op.cid)
            elif op.op == OP_TRY_MKCOLL:
                colls.add(op.cid)
            elif op.op == OP_RMCOLL:
                if op.cid not in colls:
                    raise NotFound(f"collection {op.cid}")
                live = any(
                    k[0] == op.cid and staged.get(k, True) is not None
                    for k in set(self._onodes) | set(staged)
                )
                if live:
                    raise StoreError(f"collection {op.cid} not empty")
                colls.discard(op.cid)
            elif op.op == OP_TOUCH:
                self._require_coll(colls, op.cid)
                self._materialize(staged, op.cid, op.oid, create=True)
            elif op.op == OP_WRITE:
                self._require_coll(colls, op.cid)
                st = self._materialize(staged, op.cid, op.oid, create=True)
                data = self._data(st)
                end = op.off + len(op.data)
                if len(data) < end:
                    data.extend(b"\0" * (end - len(data)))
                data[op.off : end] = op.data
                st["dirty_data"] = True
            elif op.op == OP_ZERO:
                self._require_coll(colls, op.cid)
                st = self._materialize(staged, op.cid, op.oid)
                data = self._data(st)
                end = op.off + op.length
                if len(data) < end:
                    data.extend(b"\0" * (end - len(data)))
                data[op.off : end] = b"\0" * op.length
                st["dirty_data"] = True
            elif op.op == OP_TRUNCATE:
                self._require_coll(colls, op.cid)
                st = self._materialize(staged, op.cid, op.oid)
                data = self._data(st)
                size = op.off
                if len(data) > size:
                    del data[size:]
                else:
                    data.extend(b"\0" * (size - len(data)))
                st["dirty_data"] = True
            elif op.op == OP_REMOVE:
                self._require_coll(colls, op.cid)
                self._materialize(staged, op.cid, op.oid)
                staged[(op.cid, op.oid)] = None
            elif op.op == OP_SETATTR:
                self._require_coll(colls, op.cid)
                st = self._materialize(staged, op.cid, op.oid)
                st["xattrs"][op.name] = op.data
            elif op.op == OP_RMATTR:
                self._require_coll(colls, op.cid)
                st = self._materialize(staged, op.cid, op.oid)
                st["xattrs"].pop(op.name, None)
            elif op.op == OP_OMAP_SETKEYS:
                self._require_coll(colls, op.cid)
                st = self._materialize(staged, op.cid, op.oid)
                st["omap"].update(op.keys)
            elif op.op == OP_OMAP_RMKEYS:
                self._require_coll(colls, op.cid)
                st = self._materialize(staged, op.cid, op.oid)
                for k in op.keys:
                    st["omap"].pop(k, None)
            elif op.op == OP_OMAP_CLEAR:
                self._require_coll(colls, op.cid)
                st = self._materialize(staged, op.cid, op.oid)
                st["omap"].clear()
            elif op.op == OP_COLL_MOVE_RENAME:
                self._require_coll(colls, op.cid)
                self._require_coll(colls, op.dest_cid)
                st = self._materialize(staged, op.cid, op.oid)
                data = bytearray(self._data(st))
                staged[(op.cid, op.oid)] = None
                staged[(op.dest_cid, op.dest_oid)] = {
                    "data": data,
                    "size": len(data),
                    "xattrs": dict(st["xattrs"]),
                    "omap": dict(st["omap"]),
                    "dirty_data": True,
                    "key": (op.dest_cid, op.dest_oid),
                }
            else:
                raise StoreError(f"unknown transaction op {op.op}")

        # phase 2: write dirty data to fresh extents (COW), fdatasync
        batch = Batch()
        new_extents: dict[tuple[str, str], tuple] = {}
        allocated: list[tuple[int, int]] = []
        try:
            for key, st in staged.items():
                if st is None or not st["dirty_data"]:
                    continue
                data = bytes(st["data"])
                if len(data) <= self.inline_threshold:
                    new_extents[key] = (data, [], [], None, 0)
                    continue
                comp_name = None
                stored = data
                if self._compressor is not None:
                    packed = self._compressor.compress(data)
                    # keep compression only when it saves whole blocks —
                    # the allocation granularity (reference: blobs are
                    # kept raw unless the required_ratio is met)
                    if (-(-len(packed) // self.block_size)
                            < -(-len(data) // self.block_size)):
                        stored = packed
                        comp_name = self._comp_name
                want = -(-len(stored) // self.block_size)
                extents = self._alloc.allocate(want)
                allocated.extend(extents)
                crcs = self._dev_write(extents, stored)
                new_extents[key] = (None, extents, crcs, comp_name,
                                    len(stored))
            if any(e for _, e, _c, _n, _l in new_extents.values()):
                self._dev.flush()
                if self._sync:
                    os.fdatasync(self._dev.fileno())
        except Exception:
            for s, n in allocated:
                self._alloc.release(s, n)
            raise

        # phase 3: one atomic KV batch
        for cid in colls - self._colls:
            batch.set(_ckey(cid), b"1")
        for cid in self._colls - colls:
            batch.rm(_ckey(cid))
        freed: list[tuple[int, int]] = []
        new_onodes: dict[tuple[str, str], Onode] = {}
        for key, st in staged.items():
            cid, oid = key
            old = self._onodes.get(key)
            if st is None:
                if old is not None:
                    batch.rm(_nkey(cid, oid))
                    for name in old.xattrs:
                        batch.rm(_akey(cid, oid, name))
                    for k in old.omap:
                        batch.rm(_okey(cid, oid, k))
                    freed.extend(old.extents)
                continue
            onode = Onode()
            onode.size = (
                len(st["data"]) if st["dirty_data"] else st["size"]
            )
            if key in new_extents:
                inline, extents, crcs, comp, clen = new_extents[key]
                onode.inline = inline
                onode.extents = extents
                onode.crcs = crcs
                onode.comp = comp
                onode.clen = clen
                if old is not None:
                    freed.extend(old.extents)
            elif old is not None:
                onode.inline = old.inline
                onode.extents = old.extents
                onode.crcs = old.crcs
                onode.comp = old.comp
                onode.clen = old.clen
            onode.xattrs = dict(st["xattrs"])
            onode.omap = dict(st["omap"])
            batch.set(_nkey(cid, oid), onode.encode())
            old_x = old.xattrs if old else {}
            for name in set(old_x) - set(onode.xattrs):
                batch.rm(_akey(cid, oid, name))
            for name, v in onode.xattrs.items():
                if old_x.get(name) != v:
                    batch.set(_akey(cid, oid, name), v)
            old_o = old.omap if old else {}
            for k in set(old_o) - set(onode.omap):
                batch.rm(_okey(cid, oid, k))
            for k, v in onode.omap.items():
                if old_o.get(k) != v:
                    batch.set(_okey(cid, oid, k), v)
            new_onodes[key] = onode
        try:
            self._kv.submit_batch(batch, sync=self._sync)
        except Exception:
            # KV failed: the new COW extents are unreferenced — reclaim
            for s, n in allocated:
                self._alloc.release(s, n)
            raise

        # phase 4: RAM state + release replaced extents (only after the KV
        # committed, so the switch is all-or-nothing)
        self._colls = colls
        self._onodes.update(new_onodes)
        for key, st in staged.items():
            if st is None:
                self._onodes.pop(key, None)
        for s, n in freed:
            self._alloc.release(s, n)

    # -- reads -------------------------------------------------------------
    def _get(self, cid: str, oid: str) -> Onode:
        if cid not in self._colls:
            raise NotFound(f"collection {cid}")
        o = self._onodes.get((cid, oid))
        if o is None:
            raise NotFound(f"object {cid}/{oid}")
        return o

    def read(self, cid: str, oid: str, off: int = 0, length: int = -1) -> bytes:
        with self._lock:
            data = self._dev_read(self._get(cid, oid))
        if length < 0:
            return data[off:]
        return data[off : off + length]

    def stat(self, cid: str, oid: str) -> dict:
        with self._lock:
            o = self._get(cid, oid)
            return {"size": o.size}

    def getattr(self, cid: str, oid: str, name: str) -> bytes:
        with self._lock:
            o = self._get(cid, oid)
            if name not in o.xattrs:
                raise NotFound(f"xattr {name}")
            return o.xattrs[name]

    def getattrs(self, cid: str, oid: str) -> dict[str, bytes]:
        with self._lock:
            return dict(self._get(cid, oid).xattrs)

    def omap_get(self, cid: str, oid: str) -> dict[str, bytes]:
        with self._lock:
            return dict(self._get(cid, oid).omap)

    def list_collections(self) -> list[str]:
        with self._lock:
            return sorted(self._colls)

    def list_objects(self, cid: str) -> list[str]:
        with self._lock:
            if cid not in self._colls:
                raise NotFound(f"collection {cid}")
            return sorted(o for c, o in self._onodes if c == cid)

    def collection_bytes(self, cid: str) -> int:
        with self._lock:
            return sum(
                onode.size for (c, o), onode in self._onodes.items()
                if c == cid and not o.startswith("_")
            )

    def statfs(self) -> dict:
        # allocator truth, not onode sums: compression and block
        # rounding make logical size diverge from device usage
        total = self.n_blocks * self.block_size
        with self._lock:
            free = (self._alloc.free_blocks * self.block_size
                    if self._alloc else total)
        return {"total": total, "used": total - free, "avail": free}

    def collections_bytes(self) -> dict[str, int]:
        # single pass over the onode index (collection_bytes per cid
        # would rescan all onodes once per collection)
        with self._lock:
            out = {cid: 0 for cid in self._colls}
            for (c, o), onode in self._onodes.items():
                if not o.startswith("_") and c in out:
                    out[c] += onode.size
            return out

    # -- fsck --------------------------------------------------------------
    def fsck(self, deep: bool = False, repair: bool = False) -> dict:
        """Extent audit + optional data crc verify (reference:
        BlueStore::_fsck / ceph-bluestore-tool).  Returns a report; with
        repair=True leaked blocks are reclaimed (they already are at
        mount; this validates the invariant on a live store)."""
        with self._lock:
            report = {
                "objects": len(self._onodes),
                "errors": [],
                "leaked_blocks": 0,
            }
            used = {}
            for key, onode in self._onodes.items():
                seen = 0
                for start, n in onode.extents:
                    if start + n > self.n_blocks:
                        report["errors"].append(
                            f"{key}: extent ({start},{n}) out of range"
                        )
                        continue
                    for b in range(start, start + n):
                        if b in used:
                            report["errors"].append(
                                f"{key}: block {b} also used by {used[b]}"
                            )
                        used[b] = key
                    seen += n * self.block_size
                if onode.inline is None and seen < onode.stored_len():
                    report["errors"].append(
                        f"{key}: extents cover {seen} < stored "
                        f"{onode.stored_len()}"
                    )
                if deep:
                    try:
                        self._dev_read(onode, verify=True)
                    except StoreError as e:
                        report["errors"].append(f"{key}: {e}")
            report["used_blocks"] = len(used)
            report["free_blocks"] = self._alloc.free_blocks
            leaked = self.n_blocks - len(used) - self._alloc.free_blocks
            report["leaked_blocks"] = leaked
            if repair and leaked:
                # rebuild the freelist from the onode walk (what mount
                # does): fresh allocator, re-mark referenced extents
                self._alloc = make_allocator(self.n_blocks)
                for onode in self._onodes.values():
                    for start, n in onode.extents:
                        self._alloc.mark_used(start, n)
                report["repaired"] = leaked
                report["free_blocks"] = self._alloc.free_blocks
            return report
