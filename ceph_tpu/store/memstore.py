"""MemStore — in-RAM ObjectStore (reference: src/os/memstore/MemStore.{h,cc};
SURVEY.md §4 ring 3: the unit-test backend so OSD-level tests need no disk).
"""
from __future__ import annotations

from ..common.lockdep import make_lock
from typing import Callable

from .object_store import Collection, NotFound, ObjectStore, Transaction


class MemStore(ObjectStore):
    def __init__(self):
        self._colls: dict[str, Collection] = {}
        self._lock = make_lock("store::memstore")

    def queue_transaction(
        self, t: Transaction, on_commit: Callable[[], None] | None = None
    ) -> None:
        # torn-write injection (docs/fault_injection.md): an error BEFORE
        # the apply fails the txn with nothing durable; one AFTER fails
        # the caller although the txn committed — the crash-between-ack-
        # and-apply shapes recovery must absorb
        self._fp_hit("osd.store.write_before_commit")
        with self._lock:
            self.apply_atomic(self._colls, t)
        self._fp_hit("osd.store.write_after_commit")
        if on_commit:
            on_commit()

    def _object(self, cid: str, oid: str):
        c = self._colls.get(cid)
        if c is None:
            raise NotFound(f"collection {cid}")
        o = c.objects.get(oid)
        if o is None:
            raise NotFound(f"object {cid}/{oid}")
        return o

    def read(self, cid: str, oid: str, off: int = 0, length: int = -1) -> bytes:
        with self._lock:
            o = self._object(cid, oid)
            if length < 0:
                return bytes(o.data[off:])
            return bytes(o.data[off : off + length])

    def stat(self, cid: str, oid: str) -> dict:
        with self._lock:
            o = self._object(cid, oid)
            return {"size": len(o.data)}

    def getattr(self, cid: str, oid: str, name: str) -> bytes:
        with self._lock:
            o = self._object(cid, oid)
            if name not in o.xattrs:
                raise NotFound(f"xattr {name} on {cid}/{oid}")
            return o.xattrs[name]

    def getattrs(self, cid: str, oid: str) -> dict[str, bytes]:
        with self._lock:
            return dict(self._object(cid, oid).xattrs)

    def omap_get(self, cid: str, oid: str) -> dict[str, bytes]:
        with self._lock:
            return dict(self._object(cid, oid).omap)

    def list_collections(self) -> list[str]:
        with self._lock:
            return sorted(self._colls)

    def list_objects(self, cid: str) -> list[str]:
        with self._lock:
            c = self._colls.get(cid)
            if c is None:
                raise NotFound(f"collection {cid}")
            return sorted(c.objects)
