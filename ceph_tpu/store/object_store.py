"""ObjectStore interface + Transaction (reference: src/os/ObjectStore.h ::
ObjectStore, Transaction; SURVEY.md §2.4).

A Transaction is a serialized list of ops applied all-or-nothing by
`queue_transaction` — the OSD's PGBackend builds one per client write
(reference: §3.1 "BlueStore txc commit").  Objects live in collections
(= PGs); object identity is (collection, oid).  The op set covers what the
data plane uses: object data (write/zero/truncate/remove), xattrs, omap,
collection lifecycle, and rename for recovery temp objects.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable

from ..common.buffer import BufferList, BufferListIterator
from ..common.failpoint import failpoint as _failpoint, registry as _fp_registry


class StoreError(RuntimeError):
    pass


class NotFound(StoreError, KeyError):
    pass


# Transaction op codes (reference: Transaction::OP_*)
OP_TOUCH = 1
OP_WRITE = 2
OP_ZERO = 3
OP_TRUNCATE = 4
OP_REMOVE = 5
OP_SETATTR = 6
OP_RMATTR = 7
OP_OMAP_SETKEYS = 8
OP_OMAP_RMKEYS = 9
OP_OMAP_CLEAR = 10
OP_MKCOLL = 11
OP_RMCOLL = 12
OP_COLL_MOVE_RENAME = 13
OP_TRY_MKCOLL = 14  # idempotent create (collection may already exist)


@dataclass
class Op:
    op: int
    cid: str = ""
    oid: str = ""
    off: int = 0
    length: int = 0
    data: bytes = b""
    name: str = ""
    keys: dict[str, bytes] = field(default_factory=dict)
    dest_cid: str = ""
    dest_oid: str = ""


class Transaction:
    """Ordered op list with all-or-nothing apply semantics."""

    def __init__(self):
        self.ops: list[Op] = []

    def __len__(self) -> int:
        return len(self.ops)

    # -- object data ------------------------------------------------------
    def touch(self, cid: str, oid: str) -> "Transaction":
        self.ops.append(Op(OP_TOUCH, cid, oid))
        return self

    def write(self, cid: str, oid: str, off: int, data) -> "Transaction":
        self.ops.append(Op(OP_WRITE, cid, oid, off=off, data=bytes(BufferList(data))))
        return self

    def zero(self, cid: str, oid: str, off: int, length: int) -> "Transaction":
        self.ops.append(Op(OP_ZERO, cid, oid, off=off, length=length))
        return self

    def truncate(self, cid: str, oid: str, size: int) -> "Transaction":
        self.ops.append(Op(OP_TRUNCATE, cid, oid, off=size))
        return self

    def remove(self, cid: str, oid: str) -> "Transaction":
        self.ops.append(Op(OP_REMOVE, cid, oid))
        return self

    # -- xattrs -----------------------------------------------------------
    def setattr(self, cid: str, oid: str, name: str, value) -> "Transaction":
        self.ops.append(
            Op(OP_SETATTR, cid, oid, name=name, data=bytes(BufferList(value)))
        )
        return self

    def rmattr(self, cid: str, oid: str, name: str) -> "Transaction":
        self.ops.append(Op(OP_RMATTR, cid, oid, name=name))
        return self

    # -- omap -------------------------------------------------------------
    def omap_setkeys(self, cid: str, oid: str, keys: dict[str, bytes]) -> "Transaction":
        self.ops.append(Op(OP_OMAP_SETKEYS, cid, oid, keys=dict(keys)))
        return self

    def omap_rmkeys(self, cid: str, oid: str, keys: Iterable[str]) -> "Transaction":
        self.ops.append(
            Op(OP_OMAP_RMKEYS, cid, oid, keys={k: b"" for k in keys})
        )
        return self

    def omap_clear(self, cid: str, oid: str) -> "Transaction":
        self.ops.append(Op(OP_OMAP_CLEAR, cid, oid))
        return self

    # -- collections ------------------------------------------------------
    def create_collection(self, cid: str) -> "Transaction":
        self.ops.append(Op(OP_MKCOLL, cid))
        return self

    def try_create_collection(self, cid: str) -> "Transaction":
        """Create-if-missing (the OSD touches its shard collection on every
        write; reference: OSD collections are created at PG instantiation,
        but this daemon creates them lazily)."""
        self.ops.append(Op(OP_TRY_MKCOLL, cid))
        return self

    def remove_collection(self, cid: str) -> "Transaction":
        self.ops.append(Op(OP_RMCOLL, cid))
        return self

    def collection_move_rename(
        self, cid: str, oid: str, dest_cid: str, dest_oid: str
    ) -> "Transaction":
        self.ops.append(
            Op(OP_COLL_MOVE_RENAME, cid, oid, dest_cid=dest_cid, dest_oid=dest_oid)
        )
        return self

    def append(self, other: "Transaction") -> "Transaction":
        self.ops.extend(other.ops)
        return self

    # -- wire/WAL encoding (used by KStore's log and the OSD's repops) ----
    def encode(self) -> BufferList:
        bl = BufferList()
        bl.append_u32(len(self.ops))
        for op in self.ops:
            bl.append_u8(op.op)
            bl.append_str(op.cid)
            bl.append_str(op.oid)
            bl.append_u64(op.off)
            bl.append_u64(op.length)
            bl.append_str(op.data)
            bl.append_str(op.name)
            bl.append_str(op.dest_cid)
            bl.append_str(op.dest_oid)
            bl.append_u32(len(op.keys))
            for k, v in op.keys.items():
                bl.append_str(k)
                bl.append_str(v)
        return bl

    @classmethod
    def decode(cls, it: BufferListIterator | bytes) -> "Transaction":
        if not isinstance(it, BufferListIterator):
            it = BufferListIterator(bytes(it))
        t = cls()
        for _ in range(it.get_u32()):
            op = Op(it.get_u8())
            op.cid = it.get_str()
            op.oid = it.get_str()
            op.off = it.get_u64()
            op.length = it.get_u64()
            op.data = it.get_str_bytes()
            op.name = it.get_str()
            op.dest_cid = it.get_str()
            op.dest_oid = it.get_str()
            op.keys = {}
            for _ in range(it.get_u32()):
                k = it.get_str()
                op.keys[k] = it.get_str_bytes()
            t.ops.append(op)
        return t


@dataclass
class Object:
    data: bytearray = field(default_factory=bytearray)
    xattrs: dict[str, bytes] = field(default_factory=dict)
    omap: dict[str, bytes] = field(default_factory=dict)


@dataclass
class Collection:
    objects: dict[str, Object] = field(default_factory=dict)


class ObjectStore:
    """Backend contract (reference: ObjectStore pure virtuals the OSD uses)."""

    def mount(self) -> None:  # reference: ObjectStore::mount
        pass

    def umount(self) -> None:
        pass

    def _fp_hit(self, name: str) -> None:
        """Evaluate a store-layer failpoint with this store's owner tags
        (fp_entity/fp_cct, stamped by the owning OSD) so per-daemon
        entries match — shared by every backend's commit path.  The
        configured() guard keeps the off-state commit path free (this
        runs twice per transaction on every OSD)."""
        if _fp_registry().configured(name):
            _failpoint(name, cct=getattr(self, "fp_cct", None),
                       entity=getattr(self, "fp_entity", None))

    # -- writes -----------------------------------------------------------
    def queue_transaction(
        self, t: Transaction, on_commit: Callable[[], None] | None = None
    ) -> None:
        raise NotImplementedError

    # -- reads ------------------------------------------------------------
    def read(self, cid: str, oid: str, off: int = 0, length: int = -1) -> bytes:
        raise NotImplementedError

    def stat(self, cid: str, oid: str) -> dict:
        raise NotImplementedError

    def exists(self, cid: str, oid: str) -> bool:
        try:
            self.stat(cid, oid)
            return True
        except NotFound:
            return False

    def getattr(self, cid: str, oid: str, name: str) -> bytes:
        raise NotImplementedError

    def getattrs(self, cid: str, oid: str) -> dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, cid: str, oid: str) -> dict[str, bytes]:
        raise NotImplementedError

    def list_collections(self) -> list[str]:
        raise NotImplementedError

    def collection_exists(self, cid: str) -> bool:
        return cid in self.list_collections()

    def list_objects(self, cid: str) -> list[str]:
        raise NotImplementedError

    def collection_bytes(self, cid: str) -> int:
        """Total logical object bytes in a collection (stats-report path —
        backends override with their O(metadata) walk; this default pays a
        stat per object)."""
        return sum(
            self.stat(cid, o)["size"] for o in self.list_objects(cid)
            if not o.startswith("_")
        )

    def collections_bytes(self) -> dict[str, int]:
        """{cid: bytes} for every collection in ONE metadata pass — the
        per-report-tick stats surface (a per-collection loop over a
        store-wide index would be O(collections x objects))."""
        return {
            cid: self.collection_bytes(cid)
            for cid in self.list_collections()
        }

    def statfs(self) -> dict:
        """{total, used, avail} device bytes (reference:
        ObjectStore::statfs — feeds `ceph df` / `ceph osd df`).
        Backends without a real device report a nominal 1 GiB device
        with logical usage."""
        used = sum(self.collections_bytes().values())
        total = max(1 << 30, used)  # invariant: used <= total
        return {"total": total, "used": used,
                "avail": total - used}

    # -- shared Transaction interpreter ------------------------------------
    # Backends that materialize state as {cid: Collection} dicts reuse this
    # (MemStore applies directly; KStore applies to its in-RAM image after
    # the WAL commit).
    @staticmethod
    def apply_atomic(colls: dict[str, Collection], t: Transaction) -> None:
        """All-or-nothing apply (the Transaction contract, reference:
        ObjectStore.h 'transactions are atomic').  Rollback state is
        O(touched objects), not O(collection): only the objects the
        transaction names are snapshotted; collection-level ops save the
        Collection reference (MKCOLL/RMCOLL only ever add/remove an empty
        one, so the reference plus the touched-object snapshots restore
        everything)."""
        import copy

        saved_objs: dict[tuple[str, str], Object | None] = {}
        for op in t.ops:
            for cid, oid in ((op.cid, op.oid), (op.dest_cid, op.dest_oid)):
                if oid and (cid, oid) not in saved_objs:
                    c = colls.get(cid)
                    o = c.objects.get(oid) if c else None
                    saved_objs[(cid, oid)] = copy.deepcopy(o)
        coll_cids = {op.cid for op in t.ops if not op.oid}
        saved_colls = {cid: colls.get(cid) for cid in coll_cids}
        try:
            ObjectStore._apply(colls, t)
        except Exception:
            for cid, c in saved_colls.items():
                if c is None:
                    colls.pop(cid, None)
                else:
                    colls[cid] = c
            for (cid, oid), o in saved_objs.items():
                c = colls.get(cid)
                if c is None:
                    continue
                if o is None:
                    c.objects.pop(oid, None)
                else:
                    c.objects[oid] = o
            raise

    @staticmethod
    def _apply(colls: dict[str, Collection], t: Transaction) -> None:
        for op in t.ops:
            if op.op == OP_MKCOLL:
                if op.cid in colls:
                    raise StoreError(f"collection {op.cid} exists")
                colls[op.cid] = Collection()
                continue
            if op.op == OP_TRY_MKCOLL:
                colls.setdefault(op.cid, Collection())
                continue
            if op.op == OP_RMCOLL:
                c = colls.get(op.cid)
                if c is None:
                    raise NotFound(f"collection {op.cid}")
                if c.objects:
                    raise StoreError(f"collection {op.cid} not empty")
                del colls[op.cid]
                continue
            c = colls.get(op.cid)
            if c is None:
                raise NotFound(f"collection {op.cid}")
            if op.op == OP_TOUCH:
                c.objects.setdefault(op.oid, Object())
                continue
            if op.op == OP_WRITE:
                o = c.objects.setdefault(op.oid, Object())
                end = op.off + len(op.data)
                if len(o.data) < end:
                    o.data.extend(b"\0" * (end - len(o.data)))
                o.data[op.off : end] = op.data
                continue
            o = c.objects.get(op.oid)
            if o is None:
                raise NotFound(f"object {op.cid}/{op.oid}")
            if op.op == OP_ZERO:
                end = op.off + op.length
                if len(o.data) < end:
                    o.data.extend(b"\0" * (end - len(o.data)))
                o.data[op.off : end] = b"\0" * op.length
            elif op.op == OP_TRUNCATE:
                size = op.off
                if len(o.data) > size:
                    del o.data[size:]
                else:
                    o.data.extend(b"\0" * (size - len(o.data)))
            elif op.op == OP_REMOVE:
                del c.objects[op.oid]
            elif op.op == OP_SETATTR:
                o.xattrs[op.name] = op.data
            elif op.op == OP_RMATTR:
                o.xattrs.pop(op.name, None)
            elif op.op == OP_OMAP_SETKEYS:
                o.omap.update(op.keys)
            elif op.op == OP_OMAP_RMKEYS:
                for k in op.keys:
                    o.omap.pop(k, None)
            elif op.op == OP_OMAP_CLEAR:
                o.omap.clear()
            elif op.op == OP_COLL_MOVE_RENAME:
                dest = colls.get(op.dest_cid)
                if dest is None:
                    raise NotFound(f"collection {op.dest_cid}")
                dest.objects[op.dest_oid] = o
                del c.objects[op.oid]
            else:
                raise StoreError(f"unknown transaction op {op.op}")


def create_store(
    kind: str,
    path: str | None = None,
    compression: str = "none",
    sync: bool = True,
    checksum: bool = True,
    device_size: int = 1 << 30,
) -> ObjectStore:
    """Factory (reference: ObjectStore::create keyed by `objectstore`;
    `compression`/`sync`/`checksum`/`device_size` are the
    objectstore_compression / objectstore_wal_sync /
    objectstore_checksum / bluestore_block_size options)."""
    from .kstore import KStore
    from .memstore import MemStore

    if kind == "memstore":
        return MemStore()
    if kind in ("kstore", "filestore"):
        if not path:
            raise StoreError(f"{kind} requires a path")
        return KStore(path, sync=sync, compression=compression)
    if kind == "bluestore":
        from .bluestore import BlueStore

        if not path:
            raise StoreError("bluestore requires a path")
        return BlueStore(
            path, device_size=device_size, sync=sync, checksum=checksum,
            compression=compression or "none",
        )
    raise StoreError(f"unknown objectstore {kind!r}")
