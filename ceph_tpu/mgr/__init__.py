"""Manager plane (reference: src/mgr + src/pybind/mgr; SURVEY.md §2.5)."""
from .daemon import MgrDaemon
from .module import MgrModule, MODULE_REGISTRY

__all__ = ["MgrDaemon", "MgrModule", "MODULE_REGISTRY"]
