"""progress — recovery/backfill progress events (reference:
src/pybind/mgr/progress/module.py: the mgr module that turns PG state
churn into named events with a completion fraction, served as `ceph
progress` and the one-line recovery bar in `ceph status`).

The cephheal wiring: every OSD's ``_mgr_report`` now ships per-PG
``degraded``/``misplaced``/``objects`` counts inside ``pg_info``.  The
:class:`ProgressTracker` (pure, synthesizable in tests) folds a time
series of those snapshots into per-PG recovery events:

- a PG first seen with ``degraded > 0`` opens an event whose baseline
  is the LARGEST degraded count seen (so the fraction is monotone even
  while more peers report in);
- ``progress = 1 - degraded / baseline``, clamped monotone;
- the ETA divides the remaining count by an exponentially smoothed
  drain rate;
- a PG back at ``degraded == 0`` completes its event (kept briefly for
  `ceph progress` display);
- a PG degraded with ~zero drain past ``mgr_recovery_stalled_grace``
  seconds — while the cluster-wide recovery-op rate
  (``metrics_history.rate("osd.recovery_ops")``) is also ~zero — is
  STALLED: the mon raises RECOVERY_STALLED naming it (plus any PG whose
  recovery pass raises every tick, the OSDs' ``recovery_failing``
  reports).

The module's snapshot rides the status module's mon digest, so the mon
answers the ``progress`` command and renders the status bar without a
channel to the mgr (the `perf history` precedent).
"""
from __future__ import annotations

import time

from ..common.lockdep import make_lock
from .module import MgrModule, register_module

#: completed events kept for display
_MAX_DONE = 32
#: drain-rate smoothing factor (EMA; higher = snappier ETA)
_RATE_ALPHA = 0.3


class _Event:
    __slots__ = ("pgid", "started", "baseline", "current", "rate",
                 "last_ts", "last_improve_ts", "best_fraction")

    def __init__(self, pgid: str, ts: float, degraded: int):
        self.pgid = pgid
        self.started = ts
        self.baseline = degraded
        self.current = degraded
        self.rate = 0.0           # objects/s drained, smoothed
        self.last_ts = ts
        self.last_improve_ts = ts
        self.best_fraction = 0.0  # monotone display clamp

    def fraction(self) -> float:
        """Monotone by contract: a mid-recovery regression (a second
        failure raising degraded again without exceeding the baseline)
        must not walk the `ceph status` bar backward — the raw fraction
        is clamped to the best seen."""
        if self.baseline <= 0:
            return 1.0
        raw = max(0.0, min(1.0, 1.0 - self.current / self.baseline))
        self.best_fraction = max(self.best_fraction, raw)
        return self.best_fraction

    def eta_seconds(self) -> float | None:
        if self.rate <= 1e-9 or self.current <= 0:
            return None
        return self.current / self.rate


class ProgressTracker:
    """Pure fold: (ts, {pgid: degraded}, recovery_rate) snapshots ->
    events/completed/stalled.  No clock reads of its own, so tests
    drive it with synthetic timestamps."""

    def __init__(self, stalled_grace: float = 10.0):
        self.stalled_grace = stalled_grace
        self._events: dict[str, _Event] = {}
        self._done: list[dict] = []
        self._recovery_rate = 0.0

    def update(self, ts: float, pg_degraded: dict[str, int],
               recovery_rate: float = 0.0) -> None:
        self._recovery_rate = recovery_rate
        for pgid, degraded in pg_degraded.items():
            degraded = max(0, int(degraded))
            ev = self._events.get(pgid)
            if ev is None:
                if degraded > 0:
                    self._events[pgid] = _Event(pgid, ts, degraded)
                continue
            dt = ts - ev.last_ts
            if degraded > ev.baseline:
                # more peers reported in: grow the baseline so the
                # fraction stays monotone instead of jumping backward
                ev.baseline = degraded
            if degraded < ev.current:
                drained = ev.current - degraded
                if dt > 0:
                    inst = drained / dt
                    ev.rate = (inst if ev.rate <= 0 else
                               _RATE_ALPHA * inst
                               + (1 - _RATE_ALPHA) * ev.rate)
                ev.last_improve_ts = ts
            elif degraded > ev.current:
                # a regression (second failure mid-recovery) restarts
                # the stall clock — recovery just got MORE to do, it is
                # not stuck the instant the new failure lands
                ev.last_improve_ts = ts
            ev.current = degraded
            ev.last_ts = ts
            if degraded == 0:
                self._done.append({
                    "pgid": pgid,
                    "message": f"recovery of pg {pgid}",
                    "progress": 1.0,
                    "started": ev.started,
                    "finished": ts,
                    "duration": round(ts - ev.started, 3),
                })
                del self._done[:-_MAX_DONE]
                del self._events[pgid]
        # a PG that vanished from the reports (pool deleted, primary
        # gone silent) must not sit at 60% forever
        for pgid in [p for p in self._events if p not in pg_degraded]:
            ev = self._events[pgid]
            if ts - ev.last_ts > 4 * max(self.stalled_grace, 1.0):
                del self._events[pgid]

    def events(self) -> list[dict]:
        out = []
        for ev in self._events.values():
            eta = ev.eta_seconds()
            out.append({
                "pgid": ev.pgid,
                "message": f"recovery of pg {ev.pgid}",
                "progress": round(ev.fraction(), 4),
                "degraded": ev.current,
                "baseline": ev.baseline,
                "rate_objects_per_sec": round(ev.rate, 3),
                "eta_seconds": None if eta is None else round(eta, 1),
                "started": ev.started,
            })
        return sorted(out, key=lambda e: e["pgid"])

    def completed(self) -> list[dict]:
        return list(self._done)

    def stalled(self, now: float) -> list[dict]:
        """PGs degraded with no drain past the grace while the cluster
        recovers ~nothing — the RECOVERY_STALLED inputs."""
        if self._recovery_rate > 0.1:
            return []
        out = []
        for ev in self._events.values():
            if ev.current > 0 and \
                    now - ev.last_improve_ts >= self.stalled_grace:
                out.append({
                    "pgid": ev.pgid,
                    "degraded": ev.current,
                    "stalled_for": round(now - ev.last_improve_ts, 1),
                })
        return sorted(out, key=lambda e: -e["degraded"])


@register_module
class ProgressModule(MgrModule):
    """The host loop: poll the OSDs' pg_info snapshots on
    ``mgr_progress_interval``, feed the tracker, export ceph_progress_*
    series, and hand the status module its digest section."""

    NAME = "progress"

    def __init__(self, mgr):
        super().__init__(mgr)
        self._lock = make_lock("mgr::progress")
        self.tracker = ProgressTracker(
            stalled_grace=float(
                self.cct.conf.get("mgr_recovery_stalled_grace")))

    def _pg_degraded(self) -> dict[str, int]:
        """{pgid: degraded} via the mgr's shared freshest-wins pg_info
        merge (also the balancer's degraded-gate input)."""
        return self.mgr.pg_degraded_by_pgid()

    def _recovery_failing(self) -> dict[str, dict]:
        """{pgid: {count, error, daemon}} union of the OSDs'
        repeat-failing recovery reports."""
        out: dict[str, dict] = {}
        for daemon, st in self.mgr.latest_stats().items():
            for pgid, rec in (st.get("recovery_failing") or {}).items():
                out[pgid] = {**rec, "daemon": daemon}
        return out

    def tick(self, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        stale = float(self.cct.conf.get("mgr_stale_report_age"))
        rate = sum((self.mgr.metrics_history.rate(
            "osd.recovery_ops", max_age=stale) or {}).values())
        with self._lock:
            self.tracker.stalled_grace = float(
                self.cct.conf.get("mgr_recovery_stalled_grace"))
            self.tracker.update(now, self._pg_degraded(), rate)
        self.export(now, rate)

    def snapshot(self, now: float | None = None) -> dict:
        """The `ceph progress` payload / digest section."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return {
                "events": self.tracker.events(),
                "completed": self.tracker.completed(),
                "stalled": self.tracker.stalled(now),
                "failing": self._recovery_failing(),
            }

    def export(self, now: float, recovery_rate: float) -> None:
        """ceph_progress_* series through the mgr's own report sink
        (prometheus + metrics_history — the qos-module precedent)."""
        with self._lock:
            events = self.tracker.events()
            stalled = self.tracker.stalled(now)
        counters = {"progress": {
            "events_active": len(events),
            "objects_degraded": sum(e["degraded"] for e in events),
            "recovery_rate": round(recovery_rate, 3),
            "stalled_pgs": len(stalled),
        }}
        self.mgr.ingest_local_report("mgr.progress", counters,
                                     schema=_PROGRESS_SCHEMA)

    def serve(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(timeout=float(
                self.cct.conf.get("mgr_progress_interval")))
            if self._stop.is_set():
                return
            try:
                self.tick()
            except Exception as e:
                # one torn report must not kill the loop
                self.cct.dout("mgr", 1, f"progress tick failed: {e!r}")


_PROGRESS_SCHEMA = {"progress": {
    "events_active": {"type": "gauge",
                      "description": "PG recovery/backfill events in "
                                     "flight"},
    "objects_degraded": {"type": "gauge",
                         "description": "object-copies currently "
                                        "degraded across tracked "
                                        "events"},
    "recovery_rate": {"type": "gauge",
                      "description": "cluster recovery push rate "
                                     "(objects/s, from "
                                     "metrics_history.rate)"},
    "stalled_pgs": {"type": "gauge",
                    "description": "degraded PGs with ~zero drain past "
                                   "mgr_recovery_stalled_grace"},
}}
