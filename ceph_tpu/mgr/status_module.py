"""Status module — cluster summary assembly (reference: the mgr side of
`ceph -s`/`ceph osd status`: src/pybind/mgr/status/module.py)."""
from __future__ import annotations

from .module import MgrModule, register_module


def assemble_osd_rows(m, stats: dict) -> list[dict]:
    """Per-OSD status rows — shared by `ceph osd status` (this module)
    and the dashboard's /api/osd so they can never drift apart."""
    rows = []
    if m is not None:
        for o in range(m.max_osd):
            if not m.exists(o):
                continue
            st = stats.get(f"osd.{o}", {})
            rows.append({
                "id": o,
                "up": int(m.is_up(o)),
                "in": int(m.is_in(o)),
                "pgs": st.get("num_pgs", 0),
                "objects": st.get("num_objects", 0),
            })
    return rows


@register_module
class StatusModule(MgrModule):
    NAME = "status"

    def osd_status(self) -> dict:
        m = self.get("osd_map")
        return {
            "epoch": m.epoch if m else 0,
            "osds": assemble_osd_rows(m, self.mgr.latest_stats()),
        }
