"""Status module — cluster summary assembly and the mon digest
(reference: the mgr side of `ceph -s`/`ceph osd status`
src/pybind/mgr/status/module.py, plus the MMonMgrReport digest the mgr
streams to the mon so MgrStatMonitor can answer `ceph df`/`pg dump`
from the monitor)."""
from __future__ import annotations

import weakref

from ..osd.osdmap import PG_POOL_ERASURE
from .module import MgrModule, register_module

#: assemble_osd_df's fallback scan, memoized per (map object, epoch) —
#: see the comment at its use site
_OSD_DF_MEMO: weakref.WeakKeyDictionary = weakref.WeakKeyDictionary()


def pool_usage(m, stats: dict) -> dict[int, dict]:
    """{pool_id: {"bytes": logical, "objects": n, "raw_bytes": raw}} —
    raw sums across daemon reports, logical divides out the redundancy
    factor (replica count, or size/k for EC)."""
    usage: dict[int, dict] = {}
    if m is None:
        return usage
    for pid, pool in m.pools.items():
        raw = 0
        objs = 0
        for st in stats.values():
            raw += int(st.get("pool_bytes", {}).get(str(pid), 0))
            objs += int(st.get("pool_objects", {}).get(str(pid), 0))
        if pool.type == PG_POOL_ERASURE:
            prof = m.ec_profiles.get(pool.ec_profile or "", {})
            k = int(prof.get("k", 2))
            factor = pool.size / max(k, 1)
        else:
            factor = max(pool.size, 1)
        usage[pid] = {
            "bytes": int(raw / factor),
            # object counts are per-replica too: each copy/shard is
            # one store object
            "objects": objs // max(pool.size, 1),
            "raw_bytes": raw,
            "factor": factor,
        }
    return usage


def assemble_df(m, stats: dict) -> dict:
    """`ceph df` payload (reference: PGMap::dump_cluster_stats +
    dump_pool_stats_full)."""
    total = used = avail = 0
    for st in stats.values():
        sf = st.get("statfs") or {}
        total += int(sf.get("total", 0))
        used += int(sf.get("used", 0))
        avail += int(sf.get("avail", 0))
    usage = pool_usage(m, stats)
    pools = []
    if m is not None:
        for pid, pool in sorted(m.pools.items()):
            u = usage.get(pid, {})
            factor = u.get("factor", 1) or 1
            stored = u.get("bytes", 0)
            max_avail = int(avail / factor)
            denom = stored + max_avail
            pools.append({
                "id": pid,
                "name": pool.name,
                "stored": stored,
                "objects": u.get("objects", 0),
                "kb_used": -(-u.get("raw_bytes", 0) // 1024),
                "percent_used": stored / denom if denom else 0.0,
                "max_avail": max_avail,
                "quota_bytes": pool.quota_max_bytes,
                "quota_objects": pool.quota_max_objects,
            })
    return {
        "stats": {
            "total_bytes": total,
            "total_used_raw_bytes": used,
            "total_avail_bytes": avail,
        },
        "pools": pools,
    }


def assemble_osd_df(m, stats: dict, placement: list | None = None,
                    skew: dict | None = None) -> dict:
    """`ceph osd df` payload (reference: OSDMonitor print_utilization
    via PGMap::dump_osd_stats).

    cephplace: the deviation/skew columns come from the SHARED scoring
    core (osd/placement.py) — `placement` accepts the placement
    module's cached per-OSD rows and `skew` its cluster-level
    max_deviation/stddev (so the summary shares the core's unrounded
    metrics instead of re-deriving them from rounded rows); absent a
    module, the core computes both here from a fresh batched scan."""
    if placement is None and m is not None and m.pools:
        # memoized per MAP OBJECT (weak — no hidden state written onto
        # the domain object) and validated by epoch (mon-side mutators
        # bump epoch in place), so the fallback costs one batched scan
        # per epoch — not one per digest tick — when the placement
        # module isn't hosted to hand us its cached rows
        try:
            hit = _OSD_DF_MEMO.get(m)
            if hit is not None and hit[0] == m.epoch:
                placement, skew = hit[1], hit[2]
            else:
                from ..osd.placement import cluster_report, osd_rows

                report = cluster_report(m)
                placement = osd_rows(report, m)
                skew = {"max_deviation": report["max_deviation"],
                        "stddev": report["stddev"]}
                _OSD_DF_MEMO[m] = (m.epoch, placement, skew)
        except Exception:
            placement = skew = None  # torn map mid-change: skip
    by_osd = {r["osd"]: r for r in (placement or [])}
    rows = []
    if m is not None:
        for o in range(m.max_osd):
            if not m.exists(o):
                continue
            st = stats.get(f"osd.{o}", {})
            sf = st.get("statfs") or {}
            total = int(sf.get("total", 0))
            used = int(sf.get("used", 0))
            pl = by_osd.get(o) or {}
            rows.append({
                "id": o,
                "up": int(m.is_up(o)),
                "in": int(m.is_in(o)),
                "reweight": m.osd_weight[o] / 0x10000,
                "size": total,
                "use": used,
                "avail": int(sf.get("avail", 0)),
                "utilization": used / total if total else 0.0,
                "pgs": st.get("num_pgs", 0),
                # scoring-core columns (shards mapped by the batched
                # scan vs the weight-proportional ideal)
                "pgs_mapped": pl.get("shards", 0),
                "target": pl.get("target", 0.0),
                "deviation": pl.get("deviation", 0.0),
            })
    n = len(rows) or 1
    if skew is None:
        # last resort (rows handed in without the core's summary):
        # recompute over ELIGIBLE OSDs only, matching skew_metrics —
        # an out OSD's 0.0 row must not dilute stddev
        devs = [r["deviation"] for r in rows
                if (by_osd.get(r["id"]) or {}).get("eligible")]
        skew = {
            "max_deviation": max((abs(d) for d in devs), default=0.0),
            "stddev": ((sum(d * d for d in devs) / len(devs)) ** 0.5
                       if devs else 0.0),
        }
    return {
        "nodes": rows,
        "summary": {
            "total_kb": sum(r["size"] for r in rows) // 1024,
            "total_kb_used": sum(r["use"] for r in rows) // 1024,
            "average_utilization":
                sum(r["utilization"] for r in rows) / n,
            "max_deviation": float(skew.get("max_deviation") or 0.0),
            "stddev": float(skew.get("stddev") or 0.0),
        },
    }


def assemble_osd_rows(m, stats: dict) -> list[dict]:
    """Per-OSD status rows — shared by `ceph osd status` (this module)
    and the dashboard's /api/osd so they can never drift apart."""
    rows = []
    if m is not None:
        for o in range(m.max_osd):
            if not m.exists(o):
                continue
            st = stats.get(f"osd.{o}", {})
            rows.append({
                "id": o,
                "up": int(m.is_up(o)),
                "in": int(m.is_in(o)),
                "pgs": st.get("num_pgs", 0),
                "objects": st.get("num_objects", 0),
            })
    return rows


@register_module
class StatusModule(MgrModule):
    NAME = "status"

    def osd_status(self) -> dict:
        m = self.get("osd_map")
        return {
            "epoch": m.epoch if m else 0,
            "osds": assemble_osd_rows(m, self.mgr.latest_stats()),
        }

    def build_digest(self) -> dict:
        """The MMonMgrReport payload: everything the mon needs to
        answer `df`/`osd df`/`pg dump` without talking to OSDs."""
        m = self.get("osd_map")
        # ONE report snapshot feeds every section, so pg_info can never
        # name a daemon the slow-op/df views disagree about
        stats_ts = self.mgr.latest_stats_with_ts()
        stats = {d: s for d, (_t, s) in stats_ts.items()}
        # pg_info rows merged OLDEST-report-first so on a pgid collision
        # (primary change: the dead primary's last report lingers) the
        # FRESHEST author wins (cephheal)
        pg_info: dict[str, dict] = {}
        for _ts, st in sorted(stats_ts.values(), key=lambda tv: tv[0]):
            pg_info.update(st.get("pg_info") or {})
        slow = {d: int(st.get("slow_ops", 0))
                for d, st in stats.items() if st.get("slow_ops")}
        # per-daemon detail lines (cephmeter: each names its op's
        # dominant stage) ride along only for daemons with slow ops
        slow_detail = {d: st.get("slow_ops_detail")
                       for d, st in stats.items()
                       if st.get("slow_ops") and st.get("slow_ops_detail")}
        # accelerator health (common/kernel_telemetry.py): forward only
        # daemons with something to report — a degraded sentinel or an
        # active kernel-fallback latch — so the digest stays small and
        # the mon's checks key directly off presence
        backend: dict[str, dict] = {}
        for d, st in stats.items():
            bh = st.get("backend_health") or {}
            sent = bh.get("sentinel") or {}
            if sent.get("state") == "degraded" or bh.get("fallback"):
                backend[d] = bh
        # cephheal: the progress module's event/stalled snapshot rides
        # the digest so the mon can answer `progress`, render the
        # `ceph status` recovery line, and raise RECOVERY_STALLED —
        # tolerant of the module not being hosted
        progress = None
        prog_mod = self.mgr._modules.get("progress")
        if prog_mod is not None:
            try:
                progress = prog_mod.snapshot()
            except Exception as e:
                self.cct.dout("mgr", 3,
                              f"progress snapshot failed: {e!r}")
        # cephplace: the placement module's skew/diff snapshot and the
        # balancer's pass stats ride the digest so the mon answers
        # `placement diff`/`balancer status` and raises PG_IMBALANCE —
        # tolerant of either module not being hosted
        placement = None
        placement_rows = placement_skew = None
        pl_mod = self.mgr._modules.get("placement")
        if pl_mod is not None:
            try:
                placement = pl_mod.snapshot()
                # rows + skew come from ONE locked report snapshot so a
                # scan landing mid-digest can't mismatch them
                placement_rows, placement_skew = pl_mod.df_inputs()
            except Exception as e:
                self.cct.dout("mgr", 3,
                              f"placement snapshot failed: {e!r}")
        balancer = None
        bal_mod = self.mgr._modules.get("balancer")
        if bal_mod is not None:
            try:
                balancer = bal_mod.status()
            except Exception as e:
                self.cct.dout("mgr", 3,
                              f"balancer snapshot failed: {e!r}")
        return {
            "df": assemble_df(m, stats),
            "osd_df": assemble_osd_df(m, stats, placement=placement_rows,
                                      skew=placement_skew),
            "placement": placement,
            "balancer": balancer,
            "pg_info": pg_info,
            "slow_ops": slow,
            "slow_ops_detail": slow_detail,
            "backend_health": backend,
            "progress": progress,
            # compact metrics-history snapshot: the mon's `perf history`
            # command answers from this (cephmeter; the mon has no
            # channel TO the mgr, so the history rides the digest)
            "perf_history": self.mgr.metrics_history.digest(),
        }

    def serve(self) -> None:
        interval = float(self.cct.conf.get("mgr_digest_interval"))
        while not self._stop.wait(timeout=interval):
            try:
                rv, res = self.mon_command({
                    "prefix": "mgr digest",
                    "digest": self.build_digest(),
                })
                if rv != 0:
                    self.cct.dout("mgr", 3,
                                  f"digest push refused: {rv} {res}")
            except Exception as e:
                self.cct.dout("mgr", 3, f"digest push failed: {e!r}")
