"""Devicehealth module — device failure tracking and predictive
mark-out (reference: src/pybind/mgr/devicehealth/module.py: scrape
device metrics, evaluate life expectancy, mark failing devices out
before they lose data; SURVEY.md §2.5 'other mgr modules').

The analog's health signal is the integrity-error stream the data path
already produces — scrub-detected shard inconsistencies and store-level
CRC failures (the role SMART reallocated-sector/uncorrectable counts
play for physical drives; this framework's 'devices' are stores whose
rot manifests exactly as those counters).  Per OSD the module keeps a
bounded history of (time, error-count) samples, estimates an error
RATE, and:

- `warnings()` lists OSDs whose errors grew in the sampling window
  (the DEVICE_HEALTH health-check role);
- with `mgr_devicehealth_self_heal` on, an OSD whose cumulative error
  count crosses `mgr_devicehealth_mark_out_threshold` is marked OUT via
  the mon (the mark_out_threshold behavior), letting recovery drain it
  while it can still serve reads.

The reference's dedicated `device_health_metrics` pool is elided: the
mgr keeps the bounded in-memory history and the module command surface
(`status()`) exposes it; persistence across mgr restarts would add a
pool round-trip per scrape for no test-observable behavior here.
"""
from __future__ import annotations

import time

from .module import MgrModule, register_module

_HISTORY = 128  # samples per OSD (bounded memory)


@register_module
class DeviceHealthModule(MgrModule):
    NAME = "devicehealth"

    def __init__(self, mgr):
        super().__init__(mgr)
        # daemon -> [(monotonic_ts, cumulative_errors)]
        self.history: dict[str, list[tuple[float, int]]] = {}
        self.marked_out: set[int] = set()
        self.scrapes = 0

    @staticmethod
    def _errors_of(counters: dict) -> int:
        osd = counters.get("osd", {})
        return int(osd.get("scrub_errors", 0))

    def scrape_once(self) -> None:
        now = time.monotonic()
        for daemon, counters in self.get_all_perf_counters().items():
            if not daemon.startswith("osd."):
                continue
            errs = self._errors_of(counters)
            h = self.history.setdefault(daemon, [])
            h.append((now, errs))
            del h[:-_HISTORY]
        self.scrapes += 1
        if self.cct.conf.get("mgr_devicehealth_self_heal"):
            self._self_heal()

    def warnings(self) -> dict[str, dict]:
        """OSDs whose error count GREW within the retained window
        (reference: the DEVICE_HEALTH_* health checks)."""
        out = {}
        for daemon, h in self.history.items():
            if len(h) < 2:
                continue
            grew = h[-1][1] - h[0][1]
            if grew > 0:
                dt = max(h[-1][0] - h[0][0], 1e-9)
                out[daemon] = {
                    "errors": h[-1][1],
                    "new_errors": grew,
                    "rate_per_hour": round(grew / dt * 3600.0, 3),
                }
        return out

    def _self_heal(self) -> None:
        threshold = self.cct.conf.get("mgr_devicehealth_mark_out_threshold")
        min_ratio = self.cct.conf.get("mgr_devicehealth_min_in_ratio")
        m = self.get("osd_map")
        if m is None:
            return
        # marked_out only exists to bridge map-propagation delay: once the
        # map confirms an OSD is out, drop the entry — keeping it would
        # permanently undercount n_in and permanently exempt the OSD from
        # self-heal after an operator replaces the device and marks it
        # back in
        self.marked_out = {o for o in self.marked_out if m.is_in(o)}
        # the in-count is tracked LOCALLY across this pass (and debited
        # for mark-outs we already issued whose map hasn't propagated):
        # checking each candidate against the same stale map would let a
        # storm that pushes several OSDs over the threshold at once mark
        # them all out and sail through the floor one stale read at a time
        existing = [o for o in range(m.max_osd) if m.exists(o)]
        n_in = sum(
            1 for o in existing
            if m.is_in(o) and o not in self.marked_out
        )
        for daemon, h in self.history.items():
            if not h or h[-1][1] < threshold:
                continue
            osd = int(daemon.split(".", 1)[1])
            if osd in self.marked_out or not m.is_in(osd):
                continue
            # never self-heal the cluster into an outage: refuse once
            # the in-ratio would drop below the floor (reference:
            # devicehealth's mon_osd_min_in_ratio guard — a cluster-wide
            # error storm must not mark everything out)
            if existing and (n_in - 1) / len(existing) < min_ratio:
                self.cct.dout(
                    "mgr", 0,
                    f"devicehealth: NOT marking osd.{osd} out — in-ratio "
                    f"would drop below {min_ratio}",
                )
                continue
            rv, res = self.mon_command({"prefix": "osd out", "id": osd})
            if rv == 0:
                self.marked_out.add(osd)
                n_in -= 1
                self.cct.dout(
                    "mgr", 0,
                    f"devicehealth: marked osd.{osd} OUT "
                    f"({h[-1][1]} integrity errors >= {threshold})",
                )

    def status(self) -> dict:
        return {
            "scrapes": self.scrapes,
            "tracked": sorted(self.history),
            "warnings": self.warnings(),
            "marked_out": sorted(self.marked_out),
        }

    def serve(self) -> None:
        interval = self.cct.conf.get("mgr_tick_interval")
        while not self._stop.wait(interval):
            try:
                self.scrape_once()
            except Exception as e:  # pragma: no cover - defensive loop
                self.cct.dout("mgr", 1, f"devicehealth scrape failed: {e!r}")
