"""Quota module — stats-driven pool FULL_QUOTA flagging (reference: the
monitor's stats-driven pool quota enforcement in OSDMonitor — upstream
compares pg stats to quota_max_bytes/objects and sets FLAG_FULL_QUOTA;
here cluster stats live in the mgr, so the mgr runs the comparison and
flips the flag through a mon command).

Byte accounting note: daemon reports carry RAW stored bytes (all
replicas / all EC shards).  The comparison divides by the pool's
redundancy factor (size for replicated, (k+m)/k for EC) to approximate
the LOGICAL bytes a quota intuitively bounds, matching the reference's
num_bytes semantics.  Enforcement is eventually-consistent with the
report interval, like the reference's stats-lag window.
"""
from __future__ import annotations

import time

from .module import MgrModule, register_module


@register_module
class QuotaModule(MgrModule):
    NAME = "quota"

    def serve(self) -> None:
        interval = float(self.cct.conf.get("mgr_quota_interval"))
        while not self._stop.wait(timeout=interval):
            try:
                self.enforce_once()
            except Exception as e:
                self.cct.dout("mgr", 3, f"quota pass failed: {e!r}")

    def pool_usage(self) -> dict[int, dict]:
        """{pool_id: {"bytes": logical_estimate, "objects": n}} from the
        freshest daemon reports."""
        from .status_module import pool_usage

        return pool_usage(self.get("osd_map"), self.mgr.latest_stats())

    def enforce_once(self) -> list[str]:
        """Compare usage to quotas; flip full_quota where the state
        changed.  Returns the pools whose flag flipped."""
        m = self.get("osd_map")
        if m is None:
            return []
        usage = self.pool_usage()
        flipped = []
        for pid, pool in m.pools.items():
            if not (pool.quota_max_bytes or pool.quota_max_objects):
                continue
            u = usage.get(pid, {"bytes": 0, "objects": 0})
            over = (
                (pool.quota_max_bytes
                 and u["bytes"] >= pool.quota_max_bytes)
                or (pool.quota_max_objects
                    and u["objects"] >= pool.quota_max_objects)
            )
            have = "full_quota" in getattr(pool, "flags", ())
            if bool(over) != have:
                rv, _res = self.mon_command({
                    "prefix": "osd pool quota-flag",
                    "name": pool.name, "full": int(bool(over)),
                })
                if rv == 0:
                    flipped.append(pool.name)
        return flipped
