"""Prometheus exporter module (reference: src/pybind/mgr/prometheus/
module.py — text exposition of cluster health + daemon perf counters).

Serves GET /metrics on `mgr_prometheus_port` (0 = ephemeral; read
`.url` after start).  Metric naming follows the reference's scheme:
`ceph_osd_up`-style cluster gauges plus `ceph_daemon_...` counter series
labelled by daemon."""
from __future__ import annotations

import http.server
import threading

from .module import MgrModule, register_module


def render_metrics(osdmap, reports: dict) -> str:
    """Text exposition (the pure part, unit-testable without sockets)."""
    lines: list[str] = []

    def esc(v) -> str:
        # exposition-format label escaping: one bad pool name must not
        # poison the whole scrape
        return (
            str(v)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    def metric(name, doc, typ, samples):
        lines.append(f"# HELP {name} {doc}")
        lines.append(f"# TYPE {name} {typ}")
        for labels, value in samples:
            lab = (
                "{" + ",".join(f'{k}="{esc(v)}"' for k, v in labels.items()) + "}"
                if labels
                else ""
            )
            lines.append(f"{name}{lab} {value}")

    if osdmap is not None:
        metric(
            "ceph_osd_up", "OSD up state", "gauge",
            [
                ({"ceph_daemon": f"osd.{o}"}, int(osdmap.is_up(o)))
                for o in range(osdmap.max_osd)
                if osdmap.exists(o)
            ],
        )
        metric(
            "ceph_osd_in", "OSD in state", "gauge",
            [
                ({"ceph_daemon": f"osd.{o}"}, int(osdmap.is_in(o)))
                for o in range(osdmap.max_osd)
                if osdmap.exists(o)
            ],
        )
        metric(
            "ceph_osdmap_epoch", "OSDMap epoch", "gauge",
            [({}, osdmap.epoch)],
        )
        metric(
            "ceph_pool_pg_num", "PGs per pool", "gauge",
            [
                ({"pool": p.name}, p.pg_num)
                for p in osdmap.pools.values()
            ],
        )
    # per-daemon perf counters: flatten subsystem dumps into one series
    # per counter, labelled by daemon (the reference's ceph_daemon label)
    series: dict[str, list] = {}
    for daemon, subsystems in sorted(reports.items()):
        for subsys, counters in sorted((subsystems or {}).items()):
            for cname, value in sorted(counters.items()):
                if isinstance(value, dict):  # longrunavg {avgcount, sum}
                    for part, v in value.items():
                        key = f"ceph_{subsys}_{cname}_{part}"
                        series.setdefault(key, []).append(
                            ({"ceph_daemon": daemon}, v)
                        )
                else:
                    key = f"ceph_{subsys}_{cname}"
                    series.setdefault(key, []).append(
                        ({"ceph_daemon": daemon}, value)
                    )
    for key, samples in sorted(series.items()):
        metric(key, f"perf counter {key}", "counter", samples)
    return "\n".join(lines) + "\n"


@register_module
class PrometheusModule(MgrModule):
    NAME = "prometheus"

    def __init__(self, mgr):
        super().__init__(mgr)
        # bind SYNCHRONOUSLY (module construction happens inside
        # MgrDaemon.start) so `mgr.start(); module('prometheus').url`
        # never races the serve thread
        port = int(self.cct.conf.get("mgr_prometheus_port"))
        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), self._handler_class()
        )
        self.url = f"http://127.0.0.1:{self._server.server_address[1]}/metrics"

    def _handler_class(self):
        module = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    body = render_metrics(
                        module.get("osd_map"),
                        module.get_all_perf_counters(),
                    ).encode()
                except Exception as e:  # scrape must not kill the server
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        return Handler

    def serve(self) -> None:
        t = threading.Thread(
            target=self._server.serve_forever, name="mgr-prometheus-http",
            daemon=True,
        )
        t.start()
        self._stop.wait()
        self._server.shutdown()
        self._server.server_close()
