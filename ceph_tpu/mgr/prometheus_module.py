"""Prometheus exporter module (reference: src/pybind/mgr/prometheus/
module.py — text exposition of cluster health + daemon perf counters).

Serves GET /metrics on `mgr_prometheus_port` (0 = ephemeral; read
`.url` after start).  Metric naming follows the reference's scheme:
`ceph_osd_up`-style cluster gauges plus `ceph_daemon_...` counter series
labelled by daemon."""
from __future__ import annotations

import http.server
import threading

from ..common.perf_counters import HIST_LE
from .module import MgrModule, register_module

#: exposition-time cardinality guard for labeled (per-client) series:
#: at most this many label sets per daemon per labeled structure; the
#: overflow folds into one `_other_` row (sums preserved) — the second
#: bound after the OSD table's own top-K (docs/observability.md)
_MAX_LABEL_SETS = 256


def _sanitize_label(v) -> str:
    """Label-value hygiene for client entity names: control characters
    (incl. newline before esc() would see it) are stripped and the
    value is length-capped, so one hostile or mangled entity name
    cannot poison the exposition or explode a label.  Quotes and
    backslashes are handled by esc() at emission."""
    s = str(v)
    if any(ch < " " or ch == "\x7f" for ch in s):
        s = "".join(ch for ch in s if ch >= " " and ch != "\x7f")
    return s[:120] if len(s) > 120 else s


def _fold_labeled_rows(rows: list, cap: int = _MAX_LABEL_SETS) -> list:
    """Cap a labeled-row list, folding the tail (plus any pre-existing
    `_other_` rows) into ONE `_other_` row whose scalar fields sum and
    whose histograms merge bucket-by-bucket — counts survive the cap,
    only attribution is lost."""
    if len(rows) <= cap:
        return rows
    keep = [r for r in rows[:cap - 1]
            if (r.get("labels") or {}).get("client") != "_other_"]
    fold = [r for r in rows if r not in keep]
    merged: dict = {"labels": {
        k: "_other_" for k in (fold[0].get("labels") or {"client": 0})
    }}
    for row in fold:
        for f, v in row.items():
            if f == "labels":
                continue
            if isinstance(v, dict) and "buckets" in v:
                agg = merged.setdefault(f, {
                    "count": 0, "sum": 0.0,
                    "buckets": [0] * len(v["buckets"]),
                })
                agg["count"] += v.get("count", 0)
                agg["sum"] += v.get("sum", 0.0)
                for i, c in enumerate(v["buckets"]):
                    agg["buckets"][i] += c
            elif isinstance(v, (int, float)):
                merged[f] = merged.get(f, 0) + v
    return keep + [merged]


def render_metrics(osdmap, reports: dict, schema: dict | None = None,
                   health: dict | None = None) -> str:
    """Text exposition (the pure part, unit-testable without sockets).

    `schema` is the merged {subsystem: {counter: {type, description}}}
    the daemons ship inside MMgrReport: HELP text comes from each
    counter's declared `doc` and TYPE from its PerfCounters type —
    u64/time -> counter, gauge -> gauge, histogram -> a real prometheus
    histogram with cumulative log2 `le` buckets (+Inf, _sum, _count).
    Counters without schema fall back to the generic rendering, so a
    daemon predating the schema field still exports.

    `health` is the mon's `health` payload: rendered as
    `ceph_health_status` (0=OK 1=WARN 2=ERR) plus one
    `ceph_health_detail{name,severity}` series per ACTIVE check —
    upstream mgr/prometheus parity, which is what makes the new
    TPU_BACKEND_DEGRADED / KERNEL_FALLBACK_LATCHED checks scrapeable."""
    lines: list[str] = []
    schema = schema or {}

    def esc(v) -> str:
        # exposition-format label escaping: one bad pool name must not
        # poison the whole scrape
        return (
            str(v)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    def metric(name, doc, typ, samples):
        lines.append(f"# HELP {name} {doc}")
        lines.append(f"# TYPE {name} {typ}")
        for labels, value in samples:
            lab = (
                "{" + ",".join(f'{k}="{esc(v)}"' for k, v in labels.items()) + "}"
                if labels
                else ""
            )
            lines.append(f"{name}{lab} {value}")

    if health is not None:
        hblock = health.get("health") if isinstance(
            health.get("health"), dict) else {}
        status = (hblock or {}).get("status")
        metric(
            "ceph_health_status",
            "cluster health status (0=HEALTH_OK 1=HEALTH_WARN "
            "2=HEALTH_ERR; reference: mgr/prometheus health_status)",
            "gauge",
            [({}, {"HEALTH_OK": 0, "HEALTH_WARN": 1,
                   "HEALTH_ERR": 2}.get(status, 2))],
        )
        checks = (hblock or {}).get("checks") or {}
        if checks:
            metric(
                "ceph_health_detail",
                "active health checks (1 per check; reference: "
                "mgr/prometheus health_detail)", "gauge",
                [
                    ({"name": name,
                      "severity": chk.get("severity", "HEALTH_WARN")}, 1)
                    for name, chk in sorted(checks.items())
                ],
            )
    if osdmap is not None:
        metric(
            "ceph_osd_up", "OSD up state", "gauge",
            [
                ({"ceph_daemon": f"osd.{o}"}, int(osdmap.is_up(o)))
                for o in range(osdmap.max_osd)
                if osdmap.exists(o)
            ],
        )
        metric(
            "ceph_osd_in", "OSD in state", "gauge",
            [
                ({"ceph_daemon": f"osd.{o}"}, int(osdmap.is_in(o)))
                for o in range(osdmap.max_osd)
                if osdmap.exists(o)
            ],
        )
        metric(
            "ceph_osdmap_epoch", "OSDMap epoch", "gauge",
            [({}, osdmap.epoch)],
        )
        metric(
            "ceph_pool_pg_num", "PGs per pool", "gauge",
            [
                ({"pool": p.name}, p.pg_num)
                for p in osdmap.pools.values()
            ],
        )
    # per-daemon perf counters: flatten subsystem dumps into one series
    # per counter, labelled by daemon (the reference's ceph_daemon label)
    series: dict[str, list] = {}
    hists: dict[str, dict] = {}   # base -> {"doc", "bucket", "sum", "count"}
    meta: dict[str, tuple[str, str]] = {}  # key -> (help, type)

    def declared(subsys: str, cname: str, key: str,
                 default_typ: str) -> tuple[str, str]:
        sch = (schema.get(subsys) or {}).get(cname) or {}
        doc = sch.get("description") or f"perf counter {key}"
        typ = "gauge" if sch.get("type") == "gauge" else default_typ
        return doc, typ

    def add_hist(key: str, doc: str, labels: dict, value: dict) -> None:
        """Accumulate one histogram dump (cumulative le buckets)."""
        h = hists.setdefault(key, {
            "doc": doc, "bucket": [], "sum": [], "count": [],
        })
        cum = 0
        for i, c in enumerate(value["buckets"]):
            cum += c
            le = f"{HIST_LE[i]:.6g}" if i < len(HIST_LE) else "+Inf"
            h["bucket"].append(({**labels, "le": le}, cum))
        h["sum"].append((labels, value["sum"]))
        h["count"].append((labels, value["count"]))

    for daemon, subsystems in sorted(reports.items()):
        labels = {"ceph_daemon": daemon}
        for subsys, counters in sorted((subsystems or {}).items()):
            for cname, value in sorted(counters.items()):
                key = f"ceph_{subsys}_{cname}"
                if isinstance(value, dict) and value.get("__labeled__"):
                    # cephmeter labeled rows (the per-(client,pool)
                    # accounting table): each row's fields become
                    # ceph_<subsys>_<field>{ceph_daemon,client,pool,...}
                    # series; sanitized label values, bounded row count
                    for row in _fold_labeled_rows(value.get("rows") or []):
                        rl = {**labels, **{
                            k: _sanitize_label(v)
                            for k, v in (row.get("labels") or {}).items()
                        }}
                        for f, v in sorted(row.items()):
                            if f == "labels":
                                continue
                            fkey = f"ceph_{subsys}_{f}"
                            if isinstance(v, dict) and "buckets" in v:
                                add_hist(
                                    fkey,
                                    declared(subsys, f, fkey,
                                             "histogram")[0],
                                    rl, v)
                            elif isinstance(v, (int, float)):
                                meta.setdefault(fkey, declared(
                                    subsys, f, fkey, "counter"))
                                series.setdefault(fkey, []).append(
                                    (rl, v))
                    continue
                if isinstance(value, dict) and "buckets" in value:
                    # log2-bucket latency histogram (PerfCounters
                    # TYPE_HISTOGRAM): cumulative le buckets, seconds
                    add_hist(key, declared(subsys, cname, key,
                                           "histogram")[0], labels, value)
                elif isinstance(value, dict):  # longrunavg {avgcount, sum}
                    for part, v in value.items():
                        pkey = f"{key}_{part}"
                        meta.setdefault(
                            pkey, declared(subsys, cname, pkey, "counter"))
                        series.setdefault(pkey, []).append((labels, v))
                else:
                    meta.setdefault(
                        key, declared(subsys, cname, key, "counter"))
                    series.setdefault(key, []).append((labels, value))
    for key, samples in sorted(series.items()):
        doc, typ = meta.get(key, (f"perf counter {key}", "counter"))
        metric(key, doc, typ, samples)
    for base, h in sorted(hists.items()):
        lines.append(f"# HELP {base} {h['doc']}")
        lines.append(f"# TYPE {base} histogram")
        for suffix in ("bucket", "sum", "count"):
            for labels, value in h[suffix]:
                lab = ",".join(f'{k}="{esc(v)}"' for k, v in labels.items())
                lines.append(f"{base}_{suffix}{{{lab}}} {value}")
    return "\n".join(lines) + "\n"


@register_module
class PrometheusModule(MgrModule):
    NAME = "prometheus"

    def __init__(self, mgr):
        super().__init__(mgr)
        # bind SYNCHRONOUSLY (module construction happens inside
        # MgrDaemon.start) so `mgr.start(); module('prometheus').url`
        # never races the serve thread
        port = int(self.cct.conf.get("mgr_prometheus_port"))
        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), self._handler_class()
        )
        self.url = f"http://127.0.0.1:{self._server.server_address[1]}/metrics"

    def _handler_class(self):
        module = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    # cluster health piggybacks the scrape (a mon round
                    # trip); an unreachable/electing mon drops the
                    # health series, never the whole exposition
                    try:
                        rv, health = module.mon_command(
                            {"prefix": "health"})
                        if rv != 0 or not isinstance(health, dict):
                            health = None
                    except Exception:
                        health = None
                    body = render_metrics(
                        module.get("osd_map"),
                        module.get_all_perf_counters(),
                        schema=module.get_perf_schema(),
                        health=health,
                    ).encode()
                except Exception as e:  # scrape must not kill the server
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        return Handler

    def serve(self) -> None:
        t = threading.Thread(
            target=self._server.serve_forever, name="mgr-prometheus-http",
            daemon=True,
        )
        t.start()
        self._stop.wait()
        self._server.shutdown()
        self._server.server_close()
        t.join(timeout=5)  # serve_forever returned at shutdown()
