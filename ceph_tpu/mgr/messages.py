"""Mgr wire messages (reference: src/messages/MMgrReport.h — daemons
stream perf-counter snapshots to the active mgr; MMgrOpen's session
handshake collapses into the report itself here)."""
from __future__ import annotations

from ..mon.messages import _JsonMessage
from ..msg.message import register_message


@register_message
class MMgrReport(_JsonMessage):
    """Daemon -> mgr perf snapshot.

    daemon: entity name ("osd.3"); counters: {subsystem: {name: value}}
    (the PerfCountersCollection dump); epoch: the daemon's map epoch so the
    mgr can spot laggards; stats: free-form daemon stats (pg counts,
    store bytes) for modules that want more than counters; schema:
    {subsystem: {name: {type, description}}} (PerfCountersCollection
    schema) so the prometheus exporter renders real HELP text and the
    right TYPE (counter/gauge/histogram) instead of guessing."""

    MSG_TYPE = 120
    FIELDS = ("daemon", "counters", "epoch", "stats", "schema")
