"""Mgr wire messages (reference: src/messages/MMgrReport.h — daemons
stream perf-counter snapshots to the active mgr; MMgrOpen's session
handshake collapses into the report itself here)."""
from __future__ import annotations

from ..mon.messages import _JsonMessage
from ..msg.message import register_message


@register_message
class MMgrReport(_JsonMessage):
    """Daemon -> mgr perf snapshot.

    daemon: entity name ("osd.3"); counters: {subsystem: {name: value}}
    (the PerfCountersCollection dump); epoch: the daemon's map epoch so the
    mgr can spot laggards; stats: free-form daemon stats (pg counts,
    store bytes) for modules that want more than counters; schema:
    {subsystem: {name: {type, description}}} (PerfCountersCollection
    schema) so the prometheus exporter renders real HELP text and the
    right TYPE (counter/gauge/histogram) instead of guessing."""

    MSG_TYPE = 120
    FIELDS = ("daemon", "counters", "epoch", "stats", "schema")


@register_message
class MQoSSettings(_JsonMessage):
    """Mgr -> daemon QoS retune push (cephqos; docs/qos.md).

    Rides BACK over the connection the daemon's MMgrReport arrived on
    (no new dialing, no admin-socket dependency).  ``options`` is a
    {name: value} map applied through the daemon's injectargs core
    (validate-all-then-apply, runtime options only); ``classes`` maps
    an mClock class name — the cephmeter "client/pool" identity — to
    its [reservation, weight, limit]; ``qos_epoch`` is the controller's
    monotonically increasing push counter, so a stale/reordered push
    never rolls settings back."""

    MSG_TYPE = 122
    FIELDS = ("qos_epoch", "options", "classes")
