"""Dashboard module — HTTP cluster dashboard (reference:
src/pybind/mgr/dashboard — here the REST layer + a server-rendered
status page rather than the Angular SPA, which is presentation the
framework's API surface does not depend on; SURVEY.md §2.5).

Endpoints (JSON unless noted):

    /                     HTML cluster summary (health, OSDs, pools)
    /api/health           `ceph -s` style health + check details
    /api/osd              per-OSD up/in/pgs/objects rows
    /api/pool             per-pool type/size/pg_num/bytes
    /api/perf             latest per-daemon perf counter snapshots
    /api/iostat           cluster + per-daemon IO rates (iostat module)
    /api/fs               MDS ranks, beacon liveness, subtree pins
    /api/df               cluster + per-pool usage (same as `ceph df`)

Read-only by design: mutations belong to the `ceph` CLI / mon command
surface (the reference dashboard's write paths wrap the same mon
commands and carry no extra semantics).
"""
from __future__ import annotations

import html
import http.server
import json
import threading

from .module import MgrModule, register_module


def _esc(s) -> str:
    return html.escape(str(s))


@register_module
class DashboardModule(MgrModule):
    NAME = "dashboard"

    def __init__(self, mgr):
        super().__init__(mgr)
        port = int(self.cct.conf.get("mgr_dashboard_port"))
        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), self._handler_class()
        )
        self.url = f"http://127.0.0.1:{self._server.server_address[1]}/"

    # -- data assembly -----------------------------------------------------
    def health(self) -> dict:
        rv, res = self.mon_command({"prefix": "status"})
        return res if rv == 0 else {"error": res}

    def osd_rows(self) -> list[dict]:
        # one assembly shared with `ceph osd status` (status module) so
        # the two surfaces can never drift apart
        from .status_module import assemble_osd_rows

        return assemble_osd_rows(self.get("osd_map"),
                                 self.mgr.latest_stats())

    def pool_rows(self) -> list[dict]:
        m = self.get("osd_map")
        stats = self.mgr.latest_stats()
        rows = []
        if m is None:
            return rows
        for pid, p in sorted(m.pools.items()):
            nbytes = 0
            for st in stats.values():
                nbytes += int(st.get("pool_bytes", {}).get(str(pid), 0))
            rows.append({
                "id": pid, "name": p.name,
                "type": "erasure" if p.ec_profile else "replicated",
                "size": p.size, "pg_num": p.pg_num, "bytes": nbytes,
            })
        return rows

    def iostat(self) -> dict:
        mod = self.mgr._modules.get("iostat")
        if mod is None:
            return {"error": "iostat module not hosted"}
        return mod.sample()

    def fs_ranks(self) -> list[dict]:
        """MDS rank table (the `ceph fs status` data, JSON) via the
        shared assembler in fs/mds.py."""
        from ..fs.mds import assemble_rank_rows

        try:
            io = self.mgr.rados_ioctx("cephfs_meta")
        except (IOError, KeyError):
            return []
        return assemble_rank_rows(io)

    def _page(self) -> str:
        h = self.health()
        # the mon nests: {"health": {"status": ..., "checks": {...}}, ...}
        hblock = h.get("health") if isinstance(h.get("health"), dict) else {}
        status = hblock.get("status", h.get("error", "?"))
        checks = hblock.get("checks", {})
        osds = self.osd_rows()
        pools = self.pool_rows()
        osd_rows = "".join(
            f"<tr><td>osd.{r['id']}</td><td>{'up' if r['up'] else 'down'}"
            f"</td><td>{'in' if r['in'] else 'out'}</td>"
            f"<td>{r['pgs']}</td><td>{r['objects']}</td></tr>"
            for r in osds
        )
        pool_rows = "".join(
            f"<tr><td>{r['id']}</td><td>{_esc(r['name'])}</td>"
            f"<td>{r['type']}</td><td>{r['size']}</td>"
            f"<td>{r['pg_num']}</td><td>{r['bytes']}</td></tr>"
            for r in pools
        )
        return (
            "<!doctype html><html><head><title>ceph_tpu dashboard</title>"
            "<style>body{font-family:monospace;margin:2em}"
            "table{border-collapse:collapse;margin:1em 0}"
            "td,th{border:1px solid #999;padding:2px 8px}</style></head>"
            f"<body><h1>cluster: {_esc(status)}</h1>"
            f"<pre>{_esc(json.dumps(checks, indent=1))}</pre>"
            "<h2>OSDs</h2><table><tr><th>osd</th><th>state</th>"
            f"<th>in/out</th><th>pgs</th><th>objects</th></tr>{osd_rows}"
            "</table><h2>Pools</h2><table><tr><th>id</th><th>name</th>"
            "<th>type</th><th>size</th><th>pg_num</th><th>bytes</th></tr>"
            f"{pool_rows}</table></body></html>"
        )

    def df(self) -> dict:
        """Cluster/pool usage — same assembly the mon's `ceph df` serves
        (status_module.assemble_df), so the two can never drift."""
        from .status_module import assemble_df

        return assemble_df(self.get("osd_map"), self.mgr.latest_stats())

    # -- http ---------------------------------------------------------------
    def _handler_class(self):
        module = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                try:
                    if path == "":
                        body = module._page().encode()
                        ctype = "text/html"
                    elif path == "/api/health":
                        body = json.dumps(module.health()).encode()
                        ctype = "application/json"
                    elif path == "/api/osd":
                        body = json.dumps(module.osd_rows()).encode()
                        ctype = "application/json"
                    elif path == "/api/pool":
                        body = json.dumps(module.pool_rows()).encode()
                        ctype = "application/json"
                    elif path == "/api/perf":
                        body = json.dumps(
                            module.get_all_perf_counters()).encode()
                        ctype = "application/json"
                    elif path == "/api/iostat":
                        body = json.dumps(module.iostat()).encode()
                        ctype = "application/json"
                    elif path == "/api/fs":
                        body = json.dumps(module.fs_ranks()).encode()
                        ctype = "application/json"
                    elif path == "/api/df":
                        body = json.dumps(module.df()).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:  # a bad scrape must not kill http
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        return Handler

    def serve(self) -> None:
        t = threading.Thread(
            target=self._server.serve_forever, name="mgr-dashboard-http",
            daemon=True,
        )
        t.start()
        self._stop.wait()
        self._server.shutdown()
        self._server.server_close()
        t.join(timeout=5)  # serve_forever returned at shutdown()
