"""MgrModule — the module-host contract (reference: src/mgr/ActivePyModule
+ src/pybind/mgr/mgr_module.py :: MgrModule; SURVEY.md §2.5).

A module runs `serve()` on its own thread until `shutdown()`; the host
hands it cluster state (maps, daemon perf reports) and a mon-command
channel, mirroring the reference's MgrModule API surface the in-tree
modules actually use (get, get_all_perf_counters, mon_command,
set_module_option-ish config reads)."""
from __future__ import annotations

import threading


class MgrModule:
    NAME = "module"

    def __init__(self, mgr):
        self.mgr = mgr
        self.cct = mgr.cct
        self._stop = threading.Event()

    # -- host-provided state ------------------------------------------------
    def get(self, what: str):
        """reference: MgrModule.get — 'osd_map' is the one every in-tree
        module starts from."""
        if what == "osd_map":
            return self.mgr.mc.osdmap
        if what == "mon_status":
            rv, res = self.mgr.mc.command({"prefix": "mon stat"})
            return res if rv == 0 else None
        raise KeyError(what)

    def get_all_perf_counters(self) -> dict:
        """{daemon: {subsystem: {counter: value}}} from the freshest
        MMgrReport of each daemon (reference: get_all_perf_counters)."""
        return self.mgr.latest_reports()

    def get_perf_schema(self) -> dict:
        """{subsystem: {counter: {type, description}}} merged across
        daemons (reference: MMgrReport's PerfCounterType declarations)."""
        return self.mgr.latest_schemas()

    def mon_command(self, cmd: dict):
        return self.mgr.mc.command(cmd)

    # -- lifecycle ----------------------------------------------------------
    def serve(self) -> None:  # pragma: no cover - abstract loop
        self._stop.wait()

    def shutdown(self) -> None:
        self._stop.set()


MODULE_REGISTRY: dict[str, type] = {}


def register_module(cls: type) -> type:
    MODULE_REGISTRY[cls.NAME] = cls
    return cls
