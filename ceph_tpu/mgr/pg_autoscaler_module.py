"""pg_autoscaler — per-pool PG-count tuning (reference:
src/pybind/mgr/pg_autoscaler/module.py; SURVEY.md §2.5 "other mgr
modules").

The reference's core loop: for each pool, a target PG count is computed
from the pool's share of cluster capacity times `mon_target_pg_per_osd`
times the OSD count, divided by the replication factor, rounded to a
power of two; a change is only applied when the current count is off by
more than a threshold factor (3x by default) so the autoscaler doesn't
thrash.  Shares come from observed bytes (daemon reports) with an equal
split as the prior for empty clusters — the reference uses pg_autoscale
bias/target_ratio the same way.

Applying a change issues `osd pool set <pool> pg_num <n>`; the OSDs then
run the split migration (osd/daemon.py _split_pass).  Only scale-UP is
applied (merges are rejected by the mon, matching this framework's
scope); scale-down recommendations are still reported.
"""
from __future__ import annotations

from .module import MgrModule, register_module


def _next_pow2(n: int) -> int:
    return 1 << max(0, (max(n, 1) - 1).bit_length())


@register_module
class PgAutoscalerModule(MgrModule):
    NAME = "pg_autoscaler"

    def __init__(self, mgr):
        super().__init__(mgr)
        self.last_eval: list[dict] = []
        self.passes = 0

    # -- the scale computation (reference: _get_pool_status) --------------
    def evaluate(self) -> list[dict]:
        m = self.get("osd_map")
        if m is None or not m.pools:
            return []
        n_osds = max(
            1, sum(1 for o in range(m.max_osd) if m.is_up(o) and m.is_in(o))
        )
        target_per_osd = self.cct.conf.get("mon_target_pg_per_osd")
        # byte shares from the freshest daemon stats; equal split when the
        # cluster is empty (the prior)
        stats = self.mgr.latest_stats()
        pool_bytes: dict[int, int] = {pid: 0 for pid in m.pools}
        for _daemon, s in stats.items():
            for pid_s, nbytes in (s.get("pool_bytes") or {}).items():
                pid = int(pid_s)
                if pid in pool_bytes:
                    pool_bytes[pid] += int(nbytes)
        total = sum(pool_bytes.values())
        out = []
        for pid, pool in m.pools.items():
            share = (
                pool_bytes[pid] / total if total > 0 else 1 / len(m.pools)
            )
            raw = share * target_per_osd * n_osds / max(1, pool.size)
            target = max(
                self.cct.conf.get("osd_pool_default_pg_num") // 4,
                _next_pow2(int(round(raw))),
            )
            factor = self.cct.conf.get("mgr_pg_autoscale_threshold")
            need = (
                target > pool.pg_num * factor
                or target * factor < pool.pg_num
            )
            out.append({
                "pool_id": pid,
                "pool": pool.name,
                "pg_num": pool.pg_num,
                "target": target,
                "share": round(share, 4),
                "would_adjust": bool(need),
            })
        self.last_eval = out
        return out

    def scale_once(self) -> list[dict]:
        applied = []
        for ev in self.evaluate():
            if not ev["would_adjust"] or ev["target"] <= ev["pg_num"]:
                continue  # only scale-up is actionable (mon rejects merges)
            rv, res = self.mon_command({
                "prefix": "osd pool set",
                "name": ev["pool"],
                "key": "pg_num",
                "value": ev["target"],
            })
            ev["applied"] = rv == 0
            ev["result"] = res
            if rv != 0:
                self.cct.dout(
                    "mgr", 1,
                    f"pg_autoscaler: pool {ev['pool']} -> "
                    f"{ev['target']} failed: {res}",
                )
            applied.append(ev)
        self.passes += 1
        return applied

    def serve(self) -> None:
        interval = self.cct.conf.get("mgr_pg_autoscale_interval")
        while not self._stop.wait(interval):
            try:
                if self.cct.conf.get("mgr_pg_autoscale_active"):
                    self.scale_once()
                else:
                    self.evaluate()
            except Exception as e:
                self.cct.dout("mgr", 1, f"pg_autoscaler failed: {e!r}")
