"""placement — cephplace: placement-plane observability on batched CRUSH
(reference: the distribution half of PGMap/`ceph osd df` deviation plus
the OBJECT_MISPLACED accounting `ceph status` renders during a remap —
recast as a mgr module because in this tree the mgr is where batched
mappings and daemon stats already meet).

One loop, three products per scan (the scan runs on every osdmap-epoch
change, plus a periodic tick every ``mgr_placement_interval``):

1. **Distribution analytics** — the full cluster PG→OSD mapping as one
   ``OSDMap.map_pool`` → ``crush_do_rule_batch`` launch per pool (the
   batched device path, visible in kernel telemetry), folded by the
   shared scoring core (``osd/placement.py``) into per-OSD shard/primary
   counts vs the weight-proportional ideal and per-pool skew scores
   (max deviation, stddev, normalized score) — exported as
   ``ceph_placement_*{pool,osd}`` labeled series via the mgr's own
   report sink (prometheus + metrics_history).

2. **Remap forecasting** — on epoch advance, the previous epoch's
   mappings (already device-batched, cached from the last scan) diff
   against the new ones into PGs/shards remapped and predicted
   bytes-to-move (per-shard byte weights from reported pool stats) —
   the misplaced-fraction forecast a 1M-PG storm simulation asserts
   against.  Exported as ``ceph_remap_*`` series and served as the
   ``placement diff`` mon command (the snapshot rides the status
   module's digest, like progress).

3. **Imbalance health** — pools whose max deviation exceeds
   ``mgr_placement_max_deviation`` while the balancer is idle or off
   feed the mon's ``PG_IMBALANCE`` check; a busy balancer (active and
   recently committing moves) suppresses it so an in-flight convergence
   doesn't flap the health state.
"""
from __future__ import annotations

import time

from ..common.lockdep import make_lock
from ..common.tracer import TRACER
from ..osd.placement import cluster_report, diff_mappings, osd_rows
from .module import MgrModule, register_module


@register_module
class PlacementModule(MgrModule):
    NAME = "placement"

    def __init__(self, mgr):
        super().__init__(mgr)
        self._lock = make_lock("mgr::placement")
        # serializes whole scans: the serve loop and direct scan()
        # callers (tests, the smoke) race on epoch changes, and two
        # concurrent scans of one transition would both book the diff —
        # doubling the cumulative ceph_remap_* counters the storm
        # simulation asserts against (always taken OUTSIDE self._lock)
        self._scan_lock = make_lock("mgr::placement::scan")
        self._last_epoch: int | None = None
        self._mappings: dict | None = None   # pid -> (up, primaries)
        self._report: dict | None = None     # last cluster_report
        self._map = None                     # the map _report was scanned on
        self._last_diff: dict | None = None  # last epoch diff (JSON-safe)
        self._diff_ts: float | None = None
        self._last_scan_ts: float = 0.0
        self._stats = {
            "scans": 0, "epochs_diffed": 0,
            "pgs_remapped_total": 0, "shards_remapped_total": 0,
            "predicted_bytes_total": 0,
        }

    # -- inputs --------------------------------------------------------------
    def _shard_bytes(self, m) -> dict[int, float]:
        """{pool_id: avg raw bytes per PG shard} from the daemons' pool
        stats — the byte weight one remapped shard is predicted to move."""
        stats = self.mgr.latest_stats()
        out: dict[int, float] = {}
        for pid, pool in m.pools.items():
            raw = sum(int((st.get("pool_bytes") or {}).get(str(pid), 0))
                      for st in stats.values())
            out[pid] = raw / max(1, pool.pg_num * pool.size)
        return out

    # -- one scan ------------------------------------------------------------
    def scan(self) -> dict | None:
        """Map every pool (batched), score the distribution, and — when
        the epoch advanced since the cached scan — forecast the remap.
        Returns the cluster report (None when no map/pools yet)."""
        with self._scan_lock:
            return self._scan_locked()

    def _scan_locked(self) -> dict | None:
        m = self.get("osd_map")
        if m is None or not m.pools:
            return None
        mappings = {pid: m.map_pool(pid) for pid in sorted(m.pools)}
        report = cluster_report(m, mappings=mappings)
        with self._lock:
            prev_epoch = self._last_epoch
            prev_maps = self._mappings
        diff = None
        if prev_maps is not None and m.epoch != prev_epoch:
            diff = diff_mappings(
                m,
                {pid: up for pid, (up, _p) in prev_maps.items()},
                {pid: up for pid, (up, _p) in mappings.items()},
                shard_bytes=self._shard_bytes(m),
            )
            diff["from_epoch"] = prev_epoch
            diff["to_epoch"] = m.epoch
        now = time.monotonic()
        with self._lock:
            self._last_epoch = m.epoch
            self._mappings = mappings
            self._report = report
            self._map = m
            self._last_scan_ts = now
            self._stats["scans"] += 1
            if diff is not None:
                self._last_diff = diff
                self._diff_ts = now
                self._stats["epochs_diffed"] += 1
                self._stats["pgs_remapped_total"] += diff["pgs_remapped"]
                self._stats["shards_remapped_total"] += \
                    diff["shards_remapped"]
                self._stats["predicted_bytes_total"] += \
                    diff["predicted_bytes"]
        if diff is not None and (diff["pgs_remapped"] or diff["pools_added"]
                                 or diff["pools_removed"]):
            TRACER.tracepoint(
                "placement", "epoch_diff", entity="mgr",
                from_epoch=diff["from_epoch"], to_epoch=diff["to_epoch"],
                pgs_remapped=diff["pgs_remapped"],
                shards_remapped=diff["shards_remapped"],
                misplaced_fraction=round(diff["misplaced_fraction"], 4),
                predicted_bytes=diff["predicted_bytes"])
        self.export()
        return report

    def tick(self) -> None:
        """Scan when the map moved or the periodic interval elapsed (the
        serve loop polls faster than the interval so an epoch change is
        picked up promptly)."""
        m = self.get("osd_map")
        if m is None:
            return
        interval = float(self.cct.conf.get("mgr_placement_interval"))
        with self._lock:
            due = (self._last_epoch != m.epoch
                   or time.monotonic() - self._last_scan_ts >= interval)
        if due:
            self.scan()

    # -- health + digest -----------------------------------------------------
    def imbalanced(self) -> list[dict]:
        """Pools whose max deviation exceeds the declared bound — the
        PG_IMBALANCE inputs (JSON-safe)."""
        thr = float(self.cct.conf.get("mgr_placement_max_deviation"))
        with self._lock:
            report = self._report
        if report is None:
            return []
        return [
            {"pool": sk["name"], "pool_id": pid,
             "max_deviation": round(sk["max_deviation"], 2),
             "stddev": round(sk["stddev"], 2),
             "score": round(sk["score"], 4)}
            for pid, sk in sorted(report["pools"].items())
            if sk["max_deviation"] > thr
        ]

    def _balancer_busy(self) -> bool:
        """True while the balancer is active AND recently committing
        moves — an in-flight convergence must not raise PG_IMBALANCE."""
        if not bool(self.cct.conf.get("mgr_balancer_active")):
            return False
        bal = self.mgr._modules.get("balancer")
        if bal is None:
            return False
        try:
            lp = bal.last_pass()
        except Exception:
            return False
        if not lp or not lp.get("committed"):
            return False
        grace = 2.0 * float(self.cct.conf.get("mgr_balancer_interval"))
        return time.monotonic() - lp.get("ts", 0.0) <= grace

    def df_inputs(self) -> tuple[list | None, dict | None]:
        """(per-OSD rows, cluster skew) for `ceph osd df` — BOTH from
        one report snapshot taken under the lock, so the digest can
        never pair one epoch's rows with another's summary.  Rows pair
        the report with the MAP IT WAS SCANNED ON — a newer map (e.g.
        max_osd grew) must wait for its own scan."""
        with self._lock:
            report, m = self._report, self._map
        if report is None or m is None:
            return None, None
        return osd_rows(report, m), {
            "max_deviation": report["max_deviation"],
            "stddev": report["stddev"],
        }

    def snapshot(self) -> dict:
        """The digest section: per-pool skew, imbalance state, and the
        last epoch diff — everything the mon needs for PG_IMBALANCE and
        the `placement diff` command (JSON-safe by construction)."""
        now = time.monotonic()
        with self._lock:
            report = self._report
            diff = self._last_diff
            diff_ts = self._diff_ts
            stats = dict(self._stats)
        pools = []
        cluster = None
        if report is not None:
            cluster = {"epoch": report["epoch"],
                       "score": round(report["score"], 4),
                       "max_deviation": round(report["max_deviation"], 2),
                       "stddev": round(report["stddev"], 2)}
            pools = [
                {"pool": sk["name"], "pool_id": pid,
                 "pg_num": sk["pg_num"], "shards": sk["shards"],
                 "max_deviation": round(sk["max_deviation"], 2),
                 "stddev": round(sk["stddev"], 2),
                 "score": round(sk["score"], 4)}
                for pid, sk in sorted(report["pools"].items())
            ]
        out = {
            "cluster": cluster,
            "pools": pools,
            "imbalanced": self.imbalanced(),
            "balancer_busy": self._balancer_busy(),
            "max_deviation_threshold": float(
                self.cct.conf.get("mgr_placement_max_deviation")),
            "stats": stats,
            "diff": None,
        }
        if diff is not None:
            out["diff"] = {
                **diff,
                "pools": {str(k): v for k, v in diff["pools"].items()},
                "misplaced_fraction": round(diff["misplaced_fraction"], 6),
                "age_seconds": round(now - (diff_ts or now), 1),
            }
        return out

    # -- export --------------------------------------------------------------
    def export(self) -> None:
        """ceph_placement_*{pool,osd} + ceph_remap_* series through the
        mgr's own report sink (prometheus + metrics_history)."""
        with self._lock:
            report, m = self._report, self._map
            diff = self._last_diff
            stats = dict(self._stats)
        if report is None or m is None:
            return
        pool_rows = [
            {"labels": {"pool": sk["name"]},
             "pool_shards": sk["shards"],
             "pool_max_deviation": round(sk["max_deviation"], 3),
             "pool_stddev": round(sk["stddev"], 3),
             "pool_score": round(sk["score"], 5)}
            for _pid, sk in sorted(report["pools"].items())
        ]
        osd_rows_ = [
            {"labels": {"osd": f"osd.{r['osd']}"},
             "osd_shards": r["shards"],
             "osd_primaries": r["primaries"],
             "osd_target": r["target"],
             "osd_deviation": r["deviation"]}
            for r in osd_rows(report, m)
        ]
        counters = {
            "placement": {
                "per_pool": {"__labeled__": True, "rows": pool_rows},
                "per_osd": {"__labeled__": True, "rows": osd_rows_},
                "epoch": report["epoch"],
                "scans": stats["scans"],
                "score": round(report["score"], 5),
                "max_deviation": round(report["max_deviation"], 3),
                "stddev": round(report["stddev"], 3),
                "imbalanced_pools": len(self.imbalanced()),
            },
            "remap": {
                "epochs_diffed": stats["epochs_diffed"],
                "pgs_remapped": stats["pgs_remapped_total"],
                "shards_remapped": stats["shards_remapped_total"],
                "predicted_bytes": stats["predicted_bytes_total"],
                "last_pgs_remapped": (diff or {}).get("pgs_remapped", 0),
                "last_shards_remapped":
                    (diff or {}).get("shards_remapped", 0),
                "last_predicted_bytes":
                    (diff or {}).get("predicted_bytes", 0),
                "last_misplaced_fraction": round(
                    (diff or {}).get("misplaced_fraction", 0.0), 6),
                "last_epoch": (diff or {}).get("to_epoch", 0),
            },
        }
        self.mgr.ingest_local_report("mgr.placement", counters,
                                     schema=_PLACEMENT_SCHEMA)

    def serve(self) -> None:
        interval = float(self.cct.conf.get("mgr_placement_interval"))
        # poll faster than the interval so an epoch change scans promptly
        poll = max(0.1, min(1.0, interval / 4.0))
        while not self._stop.is_set():
            self._stop.wait(timeout=poll)
            if self._stop.is_set():
                return
            try:
                self.tick()
            except Exception as e:
                # one torn map/report must not kill the loop
                self.cct.dout("mgr", 1, f"placement tick failed: {e!r}")


_PLACEMENT_SCHEMA = {
    "placement": {
        "per_pool": {"type": "labeled",
                     "description": "per-pool skew rows from the shared "
                                    "scoring core (osd/placement.py; "
                                    "docs/observability.md)"},
        "per_osd": {"type": "labeled",
                    "description": "per-OSD shard counts vs the "
                                   "weight-proportional ideal"},
        "pool_shards": {"type": "gauge",
                        "description": "placed PG shards in this pool"},
        "pool_max_deviation": {
            "type": "gauge",
            "description": "largest per-OSD deviation from the ideal "
                           "share in this pool (PG shards)"},
        "pool_stddev": {"type": "gauge",
                        "description": "stddev of per-OSD deviations in "
                                       "this pool (PG shards)"},
        "pool_score": {"type": "gauge",
                       "description": "normalized skew score (stddev / "
                                      "mean ideal share; 0 = perfect)"},
        "osd_shards": {"type": "gauge",
                       "description": "PG shards mapped to this OSD "
                                      "across pools (batched CRUSH scan)"},
        "osd_primaries": {"type": "gauge",
                          "description": "PGs whose primary is this OSD"},
        "osd_target": {"type": "gauge",
                       "description": "weight-proportional ideal shard "
                                      "share for this OSD"},
        "osd_deviation": {"type": "gauge",
                          "description": "shards minus target for this "
                                         "OSD (positive = overfull)"},
        "epoch": {"type": "gauge",
                  "description": "osdmap epoch of the last placement scan"},
        "scans": {"type": "u64",
                  "description": "full placement scans run (each = one "
                                 "batched crush_do_rule_batch launch per "
                                 "pool)"},
        "score": {"type": "gauge",
                  "description": "cluster-wide normalized skew score"},
        "max_deviation": {"type": "gauge",
                          "description": "largest per-OSD deviation "
                                         "cluster-wide (PG shards)"},
        "stddev": {"type": "gauge",
                   "description": "stddev of per-OSD deviations "
                                  "cluster-wide (PG shards)"},
        "imbalanced_pools": {
            "type": "gauge",
            "description": "pools over mgr_placement_max_deviation (the "
                           "PG_IMBALANCE inputs)"},
    },
    "remap": {
        "epochs_diffed": {"type": "u64",
                          "description": "osdmap epoch transitions "
                                         "forecast by the placement "
                                         "module"},
        "pgs_remapped": {"type": "u64",
                         "description": "cumulative PGs whose placement "
                                        "changed across observed epochs"},
        "shards_remapped": {"type": "u64",
                            "description": "cumulative PG shards "
                                           "remapped across observed "
                                           "epochs"},
        "predicted_bytes": {"type": "u64",
                            "description": "cumulative predicted "
                                           "bytes-to-move (shard byte "
                                           "weights from pool stats)"},
        "last_pgs_remapped": {"type": "gauge",
                              "description": "PGs remapped by the latest "
                                             "epoch transition"},
        "last_shards_remapped": {"type": "gauge",
                                 "description": "shards remapped by the "
                                                "latest epoch transition"},
        "last_predicted_bytes": {"type": "gauge",
                                 "description": "predicted bytes-to-move "
                                                "for the latest epoch "
                                                "transition"},
        "last_misplaced_fraction": {
            "type": "gauge",
            "description": "fraction of all placed shards the latest "
                           "epoch transition remapped (the remap-storm "
                           "forecast)"},
        "last_epoch": {"type": "gauge",
                       "description": "target epoch of the latest diff"},
    },
}
