"""qos — the cephqos closed-loop controller (reference: the mgr-side
half of mClock profile tuning plus the self-tuning throttles of
src/osd/scheduler/mClockScheduler.cc::set_osd_capacity_params; ROADMAP
"Closed-loop QoS"; arXiv:1709.05365's finding that QUEUEING, not
compute, dominates online erasure coding at scale — so the knobs worth
closing the loop on are the coalescing window and the per-tenant
admission order, not the codec).

One feedback loop, three stages per tick (``mgr_qos_interval``):

1. **Observe** — its own telemetry, nothing bespoke: stage_queue /
   stage_encode p99s from the histogram BUCKET deltas of each OSD's
   latest MMgrReport (windowed: this tick minus last tick), aggregate
   write rate + stripes-per-flush from the ``metrics_history`` rate
   API (the PR-11 store), and per-(client,pool) op rates from the
   cephmeter labeled accounting rows — the SAME identities the OSD's
   dynamic mClock classes key on.
2. **Plan** — :class:`QoSController`, a pure deterministic function
   from observation to decision, clamped by declared options: the
   coalescing window follows the observed inter-arrival toward a
   half-full batch (converging fixed point) but backs off
   multiplicatively while queue p99 overshoots its target;
   ``ec_batch_max_stripes`` grows while flushes saturate it and the
   encode stage keeps up; clients whose op rate exceeds
   ``mgr_qos_bully_factor`` x the median get a heavy (low-weight)
   mClock class while the rest keep a reservation floor — weights, not
   hard limits, so the scheduler stays work-conserving and aggregate
   throughput survives.
3. **Push + export** — one :class:`~ceph_tpu.mgr.messages.MQoSSettings`
   per reporting OSD, riding BACK over its report connection (options
   apply through the daemon's injectargs core; class params land on
   the scheduler), every decision logged as a ``qos`` tracepoint and
   exported as ``ceph_qos_*`` prometheus series via the mgr's own
   report sink — tuning is itself observable, and its history rides
   the same metrics_history ring it reads.

``mgr_qos_active`` = false (the default) observes and exports but
pushes nothing — the balancer's dry-run precedent.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..common.lockdep import make_lock
from ..common.perf_counters import HIST_LE
from ..common.tracer import TRACER
from .messages import MQoSSettings
from .module import MgrModule, register_module

#: stages whose p99 the controller watches (names match the OSD's
#: stage_* histograms / tracer.OP_STAGES verbatim)
WATCHED_STAGES = ("stage_queue", "stage_encode")

#: background mClock classes the controller OBSERVES (cephheal: their
#: depth/served/wait feed the loop's telemetry and export, but plan()
#: never writes them — the static floors stay protected, docs/qos.md)
BACKGROUND_CLASSES = ("background_recovery", "background_scrub")


def hist_quantile(buckets, q: float = 0.99) -> float | None:
    """Quantile (seconds, upper bucket bound) of one log2 bucket-count
    vector — used on windowed bucket DELTAS, so the answer describes
    this tick's samples, not all of history.  None when empty."""
    total = sum(buckets)
    if total <= 0:
        return None
    rank = q * total
    cum = 0
    for i, c in enumerate(buckets):
        cum += c
        if cum >= rank:
            return HIST_LE[i] if i < len(HIST_LE) else HIST_LE[-1] * 2.0
    return HIST_LE[-1] * 2.0


def hist_delta(cur: dict | None, prev: dict | None) -> list[int]:
    """Per-bucket delta of two histogram dumps; a counter reset (daemon
    restart) clamps to the current snapshot instead of going negative."""
    cb = list((cur or {}).get("buckets") or [])
    pb = list((prev or {}).get("buckets") or [])
    if not cb:
        return []
    if len(pb) != len(cb):
        return cb
    out = [c - p for c, p in zip(cb, pb)]
    if any(d < 0 for d in out):
        return cb
    return out


@dataclass(frozen=True)
class QoSClamps:
    """Declared bounds every decision stays inside (the options)."""

    window_min_ms: float = 0.5
    window_max_ms: float = 20.0
    stripes_min: int = 8
    stripes_max: int = 256
    queue_p99_target_ms: float = 50.0
    # hysteresis (cephstorm): grow the window back only once queue p99
    # has recovered BELOW this fraction of the target — backing off at
    # `> target` while regrowing at `<= target` limit-cycled the window
    # between the two rules every other tick under steady load
    queue_p99_recover_frac: float = 0.8
    bully_factor: float = 4.0
    heavy_weight: float = 5.0
    victim_reservation: float = 40.0


@dataclass
class QoSObservation:
    """One tick's inputs (synthesizable in tests without a cluster)."""

    window_ms: float
    max_stripes: int
    queue_p99_ms: float | None = None
    encode_p99_ms: float | None = None
    op_rate: float = 0.0                 # aggregate client writes/s
    stripes_per_flush: float | None = None
    per_client_rates: dict = field(default_factory=dict)  # key -> ops/s
    # cephheal (observe-only): {class: {depth, rate, wait_p99_ms}} for
    # BACKGROUND_CLASSES — plan() must never retune these
    background: dict = field(default_factory=dict)


class QoSController:
    """The pure planner: observation -> clamped decision.  Deterministic
    and state-free so tests drive it on synthetic series; repeated
    application under a FIXED observation converges (window approaches
    the arrival-matched ideal geometrically; overload pins the floor)."""

    def __init__(self, clamps: QoSClamps):
        self.clamps = clamps

    def _clamp_window(self, w: float) -> float:
        c = self.clamps
        return min(c.window_max_ms, max(c.window_min_ms, w))

    def plan(self, obs: QoSObservation) -> dict:
        c = self.clamps
        reasons: list[str] = []
        # -- coalescing window ------------------------------------------
        # ideal: long enough that a half-full batch accumulates at the
        # observed arrival rate (arXiv:1709.05365 — batch formation is
        # the queueing structure that matters), clamped.
        window = self._clamp_window(obs.window_ms)
        if obs.queue_p99_ms is not None \
                and obs.queue_p99_ms > c.queue_p99_target_ms:
            # queueing over target: multiplicative backoff beats any
            # model — shrink first, re-observe next tick
            window = self._clamp_window(obs.window_ms * 0.7)
            reasons.append(
                f"queue_p99 {obs.queue_p99_ms:.1f}ms > target "
                f"{c.queue_p99_target_ms:.1f}ms: window -> "
                f"{window:.2f}ms")
        elif obs.op_rate > 0 and (
                obs.queue_p99_ms is None
                or obs.queue_p99_ms
                <= c.queue_p99_recover_frac * c.queue_p99_target_ms):
            # grow only once p99 has RECOVERED below the hysteresis
            # band, not merely dipped under the backoff threshold —
            # the storm's oscillation invariant pinned the flip-flop
            # this band prevents (seed in tests/test_storm.py)
            ideal = self._clamp_window(
                (obs.max_stripes / 2.0) / obs.op_rate * 1e3)
            window = self._clamp_window(
                obs.window_ms + 0.5 * (ideal - obs.window_ms))
            if abs(window - obs.window_ms) > 1e-3:
                reasons.append(
                    f"arrivals {obs.op_rate:.0f}/s: window -> "
                    f"{window:.2f}ms (ideal {ideal:.2f}ms)")
        # -- stripe cap -------------------------------------------------
        stripes = min(c.stripes_max, max(c.stripes_min, obs.max_stripes))
        if obs.encode_p99_ms is not None \
                and obs.encode_p99_ms > 2 * c.queue_p99_target_ms:
            stripes = max(c.stripes_min, stripes // 2)
            reasons.append(
                f"encode_p99 {obs.encode_p99_ms:.1f}ms: stripes -> "
                f"{stripes}")
        elif (obs.stripes_per_flush is not None
                and obs.stripes_per_flush >= 0.9 * stripes):
            grown = min(c.stripes_max, stripes * 2)
            if grown != stripes:
                reasons.append(
                    f"flushes saturate {stripes}-stripe cap: -> {grown}")
            stripes = grown
        # -- per-client classes -----------------------------------------
        classes: dict[str, tuple] = {}
        rates = {k: v for k, v in obs.per_client_rates.items() if v > 0}
        if len(rates) >= 2:
            vals = sorted(rates.values())
            # LOWER-middle median: with few clients the upper middle is
            # the bully itself (2 clients -> med == max, nothing is ever
            # heavy); the lower middle is the light-tenant baseline
            med = vals[(len(vals) - 1) // 2]
            heavies = [k for k, v in rates.items()
                       if v > c.bully_factor * max(med, 1.0)]
            if heavies:
                for k in rates:
                    if k in heavies:
                        # low WEIGHT, no hard limit: the scheduler
                        # stays work-conserving (aggregate survives),
                        # the bully just loses ties under contention
                        classes[k] = (0.0, c.heavy_weight, 0.0)
                    else:
                        classes[k] = (c.victim_reservation, 10.0, 0.0)
                reasons.append(
                    f"heavy clients {sorted(heavies)}: weight "
                    f"{c.heavy_weight}, victims reserved "
                    f"{c.victim_reservation}/s")
        return {
            "window_ms": round(window, 3),
            "max_stripes": int(stripes),
            "classes": classes,
            "reasons": reasons,
        }


@register_module
class QoSModule(MgrModule):
    """The controller loop host (module docstring)."""

    NAME = "qos"

    def __init__(self, mgr):
        super().__init__(mgr)
        cct = self.cct
        # controller-owned targets, seeded from this process's declared
        # defaults; after the first push the controller's view IS the
        # cluster's (every OSD applied the same epoch)
        self._window_ms = float(cct.conf.get("ec_batch_window_ms"))
        self._max_stripes = int(cct.conf.get("ec_batch_max_stripes"))
        # epoch base = wall-clock seconds: a RESTARTED mgr must mint
        # epochs above the dead one's high-water mark or the OSDs'
        # monotonic guard silently drops every push from the new
        # controller (a pure 0-based counter resets on failover)
        self._epoch = int(time.time())  # noqa: CL11 — failover epoch floor MUST be wall time (see comment above)
        self._lock = make_lock("mgr::qos")
        # previous-tick snapshots for windowed deltas
        self._prev_hists: dict[tuple[str, str], dict] = {}
        self._prev_client_ops: dict[tuple[str, str], float] = {}
        self._prev_client_ts: float | None = None
        # cephheal: background-class served counters (windowed rates)
        self._prev_bg_served: dict[str, float] = {}
        self._prev_bg_ts: float | None = None
        self._stats = {"ticks": 0, "retunes": 0, "pushes": 0,
                       "push_errors": 0, "heavy_clients": 0}
        self._last = {"queue_p99_ms": None, "encode_p99_ms": None,
                      "op_rate": 0.0, "background": {}, "reasons": []}
        self.decisions: list[dict] = []  # bounded ring, introspection

    def _clamps(self) -> QoSClamps:
        cct = self.cct
        return QoSClamps(
            window_min_ms=float(cct.conf.get("mgr_qos_window_min_ms")),
            window_max_ms=float(cct.conf.get("mgr_qos_window_max_ms")),
            stripes_min=int(cct.conf.get("mgr_qos_stripes_min")),
            stripes_max=int(cct.conf.get("mgr_qos_stripes_max")),
            queue_p99_target_ms=float(
                cct.conf.get("mgr_qos_queue_p99_target_ms")),
            queue_p99_recover_frac=float(
                cct.conf.get("mgr_qos_queue_p99_recover_frac")),
            bully_factor=float(cct.conf.get("mgr_qos_bully_factor")),
            heavy_weight=float(cct.conf.get("mgr_qos_heavy_weight")),
            victim_reservation=float(
                cct.conf.get("mgr_qos_victim_reservation")),
        )

    # -- observe ------------------------------------------------------------
    def observe(self) -> QoSObservation:
        stale = float(self.cct.conf.get("mgr_stale_report_age"))
        reports = self.mgr.latest_reports()
        # stage p99s: windowed bucket deltas aggregated across OSDs
        agg: dict[str, list[int]] = {}
        for daemon, subsystems in reports.items():
            if not daemon.startswith("osd."):
                continue
            osd = (subsystems or {}).get("osd") or {}
            for stage in WATCHED_STAGES:
                cur = osd.get(stage)
                if not isinstance(cur, dict) or "buckets" not in cur:
                    continue
                prev = self._prev_hists.get((daemon, stage))
                self._prev_hists[(daemon, stage)] = cur
                if prev is None:
                    continue  # first sighting primes — booking a
                    # long-running OSD's whole cumulative histogram as
                    # one tick's samples would fake a p99 blowout
                delta = hist_delta(cur, prev)
                if delta:
                    tot = agg.setdefault(stage, [0] * len(delta))
                    if len(tot) == len(delta):
                        for i, d in enumerate(delta):
                            tot[i] += d
        q99 = hist_quantile(agg.get("stage_queue", ()))
        e99 = hist_quantile(agg.get("stage_encode", ()))
        # rates from the metrics-history store (the PR-11 substrate)
        hist = self.mgr.metrics_history
        op_rate = sum((hist.rate("osd.op_w", max_age=stale) or {}).values())
        sr = sum((hist.rate("osd.ec_batch_stripes",
                            max_age=stale) or {}).values())
        fr = sum((hist.rate("osd.ec_batch_flushes",
                            max_age=stale) or {}).values())
        spf = (sr / fr) if fr > 0 else None
        return QoSObservation(
            window_ms=self._window_ms,
            max_stripes=self._max_stripes,
            queue_p99_ms=None if q99 is None else q99 * 1e3,
            encode_p99_ms=None if e99 is None else e99 * 1e3,
            op_rate=op_rate,
            stripes_per_flush=spf,
            per_client_rates=self._client_rates(reports),
            background=self._background_state(reports),
        )

    def _background_state(self, reports: dict) -> dict:
        """Aggregate the background_recovery/background_scrub mClock
        rows (the ceph_mclock_*{qclass} SchedulerPerf series) across
        OSDs: queue depth, served-op rate (windowed cumulative-counter
        delta), and wait p99 (windowed histogram bucket delta — the
        same discipline as the stage p99s).  Observe-only: the first
        half of the ROADMAP QoS residual; feeding them into plan()
        stays future work and the background floors stay
        controller-unwritable."""
        now = time.monotonic()
        out: dict[str, dict] = {}
        agg_wait: dict[str, list[int]] = {}
        depth: dict[str, int] = {}
        served: dict[str, float] = {}
        for daemon, subsystems in reports.items():
            if not daemon.startswith("osd."):
                continue
            rows = (((subsystems or {}).get("mclock") or {})
                    .get("per_class") or {}).get("rows") or []
            for row in rows:
                cls = (row.get("labels") or {}).get("qclass")
                if cls not in BACKGROUND_CLASSES:
                    continue
                depth[cls] = depth.get(cls, 0) + int(
                    row.get("depth") or 0)
                served[cls] = served.get(cls, 0.0) + float(
                    row.get("served") or 0)
                wait = row.get("wait")
                if isinstance(wait, dict) and "buckets" in wait:
                    key = (daemon, f"mclock.{cls}.wait")
                    prev = self._prev_hists.get(key)
                    self._prev_hists[key] = wait
                    if prev is None:
                        continue  # first sighting primes
                    delta = hist_delta(wait, prev)
                    if delta:
                        tot = agg_wait.setdefault(cls, [0] * len(delta))
                        if len(tot) == len(delta):
                            for i, d in enumerate(delta):
                                tot[i] += d
        prev_ts = self._prev_bg_ts
        prev_served_map = self._prev_bg_served
        self._prev_bg_ts = now
        # the prev map is replaced WHOLESALE (the _client_rates rule):
        # a class absent this tick — every report stale during an OSD
        # outage — re-primes on return instead of booking the whole
        # gap's served delta against one tick interval
        self._prev_bg_served = {
            cls: served.get(cls, 0.0)
            for cls in BACKGROUND_CLASSES
            if cls in depth or cls in served
        }
        for cls in BACKGROUND_CLASSES:
            if cls not in depth and cls not in served:
                continue
            rate = None
            prev_served = prev_served_map.get(cls)
            if prev_served is not None and prev_ts is not None \
                    and now > prev_ts:
                rate = max(0.0, (served.get(cls, 0.0) - prev_served)
                           / (now - prev_ts))
            p99 = hist_quantile(agg_wait.get(cls, ()))
            out[cls] = {
                "depth": depth.get(cls, 0),
                "rate": None if rate is None else round(rate, 3),
                "wait_p99_ms": None if p99 is None else p99 * 1e3,
            }
        return out

    def _client_rates(self, reports: dict) -> dict:
        """Per-(client,pool) write-op rates from the cephmeter labeled
        accounting rows, windowed against the previous tick (cumulative
        row counters; a restart's negative delta clamps to 0).  Keys
        are the "client/pool" strings the OSD's dynamic mClock classes
        use, so plan() output maps straight onto scheduler classes."""
        now = time.monotonic()
        totals: dict[tuple[str, str], float] = {}
        for daemon, subsystems in reports.items():
            if not daemon.startswith("osd."):
                continue
            tab = ((subsystems or {}).get("client_io") or {})
            rows = (tab.get("per_client") or {}).get("rows") or []
            for row in rows:
                labels = row.get("labels") or {}
                client = labels.get("client")
                pool = labels.get("pool")
                if not client or client.startswith("_"):
                    continue
                key = (str(client), str(pool))
                totals[key] = totals.get(key, 0.0) + float(
                    row.get("ops_w") or 0)
        rates: dict[str, float] = {}
        prev_ts = self._prev_client_ts
        if prev_ts is not None and now > prev_ts:
            dt = now - prev_ts
            for key, tot in totals.items():
                prev = self._prev_client_ops.get(key)
                if prev is None:
                    continue  # first sighting primes; a client whose
                    # row was LRU-folded and returned would otherwise
                    # book its whole cumulative history as one tick
                d = tot - prev
                if d > 0:
                    rates[f"{key[0]}/{key[1]}"] = d / dt
        self._prev_client_ops = totals
        self._prev_client_ts = now
        return rates

    # -- one tick ------------------------------------------------------------
    def tick(self) -> dict:
        obs = self.observe()
        decision = QoSController(self._clamps()).plan(obs)
        retuned = (abs(decision["window_ms"] - self._window_ms) > 1e-3
                   or decision["max_stripes"] != self._max_stripes
                   or bool(decision["classes"]))
        with self._lock:
            self._stats["ticks"] += 1
            self._stats["heavy_clients"] = sum(
                1 for rwl in decision["classes"].values() if not rwl[0])
            self._last = {"queue_p99_ms": obs.queue_p99_ms,
                          "encode_p99_ms": obs.encode_p99_ms,
                          "op_rate": obs.op_rate,
                          "background": obs.background,
                          "reasons": decision["reasons"]}
            self.decisions.append(
                {"ts": time.monotonic(), **decision})
            del self.decisions[:-128]
        pushed = 0
        if bool(self.cct.conf.get("mgr_qos_active")):
            pushed = self.push(decision)
        if pushed:
            # commit the plan into controller state ONLY once it is on
            # the OSDs: in observe-only mode (or with every send
            # failing) compounding decisions on hypothetical state
            # would geometrically drift the window to a clamp while
            # the cluster never changed — then the first real push
            # would slam the drifted value instead of tuning from the
            # actual current one
            with self._lock:
                self._window_ms = decision["window_ms"]
                self._max_stripes = decision["max_stripes"]
                if retuned:
                    self._stats["retunes"] += 1
            if retuned:
                TRACER.tracepoint(
                    "qos", "retune", entity="mgr",
                    window_ms=decision["window_ms"],
                    max_stripes=decision["max_stripes"],
                    classes=len(decision["classes"]),
                    queue_p99_ms=obs.queue_p99_ms,
                    encode_p99_ms=obs.encode_p99_ms,
                    op_rate=round(obs.op_rate, 1),
                    reasons="; ".join(decision["reasons"]))
        self.export()
        return decision

    # -- push ----------------------------------------------------------------
    def push(self, decision: dict) -> int:
        """One MQoSSettings per reporting OSD over its report conn."""
        with self._lock:
            self._epoch += 1
            epoch = self._epoch
        msg_options = {
            "ec_batch_window_ms": decision["window_ms"],
            "ec_batch_max_stripes": decision["max_stripes"],
        }
        classes = {name: list(rwl)
                   for name, rwl in decision["classes"].items()}
        sent = 0
        for daemon, conn in sorted(
                self.mgr.report_conns(prefix="osd.").items()):
            try:
                conn.send_message(MQoSSettings(
                    qos_epoch=epoch, options=msg_options,
                    classes=classes))
                sent += 1
            except (OSError, ConnectionError) as e:
                with self._lock:
                    self._stats["push_errors"] += 1
                self.cct.dout("mgr", 3,
                              f"qos push to {daemon} failed: {e!r}")
        with self._lock:
            self._stats["pushes"] += sent
        return sent

    # -- export ---------------------------------------------------------------
    def export(self) -> None:
        """Render the controller's state as ceph_qos_* series through
        the mgr's own report sink (prometheus + metrics_history)."""
        with self._lock:
            last = dict(self._last)
            bg = last.get("background") or {}
            rec = bg.get("background_recovery") or {}
            scr = bg.get("background_scrub") or {}
            counters = {"qos": {
                # cephheal (observe-only): the background classes'
                # scheduler state as first-class controller telemetry
                "recovery_depth": rec.get("depth") or 0,
                "recovery_served_rate": rec.get("rate") or 0.0,
                "recovery_wait_p99_ms": rec.get("wait_p99_ms") or 0.0,
                "scrub_depth": scr.get("depth") or 0,
                "scrub_served_rate": scr.get("rate") or 0.0,
                "scrub_wait_p99_ms": scr.get("wait_p99_ms") or 0.0,
                "window_ms": self._window_ms,
                "max_stripes": self._max_stripes,
                "ticks": self._stats["ticks"],
                "retunes": self._stats["retunes"],
                "pushes": self._stats["pushes"],
                "push_errors": self._stats["push_errors"],
                "heavy_clients": self._stats["heavy_clients"],
                "qos_epoch": self._epoch,
                "queue_p99_ms": last["queue_p99_ms"] or 0.0,
                "encode_p99_ms": last["encode_p99_ms"] or 0.0,
                "op_rate": round(last["op_rate"], 3),
                "active": int(bool(self.cct.conf.get("mgr_qos_active"))),
            }}
        self.mgr.ingest_local_report("mgr", counters, schema=_QOS_SCHEMA)

    def status(self) -> dict:
        with self._lock:
            return {
                "active": bool(self.cct.conf.get("mgr_qos_active")),
                "window_ms": self._window_ms,
                "max_stripes": self._max_stripes,
                "qos_epoch": self._epoch,
                "stats": dict(self._stats),
                "last": dict(self._last),
            }

    def serve(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(timeout=float(
                self.cct.conf.get("mgr_qos_interval")))
            if self._stop.is_set():
                return
            try:
                self.tick()
            except Exception as e:
                # one bad tick (a daemon mid-restart, a torn report)
                # must not kill the loop
                self.cct.dout("mgr", 1, f"qos tick failed: {e!r}")


_QOS_SCHEMA = {"qos": {
    "window_ms": {"type": "gauge",
                  "description": "controller's current "
                                 "ec_batch_window_ms target"},
    "max_stripes": {"type": "gauge",
                    "description": "controller's current "
                                   "ec_batch_max_stripes target"},
    "ticks": {"type": "u64", "description": "controller ticks run"},
    "retunes": {"type": "u64",
                "description": "ticks whose decision changed a knob or "
                               "class"},
    "pushes": {"type": "u64",
               "description": "MQoSSettings successfully sent to OSDs"},
    "push_errors": {"type": "u64",
                    "description": "failed MQoSSettings sends"},
    "heavy_clients": {"type": "gauge",
                      "description": "clients currently classed heavy "
                                     "(low mClock weight)"},
    "qos_epoch": {"type": "gauge",
                  "description": "monotonic settings epoch stamped on "
                                 "pushes"},
    "queue_p99_ms": {"type": "gauge",
                     "description": "observed stage_queue p99 this tick "
                                    "(windowed bucket deltas)"},
    "encode_p99_ms": {"type": "gauge",
                      "description": "observed stage_encode p99 this "
                                     "tick"},
    "op_rate": {"type": "gauge",
                "description": "aggregate client write ops/s observed"},
    "active": {"type": "gauge",
               "description": "1 = controller pushes settings; 0 = "
                              "observe/export only"},
    "recovery_depth": {
        "type": "gauge",
        "description": "background_recovery mClock queue depth summed "
                       "across OSDs (cephheal observe-only)"},
    "recovery_served_rate": {
        "type": "gauge",
        "description": "background_recovery ops dequeued per second "
                       "(windowed served-counter delta)"},
    "recovery_wait_p99_ms": {
        "type": "gauge",
        "description": "background_recovery enqueue->dequeue wait p99 "
                       "this tick (windowed bucket deltas)"},
    "scrub_depth": {
        "type": "gauge",
        "description": "background_scrub mClock queue depth summed "
                       "across OSDs"},
    "scrub_served_rate": {
        "type": "gauge",
        "description": "background_scrub ops dequeued per second"},
    "scrub_wait_p99_ms": {
        "type": "gauge",
        "description": "background_scrub enqueue->dequeue wait p99 "
                       "this tick"},
}}
