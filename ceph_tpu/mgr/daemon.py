"""MgrDaemon — module host + daemon report sink (reference: src/mgr/Mgr.cc
/ DaemonServer.cc: daemons stream MMgrReport, modules consume the state;
SURVEY.md §2.5).

    mgr = MgrDaemon(cct, mon_addrs)
    mgr.start()                  # hosts cct.conf 'mgr_modules'
    mgr.module('prometheus').url # scrape target
"""
from __future__ import annotations

import threading
import time

from ..mon.mon_client import MonClient
from ..msg import Dispatcher, Messenger
from .messages import MMgrReport
from .module import MODULE_REGISTRY, MgrModule

# imports register the in-tree modules
from . import balancer_module  # noqa: F401
from . import dashboard_module  # noqa: F401
from . import devicehealth_module  # noqa: F401
from . import iostat_module  # noqa: F401
from . import quota_module  # noqa: F401
from . import pg_autoscaler_module  # noqa: F401
from . import placement_module  # noqa: F401
from . import progress_module  # noqa: F401
from . import prometheus_module  # noqa: F401
from . import qos_module  # noqa: F401
from . import status_module  # noqa: F401
from .metrics_history import MetricsHistory  # also registers the module


class MgrDaemon(Dispatcher):
    def __init__(self, cct, mon_addrs):
        self.cct = cct
        self.messenger = Messenger.create(cct, "mgr")
        self.messenger.add_dispatcher(self)
        self.mc = MonClient(cct, mon_addrs, name="mgr-monc")
        self.messenger.auth_gen_provider = lambda: (
            self.mc.osdmap.auth_gens.get("mgr", 1) if self.mc.osdmap else 1
        )
        self._reports: dict[str, dict] = {}   # daemon -> last MMgrReport view
        self._reports_lock = threading.Lock()
        # cephqos: the connection each daemon's last report arrived on —
        # the controller's push channel back to it (MQoSSettings rides
        # the report plumbing instead of dialing admin sockets)
        self._report_conns: dict[str, object] = {}
        # cephmeter: the bounded time-series ring every history consumer
        # (iostat, `perf history`, future QoS controllers) queries — fed
        # synchronously per incoming MMgrReport, daemon-owned so it
        # exists whether or not the metrics_history module is hosted
        self.metrics_history = MetricsHistory(
            max_samples=int(cct.conf.get("mgr_metrics_history_samples")),
            max_series=int(cct.conf.get("mgr_metrics_history_max_series")),
            # well past the query-side staleness filter: hidden first,
            # forgotten (series slots freed) only once clearly dead
            forget_age=10 * float(cct.conf.get("mgr_stale_report_age")),
        )
        self._modules: dict[str, MgrModule] = {}
        self._threads: list[threading.Thread] = []
        self.addr: tuple[str, int] | None = None
        self._mon_addrs = mon_addrs
        self._rados = None  # lazy module-facing RADOS client
        self._rados_lock = threading.Lock()
        self._closed = False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.addr = self.messenger.bind(("127.0.0.1", 0))
        self.messenger.start()
        self.mc.subscribe_osdmap()
        self.mc.wait_for_osdmap(timeout=30.0)
        wanted = [
            m.strip()
            for m in str(self.cct.conf.get("mgr_modules")).split(",")
            if m.strip()
        ]
        for name in wanted:
            cls = MODULE_REGISTRY.get(name)
            if cls is None:
                self.cct.dout("mgr", 0, f"mgr: unknown module {name!r}")
                continue
            try:
                mod = cls(self)
            except Exception as e:
                # one module failing to construct (e.g. prometheus port
                # taken) must not take down the whole mgr
                self.cct.dout(
                    "mgr", 0, f"mgr module {name!r} failed to load: {e!r}"
                )
                continue
            self._modules[name] = mod
            t = threading.Thread(
                target=self._serve_module, args=(mod,),
                name=f"mgr-{name}", daemon=True,
            )
            self._threads.append(t)
            t.start()

    def _serve_module(self, mod: MgrModule) -> None:
        try:
            mod.serve()
        except Exception as e:
            self.cct.dout("mgr", 0, f"mgr module {mod.NAME} died: {e!r}")

    def shutdown(self) -> None:
        with self._rados_lock:
            self._closed = True  # no module may lazily mint a client now
        for mod in self._modules.values():
            try:
                mod.shutdown()
            except Exception as e:
                self.cct.dout("mgr", 0,
                              f"mgr module {mod.NAME} shutdown raised: {e!r}")
        # rados AFTER the modules that reach through it
        with self._rados_lock:
            if self._rados is not None:
                try:
                    self._rados.shutdown()
                except Exception as e:
                    self.cct.dout("mgr", 0,
                                  f"mgr rados shutdown raised: {e!r}")
                self._rados = None
        # module serve threads before the transports they report
        # through (teardown reverses bring-up)
        for t in self._threads:
            t.join(timeout=5)
        try:
            self.mc.shutdown()
        except Exception as e:
            self.cct.dout("mgr", 0,
                          f"mgr mon client shutdown raised: {e!r}")
        try:
            self.messenger.shutdown()
        except Exception as e:
            self.cct.dout("mgr", 0,
                          f"mgr messenger shutdown raised: {e!r}")
        # the context goes last: its admin socket serves debug commands
        # right up until the daemon is gone
        self.cct.shutdown()

    def module(self, name: str) -> MgrModule:
        return self._modules[name]

    # -- report sink -------------------------------------------------------
    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MMgrReport):
            ts = time.monotonic()
            with self._reports_lock:
                self._reports[msg.daemon] = {
                    "counters": msg.counters or {},
                    "schema": getattr(msg, "schema", None) or {},
                    "stats": msg.stats or {},
                    "epoch": msg.epoch,
                    "ts": ts,
                }
                self._report_conns[msg.daemon] = conn
            # one history sample per report, stamped with the ARRIVAL
            # time (rates divide by the report interval, not a sampling
            # cadence) — outside the reports lock; the store has its own
            self.metrics_history.add_report(
                msg.daemon, ts, msg.counters or {})
            return True
        return False

    def report_conns(self, prefix: str = "") -> dict:
        """{daemon: connection} of the freshest report senders (optionally
        filtered by name prefix, e.g. "osd.") — the QoS controller's
        push fan-out.  Staleness mirrors latest_reports: a dead daemon's
        conn must not be dialed forever."""
        max_age = self.cct.conf.get("mgr_stale_report_age")
        now = time.monotonic()
        with self._reports_lock:
            return {
                d: c for d, c in self._report_conns.items()
                if d.startswith(prefix)
                and d in self._reports
                and now - self._reports[d]["ts"] <= max_age
            }

    def ingest_local_report(self, daemon: str, counters: dict,
                            schema: dict | None = None,
                            stats: dict | None = None) -> None:
        """Feed a report authored INSIDE the mgr process (the QoS
        module's ceph_qos_* series) through the same sink daemon
        reports take: it lands in the latest-reports view (so the
        prometheus exporter renders it) AND the metrics-history ring
        (so the controller's own decisions are queryable history)."""
        ts = time.monotonic()
        with self._reports_lock:
            self._reports[daemon] = {
                "counters": counters or {},
                "schema": schema or {},
                "stats": stats or {},
                "epoch": 0,
                "ts": ts,
            }
        self.metrics_history.add_report(daemon, ts, counters or {})

    def latest_reports(self) -> dict:
        """{daemon: {subsystem: {counter: value}}}, stale reports dropped
        (a dead OSD's last snapshot must not linger on the dashboard)."""
        max_age = self.cct.conf.get("mgr_stale_report_age")
        now = time.monotonic()
        with self._reports_lock:
            return {
                d: r["counters"]
                for d, r in self._reports.items()
                if now - r["ts"] <= max_age
            }

    def latest_schemas(self) -> dict:
        """Merged {subsystem: {counter: {type, description}}} across
        daemons (same subsystem name = same declaration; later daemons
        win harmlessly) — the prometheus exporter's HELP/TYPE source."""
        merged: dict = {}
        with self._reports_lock:
            reports = [r.get("schema") or {} for r in self._reports.values()]
        for schema in reports:
            for subsys, counters in schema.items():
                merged.setdefault(subsys, {}).update(counters or {})
        return merged

    def rados_ioctx(self, pool: str):
        """Pool I/O handle for modules (the reference mgr holds its own
        librados instance modules reach through MgrModule.rados).
        Serialized + fail-safe: module HTTP threads race here, a failed
        connect must not leak its half-started client, and nothing may
        lazily mint a client after shutdown."""
        with self._rados_lock:
            if self._closed:
                raise IOError("mgr shutting down")
            if self._rados is None:
                from ..client.rados import Rados

                r = Rados(self.cct, self._mon_addrs, name="mgr-rados")
                try:
                    r.connect(timeout=10.0)
                except Exception:
                    r.shutdown()
                    raise
                self._rados = r
            return self._rados.open_ioctx(pool)

    def latest_reports_with_ts(self) -> dict:
        """{daemon: (arrival_ts, counters)} — rate computations must
        divide by the REPORT interval, not the caller's sampling
        interval (iostat)."""
        max_age = self.cct.conf.get("mgr_stale_report_age")
        now = time.monotonic()
        with self._reports_lock:
            return {
                d: (r["ts"], r["counters"])
                for d, r in self._reports.items()
                if now - r["ts"] <= max_age
            }

    def latest_stats(self) -> dict:
        return {d: s for d, (_t, s)
                in self.latest_stats_with_ts().items()}

    def pg_degraded_by_pgid(self) -> dict[str, int]:
        """Freshest-wins union of the primaries' pg_info rows ->
        {pgid: degraded objects}.  THE shared merge (progress module,
        balancer degraded-gate): each PG has one live author, but a
        deposed primary's final report lingers up to
        mgr_stale_report_age — merged oldest-first so the freshest
        author wins a same-pgid collision."""
        out: dict[str, int] = {}
        for _ts, st in sorted(self.latest_stats_with_ts().values(),
                              key=lambda tv: tv[0]):
            for pgid, info in (st.get("pg_info") or {}).items():
                out[pgid] = int(info.get("degraded") or 0)
        return out

    def latest_stats_with_ts(self) -> dict:
        """{daemon: (arrival_ts, stats)} — consumers that merge
        per-PG rows across daemons (progress, the status digest) must
        arbitrate duplicates by report FRESHNESS: after a primary
        change, the dead primary's final report lingers up to
        mgr_stale_report_age and its stale pg_info rows must not mask
        the new primary's (cephheal)."""
        max_age = self.cct.conf.get("mgr_stale_report_age")
        now = time.monotonic()
        with self._reports_lock:
            return {
                d: (r["ts"], r["stats"])
                for d, r in self._reports.items()
                if now - r["ts"] <= max_age
            }
