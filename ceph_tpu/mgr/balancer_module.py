"""Balancer module — periodic upmap optimization (reference:
src/pybind/mgr/balancer/module.py upmap mode: propose OSDMap::calc_pg_upmaps
fills against the current map, commit via mon commands).

The placement math itself is the batched-CRUSH library routine
(ceph_tpu/osd/balancer.py :: calc_pg_upmaps — one device launch per pass);
this module is the daemon loop driving it against the LIVE map."""
from __future__ import annotations

from ..osd.balancer import calc_pg_upmaps
from .module import MgrModule, register_module


@register_module
class BalancerModule(MgrModule):
    NAME = "balancer"

    def __init__(self, mgr):
        super().__init__(mgr)
        self.last_result: list = []
        self.passes = 0

    def optimize_once(self) -> list[tuple[int, int, int, int]]:
        """One balance pass: propose on a scratch copy of the live map,
        commit each change as `osd pg-upmap-items` (the reference commits
        an inc map the same way)."""
        m = self.get("osd_map")
        if m is None or not m.pools:
            return []
        import copy

        scratch = copy.deepcopy(m)
        changes = calc_pg_upmaps(scratch)
        active = self.cct.conf.get("mgr_balancer_active")
        if active:
            committed = set()
            for pool_id, ps, _from, _to in changes:
                if (pool_id, ps) in committed:
                    continue  # one command carries the pg's full pair list
                committed.add((pool_id, ps))
                pairs = scratch.pg_upmap_items.get((pool_id, ps), [])
                rv, res = self.mon_command({
                    "prefix": "osd pg-upmap-items",
                    "pool": pool_id,
                    "ps": ps,
                    "mappings": [list(p) for p in pairs],
                })
                if rv != 0:
                    self.cct.dout(
                        "mgr", 1, f"balancer: upmap commit failed: {res}"
                    )
        self.last_result = changes
        self.passes += 1
        return changes

    def serve(self) -> None:
        interval = self.cct.conf.get("mgr_balancer_interval")
        while not self._stop.wait(interval):
            try:
                self.optimize_once()
            except Exception as e:
                self.cct.dout("mgr", 1, f"balancer pass failed: {e!r}")
