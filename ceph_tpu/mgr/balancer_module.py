"""Balancer module — periodic upmap optimization (reference:
src/pybind/mgr/balancer/module.py upmap mode: propose OSDMap::calc_pg_upmaps
fills against the current map, commit via mon commands; `balancer eval` /
`balancer status` are the upstream introspection surfaces mirrored here).

The placement math itself is the batched-CRUSH library routine
(ceph_tpu/osd/balancer.py :: calc_pg_upmaps — one device launch per pass);
this module is the daemon loop driving it against the LIVE map.

cephplace un-blinding: every pass is a first-class observed operation —
pre/post skew scores from the shared scoring core (the `balancer eval`
analog), proposed/committed/failed move counts, a bounded score
trajectory, `balancer` tracepoints per pass and per commit failure,
``ceph_balancer_*`` prometheus series, and a snapshot riding the status
digest so the mon answers `balancer status`.  Failed `osd
pg-upmap-items` commits COUNT (``balancer_errors`` + ``last_error``)
instead of scrolling away at dout level 1."""
from __future__ import annotations

import copy
import time

from ..common.lockdep import make_lock
from ..common.tracer import TRACER
from ..osd.balancer import calc_pg_upmaps
from ..osd.placement import cluster_report
from .module import MgrModule, register_module

#: score-trajectory samples kept for `balancer status`
_MAX_SCORES = 64


def _scores(report: dict) -> dict:
    return {"score": round(report["score"], 4),
            "max_deviation": round(report["max_deviation"], 2),
            "stddev": round(report["stddev"], 2)}


@register_module
class BalancerModule(MgrModule):
    NAME = "balancer"

    def __init__(self, mgr):
        super().__init__(mgr)
        self._lock = make_lock("mgr::balancer")
        self.last_result: list = []
        self.passes = 0
        self._stats = {"moves_proposed": 0, "moves_committed": 0,
                       "commits_failed": 0, "balancer_errors": 0,
                       "passes_skipped": 0}
        self._last_error: str | None = None
        self._last_pass: dict = {}
        self._last_skip: dict = {}
        self._score_trajectory: list[dict] = []

    def _unclean_reason(self) -> str | None:
        """Upstream parity (mgr balancer Module.optimize refuses while
        objects are degraded): an upmap commit mid-recovery retargets
        acting sets under the recovering PGs."""
        try:
            merged = self.mgr.pg_degraded_by_pgid()
        except Exception:
            return None  # fail open: a bare test mgr carries no stats
        deg = sum(merged.values())
        if deg:
            pgs = sum(1 for v in merged.values() if v)
            return f"{deg} object(s) degraded across {pgs} pg(s)"
        return None

    def optimize_once(self) -> list[tuple[int, int, int, int]]:
        """One balance pass: propose on a scratch copy of the live map,
        commit each change as `osd pg-upmap-items` (the reference commits
        an inc map the same way)."""
        m = self.get("osd_map")
        if m is None or not m.pools:
            # nothing to score or move — and no O(map) deepcopy either.
            # Still export: the series are guaranteed from boot, and a
            # report older than mgr_stale_report_age drops off the
            # exporter — idling must not unpublish them
            self.export()
            return []
        unclean = self._unclean_reason()
        if unclean is not None:
            # the skip is itself observed (`balancer status` last_skip,
            # `balancer_passes_skipped`, `balancer` tracepoint); the
            # pass counter stays still, so PG_IMBALANCE's idle-balancer
            # rule sees an idle balancer
            with self._lock:
                self._last_skip = {"ts": time.monotonic(),
                                   "reason": unclean}
                self._stats["passes_skipped"] += 1
            TRACER.tracepoint("balancer", "skipped", entity="mgr",
                              reason=unclean)
            self.export()
            return []
        scratch = copy.deepcopy(m)
        # pre/post skew from the shared core: ONE batched sweep of the
        # pre-change scratch feeds both the pre score and the greedy
        # loop; only the post score re-maps (the upmaps changed) — the
        # `balancer eval` pair at two sweeps per pass, not three
        mappings = {pid: scratch.map_pool(pid)
                    for pid in sorted(scratch.pools)}
        pre = _scores(cluster_report(scratch, mappings=mappings))
        changes = calc_pg_upmaps(scratch, mappings=mappings)
        active = bool(self.cct.conf.get("mgr_balancer_active"))
        committed = failed = 0
        last_error = None
        failed_keys: set[tuple[int, int]] = set()
        # moves per PG: one mon command carries a pg's full pair list,
        # but committed/failed count MOVES so they share units with
        # `proposed` (a 2-move PG must not render as 2 proposed /
        # 1 committed / 0 errors)
        per_pg: dict[tuple[int, int], int] = {}
        for pool_id, ps, _from, _to in changes:
            per_pg[(pool_id, ps)] = per_pg.get((pool_id, ps), 0) + 1
        if active:
            for (pool_id, ps), n_moves in per_pg.items():
                pairs = scratch.pg_upmap_items.get((pool_id, ps), [])
                rv, res = self.mon_command({
                    "prefix": "osd pg-upmap-items",
                    "pool": pool_id,
                    "ps": ps,
                    "mappings": [list(p) for p in pairs],
                })
                if rv != 0:
                    failed += n_moves
                    failed_keys.add((pool_id, ps))
                    last_error = (f"pg-upmap-items {pool_id}.{ps:x} "
                                  f"refused: {rv} {res}")
                    self.cct.dout(
                        "mgr", 1, f"balancer: upmap commit failed: {res}"
                    )
                    TRACER.tracepoint(
                        "balancer", "commit_failed", entity="mgr",
                        pg=f"{pool_id}.{ps:x}", retval=rv,
                        error=str(res)[:200])
                else:
                    committed += n_moves
        # score_after describes what LANDED: roll refused commits back
        # off the scratch map before re-scoring (a mon that refuses
        # every move must not export a converging score).  In dry-run
        # the full proposal is scored — the `balancer eval` semantics.
        for key in failed_keys:
            orig = m.pg_upmap_items.get(key)
            if orig is None:
                scratch.pg_upmap_items.pop(key, None)
            else:
                scratch.pg_upmap_items[key] = [tuple(p) for p in orig]
        landed = committed if active else len(changes)
        post = _scores(cluster_report(scratch)) if landed else dict(pre)
        with self._lock:
            self.last_result = changes
            self.passes += 1
            n_pass = self.passes
            self._stats["moves_proposed"] += len(changes)
            self._stats["moves_committed"] += committed
            self._stats["commits_failed"] += failed
            # error EVENTS (one per refused command), not failed moves
            self._stats["balancer_errors"] += len(failed_keys)
            if last_error is not None:
                self._last_error = last_error
            self._last_pass = {
                "ts": time.monotonic(),
                "active": active,
                "proposed": len(changes),
                "committed": committed,
                "failed": failed,
                "score_before": pre,
                "score_after": post,
            }
            self._score_trajectory.append(
                {"pass": n_pass, "before": pre["score"],
                 "after": post["score"]})
            del self._score_trajectory[:-_MAX_SCORES]
        TRACER.tracepoint(
            "balancer", "pass", entity="mgr", n=n_pass, active=active,
            proposed=len(changes), committed=committed, failed=failed,
            score_before=pre["score"], score_after=post["score"],
            max_deviation_before=pre["max_deviation"],
            max_deviation_after=post["max_deviation"])
        self.export()
        return changes

    # -- introspection -------------------------------------------------------
    def last_pass(self) -> dict:
        with self._lock:
            return dict(self._last_pass)

    def status(self) -> dict:
        """The `balancer status` payload / digest section (JSON-safe):
        passes, move outcomes, score trajectory, last error."""
        now = time.monotonic()
        with self._lock:
            lp = dict(self._last_pass)
            ls = dict(self._last_skip)
            out = {
                "active": bool(self.cct.conf.get("mgr_balancer_active")),
                "passes": self.passes,
                **dict(self._stats),
                "last_error": self._last_error,
                "last_pass": lp or None,
                "last_skip": ls or None,
                "score_trajectory": list(self._score_trajectory[-16:]),
            }
        if lp:
            out["last_pass_age_seconds"] = round(now - lp["ts"], 1)
        if ls:
            out["last_skip_age_seconds"] = round(now - ls["ts"], 1)
        return out

    def export(self) -> None:
        """ceph_balancer_* series through the mgr's own report sink."""
        with self._lock:
            lp = self._last_pass
            counters = {"balancer": {
                "passes": self.passes,
                "passes_skipped": self._stats["passes_skipped"],
                "moves_proposed": self._stats["moves_proposed"],
                "moves_committed": self._stats["moves_committed"],
                "balancer_errors": self._stats["balancer_errors"],
                "active": int(bool(
                    self.cct.conf.get("mgr_balancer_active"))),
                "last_proposed": lp.get("proposed", 0),
                "last_committed": lp.get("committed", 0),
                "score_before": (lp.get("score_before") or {}).get(
                    "score", 0.0),
                "score_after": (lp.get("score_after") or {}).get(
                    "score", 0.0),
                "max_deviation_after": (lp.get("score_after") or {}).get(
                    "max_deviation", 0.0),
            }}
        self.mgr.ingest_local_report("mgr.balancer", counters,
                                     schema=_BALANCER_SCHEMA)

    def serve(self) -> None:
        interval = self.cct.conf.get("mgr_balancer_interval")
        try:
            # the series must exist from boot, not from the first pass
            # (a dashboard scraping a freshly-started idle balancer)
            self.export()
        except Exception as e:
            self.cct.dout("mgr", 3, f"balancer boot export failed: {e!r}")
        while not self._stop.wait(interval):
            try:
                self.optimize_once()
            except Exception as e:
                with self._lock:
                    self._stats["balancer_errors"] += 1
                    self._last_error = f"pass raised: {e!r}"
                self.cct.dout("mgr", 1, f"balancer pass failed: {e!r}")
                try:
                    # the error counter is the alertable surface — it
                    # must move even when the pass never reached export
                    self.export()
                except Exception as e2:
                    self.cct.dout("mgr", 3,
                                  f"balancer error export failed: {e2!r}")


_BALANCER_SCHEMA = {"balancer": {
    "passes": {"type": "u64", "description": "balancer passes run"},
    "passes_skipped": {"type": "u64",
                       "description": "passes refused against a "
                                      "degraded cluster (reason in "
                                      "`balancer status` last_skip)"},
    "moves_proposed": {"type": "u64",
                       "description": "upmap moves calc_pg_upmaps "
                                      "proposed across passes"},
    "moves_committed": {"type": "u64",
                        "description": "upmap moves the mon accepted "
                                       "(same units as moves_proposed; "
                                       "one pg-upmap-items command may "
                                       "carry several)"},
    "balancer_errors": {"type": "u64",
                        "description": "error events: refused "
                                       "pg-upmap-items commands + raised "
                                       "passes (details in `balancer "
                                       "status` last_error)"},
    "active": {"type": "gauge",
               "description": "1 = commits moves; 0 = dry-run "
                              "(mgr_balancer_active)"},
    "last_proposed": {"type": "gauge",
                      "description": "moves proposed by the latest pass"},
    "last_committed": {"type": "gauge",
                       "description": "moves committed by the latest "
                                      "pass"},
    "score_before": {"type": "gauge",
                     "description": "normalized skew score before the "
                                    "latest pass (shared scoring core; "
                                    "0 = perfect)"},
    "score_after": {"type": "gauge",
                    "description": "normalized skew score after the "
                                   "latest pass"},
    "max_deviation_after": {"type": "gauge",
                            "description": "largest per-OSD deviation "
                                           "(PG shards) after the "
                                           "latest pass"},
}}
