"""iostat module — cluster IO rates from perf-report deltas (reference:
src/pybind/mgr/iostat/module.py feeding `ceph iostat`: rd/wr ops and
bytes per second computed between consecutive daemon reports)."""
from __future__ import annotations

from .module import MgrModule, register_module

_RATE_COUNTERS = ("op", "op_r", "op_w", "op_r_bytes", "op_w_bytes")


@register_module
class IostatModule(MgrModule):
    NAME = "iostat"

    def __init__(self, mgr):
        super().__init__(mgr)
        # daemon -> (ts, {counter: value}) of the previous sample
        self._prev: dict[str, tuple[float, dict]] = {}

    def sample(self) -> dict:
        """Cluster-wide rates between each daemon's two most recent
        REPORTS (first call primes the baseline and reports zeros, like
        `iostat`'s first line being since-boot noise the reference also
        skips).  Deltas divide by the report ARRIVAL interval, not the
        caller's sampling cadence, so polling faster than
        mgr_report_interval neither zeroes nor inflates the rates."""
        reports = self.mgr.latest_reports_with_ts()
        # prune daemons that fell out of the report window (dead or
        # removed): their stale baselines must not linger, and a daemon
        # returning later restarts from a fresh baseline
        for gone in set(self._prev) - set(reports):
            del self._prev[gone]
        totals = {c: 0.0 for c in _RATE_COUNTERS}
        per_daemon: dict[str, dict] = {}
        for daemon, (ts, subsystems) in reports.items():
            osd = subsystems.get("osd") or {}
            cur = {c: float(osd.get(c, 0)) for c in _RATE_COUNTERS}
            prev = self._prev.get(daemon)
            if prev is not None and ts == prev[0]:
                # same report as last sample: keep the old baseline so
                # the NEXT fresh report diffs against real history
                prev_for_rates = None
            else:
                self._prev[daemon] = (ts, cur)
                prev_for_rates = prev
            prev = prev_for_rates
            if prev is None:
                continue
            dt = ts - prev[0]
            if dt <= 0:
                continue
            rates = {
                # counters can reset when a daemon restarts: clamp to 0
                # instead of reporting a huge negative rate
                c: max(0.0, (cur[c] - prev[1][c]) / dt)
                for c in _RATE_COUNTERS
            }
            per_daemon[daemon] = rates
            for c in _RATE_COUNTERS:
                totals[c] += rates[c]
        return {
            "ops_per_s": round(totals["op"], 1),
            "rd_ops_per_s": round(totals["op_r"], 1),
            "wr_ops_per_s": round(totals["op_w"], 1),
            "rd_bytes_per_s": round(totals["op_r_bytes"], 1),
            "wr_bytes_per_s": round(totals["op_w_bytes"], 1),
            "daemons": per_daemon,
        }
