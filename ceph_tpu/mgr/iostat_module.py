"""iostat module — cluster IO rates from the shared metrics-history
store (reference: src/pybind/mgr/iostat/module.py feeding `ceph
iostat`: rd/wr ops and bytes per second computed between consecutive
daemon reports).

cephmeter refactor (PR 11): the module used to hand-roll its own
``_prev`` delta tracking over ``latest_reports_with_ts``; that private
value history is gone — the DATA lives in ``mgr.metrics_history``, the
same bounded ring every other history consumer (the `perf history`
command, future QoS controllers) queries.  The module keeps only a
per-daemon poll CURSOR (the newest sample ts it saw last time) so the
old semantics survive the refactor: a rate covers everything since the
previous ``sample()`` call — a counter burst between two polls is never
missed — deltas divide by report ARRIVAL intervals, counter resets
clamp to 0, and dead daemons drop out via the staleness filter (hidden
from output immediately; the store forgets their series — and this
module their cursors — after the store's ``forget_age``)."""
from __future__ import annotations

from .module import MgrModule, register_module

_RATE_COUNTERS = ("op", "op_r", "op_w", "op_r_bytes", "op_w_bytes")


@register_module
class IostatModule(MgrModule):
    NAME = "iostat"

    def __init__(self, mgr):
        super().__init__(mgr)
        # daemon -> newest history-sample ts consumed by the previous
        # sample() call (a cursor into the SHARED store, not a value
        # copy — the first call primes it and reports zeros, like
        # `iostat`'s since-boot first line the reference also skips)
        self._cursor: dict[str, float] = {}

    def sample(self) -> dict:
        """Cluster-wide rates since the PREVIOUS sample() call, from
        the shared metrics-history store."""
        h = self.mgr.metrics_history
        max_age = self.cct.conf.get("mgr_stale_report_age")
        totals = {c: 0.0 for c in _RATE_COUNTERS}
        per_daemon: dict[str, dict] = {}
        seen: dict[str, float] = {}
        for c in _RATE_COUNTERS:
            rates = h.rate_since(f"osd.{c}", self._cursor,
                                 max_age=max_age)
            for daemon, (r, ts) in rates.items():
                seen[daemon] = max(ts, seen.get(daemon, 0.0))
                if r is None:
                    continue  # priming: cursor set, rate next poll
                per_daemon.setdefault(daemon, {})[c] = r
                totals[c] += r
        # advance cursors for daemons with fresh reports.  A daemon
        # rate_since omitted this poll (nothing new yet, or briefly
        # stale) keeps its cursor — if it returns after a restart the
        # reset-clamp yields one 0 rate and the next poll is clean;
        # one the STORE has forgotten (silent past forget_age) loses
        # its cursor too, so _cursor cannot grow without bound under
        # daemon churn
        for daemon, ts in seen.items():
            self._cursor[daemon] = ts
        live = set(h.daemons())
        for gone in set(self._cursor) - live:
            del self._cursor[gone]
        for rates in per_daemon.values():
            for c in _RATE_COUNTERS:
                rates.setdefault(c, 0.0)
        return {
            "ops_per_s": round(totals["op"], 1),
            "rd_ops_per_s": round(totals["op_r"], 1),
            "wr_ops_per_s": round(totals["op_w"], 1),
            "rd_bytes_per_s": round(totals["op_r_bytes"], 1),
            "wr_bytes_per_s": round(totals["op_w_bytes"], 1),
            "daemons": per_daemon,
        }
