"""metrics_history — the mgr's bounded time-series ring (reference:
the PGMap/ClusterState history the reference mgr keeps for `ceph
iostat` and the prometheus module's self-queries; cephmeter PR 11).

Every incoming ``MMgrReport`` lands one sample per numeric counter into
a per-(daemon, series) ring — fed synchronously from
``MgrDaemon.ms_dispatch``, so there is no polling race and the sample
timestamp IS the report's arrival time (rates must divide by the report
interval, not a caller's cadence).  The store is the "controller reads
its own Prometheus series" substrate from the ROADMAP's closed-loop QoS
item: anything hosted by the mgr (iostat, a future batch-window tuner)
queries ``series()``/``rate()`` instead of hand-rolling private delta
tracking.

Bounds: ``mgr_metrics_history_samples`` per series,
``mgr_metrics_history_max_series`` series total (overflow is dropped
and counted — a runaway-cardinality daemon cannot eat the mgr).

Series names are ``"<subsystem>.<counter>"``; histogram counters
contribute ``<name>.count``/``<name>.sum`` sub-series and longrunavg
counters ``<name>.avgcount``/``<name>.sum`` (both rate-able).  Labeled
row structures (the ``client_io`` accounting table) stay on the
prometheus path — flattening per-client rows here would defeat the
series cap.

The ``metrics_history`` mgr module is the query surface; a compact
``digest()`` snapshot rides the status module's MMonMgrReport digest so
the mon can answer the ``perf history`` CLI command without talking to
the mgr.
"""
from __future__ import annotations

from collections import deque

from ..common.lockdep import make_lock
from .module import MgrModule, register_module

#: the series the mon-facing digest snapshot carries (the `ceph perf
#: history` surface — iostat's rate counters, the cluster IO story)
DIGEST_SERIES = ("osd.op", "osd.op_r", "osd.op_w",
                 "osd.op_r_bytes", "osd.op_w_bytes")
#: samples per series in the digest snapshot (bounded: the digest
#: repeats every mgr_digest_interval)
DIGEST_SAMPLES = 20


def _flatten(counters: dict):
    """Yield (series_name, float) for every rate-able value in one
    MMgrReport counters payload."""
    for subsys, cs in (counters or {}).items():
        if not isinstance(cs, dict):
            continue
        for cname, v in cs.items():
            name = f"{subsys}.{cname}"
            if isinstance(v, bool):
                yield name, float(v)
            elif isinstance(v, (int, float)):
                yield name, float(v)
            elif isinstance(v, dict):
                if v.get("__labeled__"):
                    continue  # labeled rows: prometheus-path only
                if "buckets" in v:  # TYPE_HISTOGRAM dump
                    yield f"{name}.count", float(v.get("count", 0))
                    yield f"{name}.sum", float(v.get("sum", 0.0))
                elif "avgcount" in v:  # longrunavg dump
                    yield f"{name}.avgcount", float(v.get("avgcount", 0))
                    yield f"{name}.sum", float(v.get("sum", 0.0))


class MetricsHistory:
    """Bounded per-(daemon, series) sample rings + query API."""

    def __init__(self, max_samples: int = 512, max_series: int = 8192,
                 forget_age: float | None = 300.0):
        self.max_samples = max(2, int(max_samples))
        self.max_series = max(1, int(max_series))
        #: a daemon silent this long is FORGOTTEN at the next ingest —
        #: dead/renamed daemons must not pin max_series slots forever
        #: (None disables; distinct from the query-side staleness
        #: filter, which only hides, never frees)
        self.forget_age = forget_age
        self._lock = make_lock("mgr::metrics_history")
        self._series: dict[tuple[str, str], deque] = {}
        self._last_ts: dict[str, float] = {}
        # distinct (daemon, series) keys refused by the cap (bounded
        # itself) vs raw refused samples — the cardinality diagnostic
        # must count SERIES, not inflate per report
        self._refused: set[tuple[str, str]] = set()
        self._dropped_samples = 0

    # -- ingest (MgrDaemon.ms_dispatch, one call per MMgrReport) -----------
    def add_report(self, daemon: str, ts: float, counters: dict) -> None:
        with self._lock:
            if self._last_ts.get(daemon) == ts:
                # same-timestamp re-ingest (an explicit-ts caller
                # replaying a report); the mgr's dispatch path stamps
                # fresh arrival times, so there this never fires
                return
            if self.forget_age is not None:
                for gone in [d for d, t in self._last_ts.items()
                             if ts - t > self.forget_age]:
                    self._forget_daemon_locked(gone)
            self._last_ts[daemon] = ts
            for name, value in _flatten(counters):
                key = (daemon, name)
                ring = self._series.get(key)
                if ring is None:
                    if len(self._series) >= self.max_series:
                        self._dropped_samples += 1
                        if len(self._refused) < 1024:
                            self._refused.add(key)
                        continue
                    ring = self._series[key] = deque(
                        maxlen=self.max_samples)
                ring.append((ts, value))

    def _forget_daemon_locked(self, daemon: str) -> None:
        self._last_ts.pop(daemon, None)
        for key in [k for k in self._series if k[0] == daemon]:
            del self._series[key]

    def forget_daemon(self, daemon: str) -> None:
        with self._lock:
            self._forget_daemon_locked(daemon)

    # -- queries -----------------------------------------------------------
    def names(self) -> list[str]:
        with self._lock:
            return sorted({n for _d, n in self._series})

    def daemons(self) -> list[str]:
        with self._lock:
            return sorted({d for d, _n in self._series})

    def series(self, name: str, since: float | None = None,
               daemon: str | None = None):
        """Samples of one series: ``{daemon: [(ts, value), ...]}``, or a
        plain ``[(ts, value), ...]`` when ``daemon`` is given.  ``since``
        filters to samples with ts > since (pass the last ts you saw —
        the incremental-poll idiom a controller loop uses)."""
        with self._lock:
            out = {
                d: [s for s in ring if since is None or s[0] > since]
                for (d, n), ring in self._series.items()
                if n == name and (daemon is None or d == daemon)
            }
        if daemon is not None:
            return out.get(daemon, [])
        return out

    def latest(self, name: str, daemon: str) -> tuple[float, float] | None:
        with self._lock:
            ring = self._series.get((daemon, name))
            return ring[-1] if ring else None

    def rate(self, name: str, daemon: str | None = None,
             max_age: float | None = None, now: float | None = None):
        """Per-second rate between each daemon's two most recent samples
        of a counter series — ``{daemon: rate}`` (or a float/None when
        ``daemon`` is given).  Counter resets (daemon restart) clamp to
        0 instead of a huge negative rate; a daemon whose newest sample
        is older than ``max_age`` (dead or removed) is excluded, so
        stale baselines never linger."""
        if now is None:
            import time

            now = time.monotonic()
        with self._lock:
            out: dict[str, float] = {}
            for (d, n), ring in self._series.items():
                if n != name or (daemon is not None and d != daemon):
                    continue
                if len(ring) < 2:
                    continue
                (t0, v0), (t1, v1) = ring[-2], ring[-1]
                if max_age is not None and now - t1 > max_age:
                    continue
                dt = t1 - t0
                if dt <= 0:
                    continue
                out[d] = max(0.0, (v1 - v0) / dt)
        if daemon is not None:
            return out.get(daemon)
        return out

    def rate_since(self, name: str, cursors: dict[str, float],
                   max_age: float | None = None,
                   now: float | None = None) -> dict:
        """Per-second rate between each daemon's NEWEST sample and its
        newest sample at-or-before ``cursors[daemon]`` — the
        poll-cursor idiom: a caller that samples on its own cadence
        (iostat) passes the newest ts it saw last time, so a counter
        burst BETWEEN two polls is never missed the way a
        last-two-reports rate would miss it.

        Returns ``{daemon: (rate_or_None, newest_ts)}``: rate None
        means "priming" (no cursor yet — the caller records newest_ts
        and gets a real rate next poll).  A daemon with no report newer
        than its cursor, or staler than ``max_age``, is omitted (the
        caller keeps its old cursor).  A cursor older than the ring
        tail falls back to the oldest retained sample.  Counter resets
        clamp to 0."""
        if now is None:
            import time

            now = time.monotonic()
        out: dict[str, tuple[float | None, float]] = {}
        with self._lock:
            for (d, n), ring in self._series.items():
                if n != name or not ring:
                    continue
                t1, v1 = ring[-1]
                if max_age is not None and now - t1 > max_age:
                    continue
                cur = cursors.get(d)
                if cur is None:
                    out[d] = (None, t1)  # prime
                    continue
                if t1 <= cur:
                    continue  # no new report since the caller's cursor
                base = None
                for ts, v in reversed(ring):
                    if ts <= cur:
                        base = (ts, v)
                        break
                if base is None:
                    base = ring[0]  # cursor evicted: oldest retained
                t0, v0 = base
                dt = t1 - t0
                if dt <= 0:
                    continue
                out[d] = (max(0.0, (v1 - v0) / dt), t1)
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "samples": sum(len(r) for r in self._series.values()),
                "max_samples": self.max_samples,
                "max_series": self.max_series,
                "dropped_series": len(self._refused),
                "dropped_samples": self._dropped_samples,
            }

    def digest(self, names: tuple = DIGEST_SERIES,
               samples: int = DIGEST_SAMPLES) -> dict:
        """Compact snapshot for the mgr->mon digest: the `perf history`
        mon command answers from this without a mon->mgr channel."""
        with self._lock:
            daemons: dict[str, dict] = {}
            for (d, n), ring in self._series.items():
                if n not in names or not ring:
                    continue
                daemons.setdefault(d, {})[n] = [
                    [round(ts, 3), v] for ts, v in list(ring)[-samples:]
                ]
        return {"names": sorted(names), "daemons": daemons,
                "samples_per_series": samples}


@register_module
class MetricsHistoryModule(MgrModule):
    """Query surface over the MgrDaemon-owned store (the store itself
    is fed in ms_dispatch so it exists even when this module is not
    hosted — iostat reaches it through ``mgr.metrics_history``)."""

    NAME = "metrics_history"

    @property
    def store(self) -> MetricsHistory:
        return self.mgr.metrics_history

    def series(self, name: str, since: float | None = None,
               daemon: str | None = None):
        return self.store.series(name, since=since, daemon=daemon)

    def rate(self, name: str, daemon: str | None = None):
        return self.store.rate(
            name, daemon=daemon,
            max_age=self.cct.conf.get("mgr_stale_report_age"))

    def summary(self) -> dict:
        return {"stats": self.store.stats(),
                "daemons": self.store.daemons(),
                "names": self.store.names()}
