"""Multi-chip sharding of EC stripe batches and CRUSH x-batches."""
from .mesh import (
    LEN_AXIS,
    ROW_AXIS,
    distributed_decode,
    make_mesh,
    sharded_apply_matrix,
)

__all__ = [
    "LEN_AXIS",
    "ROW_AXIS",
    "distributed_decode",
    "make_mesh",
    "sharded_apply_matrix",
]
