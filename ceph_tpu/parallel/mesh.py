"""Device-mesh sharding of EC batches — the ICI/DCN story (SURVEY.md §5.8).

The reference scales by spreading PGs over OSDs with CRUSH and shipping
sub-ops over the AsyncMessenger (reference: src/msg/async/AsyncMessenger.cc);
the TPU-native equivalent parallelizes the *batch*: shard-length (stripe) and
CRUSH-x batches are laid out over a jax.sharding.Mesh so XLA rides ICI with
collectives only where the computation genuinely mixes shards:

- encode / matrix apply: contraction is over bitplanes (replicated), batch
  axis is shard length -> purely local compute, zero collectives (the DP/SP
  analog; SURVEY.md §2.9).
- distributed recovery: surviving shard rows live on different devices and
  the decode mixes all of them -> one all_gather over the shard axis (the
  TP analog of ECBackend reading k shards across OSDs, reference:
  src/osd/ECBackend.cc :: objects_read_and_reconstruct).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.device_policy import get_device_policy, mesh_over
from ..ops.bitplane import _apply_bitmatrix, bitmatrix_device

# jax.shard_map graduated from jax.experimental at 0.4.x boundaries and
# renamed its replication-check kwarg (check_rep -> check_vma) on the
# way; accept either spelling so the decode path works on the pinned
# runtime
_shard_map = getattr(jax, "shard_map", None)
_CHECK_KW = "check_vma"
if _shard_map is None:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"

LEN_AXIS = "shard_len"  # stripe-batch axis (data/sequence-parallel analog)
ROW_AXIS = "shard_row"  # shard-id axis (tensor-parallel analog)


def make_mesh(n_devices: int | None = None, axis: str = LEN_AXIS,
              policy=None) -> Mesh:
    """Mesh over the policy-granted devices (cephtopo: the ambient
    jax.devices() probe moved behind the injected DevicePolicy; the cpu
    variant yields a 1-device mesh, a sentinel-shrunk policy a smaller
    one).  ``policy=None`` consults the process-wide policy the first
    daemon configured; ``n_devices`` keeps the historical take-first-n
    cap so MULTICHIP_r05 callers are unchanged."""
    pol = policy if policy is not None else get_device_policy()
    return pol.mesh(n_devices, axis)


def sharded_apply_matrix(mesh: Mesh, mat: np.ndarray, chunks) -> jax.Array:
    """GF matrix apply with the shard-length axis split across the mesh.

    chunks [n, L] with L sharded; the bitmatrix is replicated; no
    collectives are inserted (verified by the multichip dryrun).
    """
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    B = bitmatrix_device(mat.tobytes(), mat.shape)
    chunks = jnp.asarray(chunks, dtype=jnp.uint8)
    in_shard = NamedSharding(mesh, P(None, LEN_AXIS))
    rep = NamedSharding(mesh, P(None, None))
    chunks = jax.device_put(chunks, in_shard)
    B = jax.device_put(B, rep)
    fn = jax.jit(_apply_bitmatrix, out_shardings=in_shard)
    return fn(B, chunks)


def distributed_decode(mesh: Mesh, decode_mat: np.ndarray, shards) -> jax.Array:
    """Recover data when the k surviving shard rows are sharded over devices.

    shards [k, L] with the ROW axis sharded (each device holds some shard
    rows, like OSDs holding EC shards); the decode matrix mixes every row, so
    shard rows are all-gathered over ICI, then each device computes the full
    [k, L] reconstruction of its L-slice.  Uses shard_map + all_gather — the
    explicit-collective formulation of SURVEY.md §7 step 7.
    """
    k, L = shards.shape
    mat = np.ascontiguousarray(decode_mat, dtype=np.uint8)
    B = bitmatrix_device(mat.tobytes(), mat.shape)
    shards = jnp.asarray(shards, dtype=jnp.uint8)
    row_mesh = mesh_over(mesh.devices, ROW_AXIS)
    n = row_mesh.devices.size
    if k % n != 0:
        # pad shard rows to a multiple of the mesh (zero rows are inert:
        # their bitmatrix columns are zero because decode_mat has k columns)
        pad = n - k % n
        shards = jnp.concatenate([shards, jnp.zeros((pad, L), jnp.uint8)])
        B = jnp.concatenate(
            [B, jnp.zeros((B.shape[0], pad * 8), jnp.int8)], axis=1
        )

    @partial(
        _shard_map,
        mesh=row_mesh,
        in_specs=(P(None, None), P(ROW_AXIS, None)),
        out_specs=P(None, None),
        # after the all_gather every device computes the same full result;
        # that replication isn't statically inferable, so skip the check
        # (check_vma on current jax, check_rep on the experimental home)
        **{_CHECK_KW: False},
    )
    def _decode(B_full, shard_slice):
        gathered = jax.lax.all_gather(
            shard_slice, ROW_AXIS, axis=0, tiled=True
        )
        return _apply_bitmatrix(B_full, gathered)

    return _decode(B, shards)
