"""Reed-Solomon plugin family — the jerasure/ISA-L analog, TPU-first.

Covers the matrix techniques of the reference's jerasure plugin (reference:
src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc} — one subclass per
technique, each with prepare() building the matrix) and the ISA-L plugin
(reference: src/erasure-code/isa/ErasureCodeIsa.{h,cc}):

    reed_sol_van    ErasureCodeJerasureReedSolomonVandermonde
    reed_sol_r6_op  ErasureCodeJerasureReedSolomonRAID6 (m=2: rows 1, 2^j)
    cauchy_orig     ErasureCodeJerasureCauchyOrig
    cauchy_good     ErasureCodeJerasureCauchyGood

The bitmatrix/packet techniques (liberation, blaum_roth, liber8tion —
reference: jerasure/liberation.c + ErasureCodeJerasureLiberation/
BlaumRoth/Liber8tion) run through BitmatrixCodec: m=2 RAID-6 codes whose
chunks split into w packets XOR-combined per a [2w, kw] GF(2) bitmatrix
(construction + provenance notes: gf/gf2.py), applied on-device through
the same MXU bitplane matmul as the byte codes.

Three interchangeable backends execute the same matrices:
    jax     bitplane GF(2) matmul on TPU (ceph_tpu.ops.bitplane)
    oracle  C++ SIMD split-table path (native/gf_oracle.cc — ISA-L analog)
    numpy   pure-python referee (ceph_tpu.gf.reference_codec)
Parity bytes are identical across backends (byte-wise GF semantics).
"""
from __future__ import annotations

import numpy as np

from ...gf.matrix import (
    cauchy_good_coding_matrix,
    cauchy_original_coding_matrix,
    vandermonde_coding_matrix,
)
from ...gf.tables import gf_pow
from ..interface import ErasureCode, InsufficientChunks, InvalidProfile
from ..registry import ErasureCodePlugin

TECHNIQUES = ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good")
BITMATRIX_TECHNIQUES = ("liberation", "blaum_roth", "liber8tion")


def build_coding_matrix(technique: str, k: int, m: int) -> np.ndarray:
    if technique == "reed_sol_van":
        return vandermonde_coding_matrix(k, m).astype(np.uint8)
    if technique == "reed_sol_r6_op":
        # reed_sol.c :: reed_sol_r6_coding_matrix — RAID-6: row0 all ones,
        # row1[j] = 2^j
        if m != 2:
            raise InvalidProfile("technique=reed_sol_r6_op requires m=2")
        mat = np.ones((2, k), dtype=np.uint8)
        mat[1] = [gf_pow(2, j) for j in range(k)]
        return mat
    if technique == "cauchy_orig":
        return cauchy_original_coding_matrix(k, m).astype(np.uint8)
    if technique == "cauchy_good":
        return cauchy_good_coding_matrix(k, m).astype(np.uint8)
    raise InvalidProfile(
        f"unknown technique {technique!r}; known: "
        f"{TECHNIQUES + BITMATRIX_TECHNIQUES}"
    )


class RSCodec(ErasureCode):
    """Systematic MDS Reed-Solomon codec over GF(2^8)."""

    def __init__(self, profile: dict | None = None, backend: str = "jax"):
        self.backend = backend
        self._jax_codec = None
        super().__init__(profile)

    def init(self, profile: dict) -> None:
        self.profile = dict(profile)
        self.k = self.parse_int(profile, "k", 2)
        self.m = self.parse_int(profile, "m", 1)
        self.technique = profile.get("technique", "reed_sol_van")
        w = self.parse_int(profile, "w", 8)
        if w != 8:
            raise InvalidProfile(
                f"w={w} unsupported: the TPU bitplane kernel is specialized "
                "for GF(2^8) (w=8), the default in the reference too"
            )
        if self.k < 1 or self.m < 1:
            raise InvalidProfile(f"k={self.k}, m={self.m} must be >= 1")
        self.coding = build_coding_matrix(self.technique, self.k, self.m)
        if self.backend == "jax":
            from ...ops.bitplane import BitplaneCodec

            self._jax_codec = BitplaneCodec(self.coding)

    # -- hot path (reference: ErasureCodeInterface.h :: encode_chunks) ----
    def supports_parity_delta(self) -> bool:
        # byte-wise GF matrix apply: strictly column-local, identity
        # placement — safe for the OSD's RMW parity-delta
        return True

    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray:
        data_chunks = np.ascontiguousarray(data_chunks, dtype=np.uint8)
        if self.backend == "jax":
            return np.asarray(self._jax_codec.encode(data_chunks))
        if self.backend == "oracle":
            from ... import native_oracle

            return native_oracle.encode(self.coding, data_chunks, fast=True)
        from ...gf.reference_codec import encode_chunks as np_encode

        return np_encode(self.coding, data_chunks)

    def decode_chunks(self, want_to_read, chunks: dict[int, np.ndarray]):
        avail = sorted(chunks)
        if len(avail) < self.k:
            raise InsufficientChunks(f"need {self.k}, have {len(avail)}")
        use = avail[: self.k]
        shards = np.stack([np.asarray(chunks[r], dtype=np.uint8) for r in use])
        if self.backend == "jax":
            # cephdma: the gathered helper stack commits to the device
            # through the stripe pool (recovery's _rebuild_shard_chunk
            # and degraded reads both land here), so repeated rebuilds
            # of one geometry recycle buffers instead of allocating
            from ...ops.device_pool import POOL

            dev = POOL.put(shards) if POOL.enabled() else shards
            try:
                data = np.asarray(self._jax_codec.decode(use, dev))
            finally:
                # a decode failure (bad shard set, kernel abort) must
                # not strand the pooled stripe buffer
                if dev is not shards:
                    POOL.release(dev)
        elif self.backend == "oracle":
            from ... import native_oracle

            data = native_oracle.decode(self.coding, self.k, use, shards)
        else:
            from ...gf.reference_codec import decode_chunks as np_decode

            out = np_decode(self.coding, self.k, dict(zip(use, shards)), want=list(range(self.k)))
            data = np.stack([out[i] for i in range(self.k)])
        result: dict[int, np.ndarray] = {}
        missing_par = [
            w for w in sorted(set(want_to_read))
            if w >= self.k and w not in chunks
        ]
        if missing_par:
            # one batched apply for every missing parity row (device-path
            # when backend is jax, host referee otherwise)
            rowmat = np.ascontiguousarray(
                self.coding[[w - self.k for w in missing_par]]
            )
            if self.backend == "jax":
                from ...ops.bitplane import apply_matrix_jax

                par = np.asarray(apply_matrix_jax(rowmat, data))
            else:
                from ...gf.reference_codec import apply_matrix

                par = apply_matrix(rowmat, data)
            for i, w in enumerate(missing_par):
                result[w] = par[i]
        for wanted in sorted(set(want_to_read)):
            if wanted in chunks:
                result[wanted] = np.asarray(chunks[wanted], dtype=np.uint8)
            elif wanted < self.k:
                result[wanted] = data[wanted]
        return result


class BitmatrixCodec(ErasureCode):
    """m=2 RAID-6 packet codec for the jerasure bitmatrix techniques
    (reference: ErasureCodeJerasureLiberation et al.: chunks split into w
    packets, parity = GF(2) bitmatrix over packets).  Default w per
    technique follows the reference's ErasureCodeJerasure defaults where
    they exist (liberation/blaum_roth stock w=7; liber8tion w=8)."""

    def __init__(self, profile: dict | None = None, backend: str = "jax"):
        self.backend = backend
        self._dm_cache: dict[tuple, np.ndarray] = {}
        super().__init__(profile)

    def init(self, profile: dict) -> None:
        from ...gf.gf2 import gf2_inv, raid6_bitmatrix

        self._dm_cache.clear()
        self.profile = dict(profile)
        self.k = self.parse_int(profile, "k", 2)
        self.m = self.parse_int(profile, "m", 2)
        self.technique = profile.get("technique", "liberation")
        if self.m != 2:
            raise InvalidProfile(
                f"technique={self.technique} is RAID-6 only (m=2), got "
                f"m={self.m}"
            )
        default_w = 8 if self.technique == "liber8tion" else 7
        self.w = self.parse_int(profile, "w", default_w)
        try:
            self.B = raid6_bitmatrix(self.technique, self.k, self.w)
        except ValueError as e:
            raise InvalidProfile(str(e))
        self._gf2_inv = gf2_inv
        if self.backend == "jax":
            # stable device-cache key, once per codec (cephdma)
            from ...ops.bitplane import matrix_digest

            self._B_digest = matrix_digest(self.B)

    def get_chunk_size(self, stripe_width: int) -> int:
        base = super().get_chunk_size(stripe_width)
        return -(-base // self.w) * self.w  # w packets per chunk

    def _apply(self, M: np.ndarray, rows: np.ndarray,
               mat_key: str | None = None) -> np.ndarray:
        if self.backend == "jax":
            # cephdma: the packet rows commit through the device stripe
            # pool and the XOR apply runs the donation-enabled variant
            # (apply_xor_matrix_dev) — the bitmatrix codecs encode
            # inline (not batcher-eligible), so this is their whole
            # pool/donation story; the np.asarray is their deliberate
            # codec-seam sync
            from ...ops.bitplane import (
                apply_xor_matrix_dev,
                apply_xor_matrix_jax,
            )
            from ...ops.device_pool import POOL, donation_supported

            if POOL.enabled():
                don = donation_supported()
                dev = POOL.put(rows)
                try:
                    out = np.asarray(apply_xor_matrix_dev(
                        M, dev, mat_key=mat_key, donate=don))
                finally:
                    if not don:
                        # donated buffers are consumed by the kernel;
                        # an undonated one is dead now (or the apply
                        # raised) and recycles either way
                        POOL.release(dev)
                return out
            return np.asarray(apply_xor_matrix_jax(M, rows,
                                                   mat_key=mat_key))
        out = np.zeros((M.shape[0], rows.shape[1]), dtype=np.uint8)
        for r in range(M.shape[0]):
            for j in np.nonzero(M[r])[0]:
                out[r] ^= rows[j]
        return out

    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray:
        data_chunks = np.ascontiguousarray(data_chunks, dtype=np.uint8)
        k, L = data_chunks.shape
        if L % self.w:
            raise ValueError(f"chunk length {L} not divisible by w={self.w}")
        rows = data_chunks.reshape(k * self.w, L // self.w)
        parity = self._apply(self.B, rows,
                             mat_key=getattr(self, "_B_digest", None))
        return parity.reshape(2, L)

    def decode_chunks(self, want_to_read, chunks: dict[int, np.ndarray]):
        avail = sorted(chunks)
        if len(avail) < self.k:
            raise InsufficientChunks(f"need {self.k}, have {len(avail)}")
        use = avail[: self.k]
        L = len(next(iter(chunks.values())))
        w, k = self.w, self.k
        inv = self._dm_cache.get(tuple(use))
        if inv is None:
            # generator rows: data chunk i = identity block i; parity j =
            # B row block j; per-pattern cache (the ShecTableCache /
            # BitplaneCodec._decode_cache role — at most C(k+2,2) entries)
            G = np.concatenate(
                [np.eye(k * w, dtype=np.uint8), self.B], axis=0
            )
            sel = np.concatenate(
                [G[c * w : (c + 1) * w] for c in use], axis=0
            )  # [kw, kw]
            inv = self._gf2_inv(sel)
            self._dm_cache[tuple(use)] = inv
        rows = np.concatenate([
            np.asarray(chunks[c], dtype=np.uint8).reshape(w, L // w)
            for c in use
        ])
        data_rows = self._apply(inv, rows)
        data = data_rows.reshape(k, L)
        result: dict[int, np.ndarray] = {}
        missing_par = [
            c for c in sorted(set(want_to_read))
            if c >= k and c not in chunks
        ]
        if missing_par:
            par = self._apply(
                np.concatenate(
                    [self.B[(c - k) * w : (c - k + 1) * w]
                     for c in missing_par]
                ),
                data_rows,
            )
            for i, c in enumerate(missing_par):
                result[c] = par[i * w : (i + 1) * w].reshape(L)
        for wanted in sorted(set(want_to_read)):
            if wanted in chunks:
                result[wanted] = np.asarray(chunks[wanted], dtype=np.uint8)
            elif wanted < k:
                result[wanted] = data[wanted]
        return result


class RSPlugin(ErasureCodePlugin):
    """Registry factory (reference: jerasure/ErasureCodePluginJerasure.cc ::
    ErasureCodePluginJerasure::factory switching on technique)."""

    def __init__(self, backend: str = "jax"):
        self.backend = backend

    def factory(self, profile: dict):
        if profile.get("technique") in BITMATRIX_TECHNIQUES:
            return BitmatrixCodec(profile, backend=self.backend)
        return RSCodec(profile, backend=self.backend)
