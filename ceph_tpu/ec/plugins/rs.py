"""Reed-Solomon plugin family — the jerasure/ISA-L analog, TPU-first.

Covers the matrix techniques of the reference's jerasure plugin (reference:
src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc} — one subclass per
technique, each with prepare() building the matrix) and the ISA-L plugin
(reference: src/erasure-code/isa/ErasureCodeIsa.{h,cc}):

    reed_sol_van    ErasureCodeJerasureReedSolomonVandermonde
    reed_sol_r6_op  ErasureCodeJerasureReedSolomonRAID6 (m=2: rows 1, 2^j)
    cauchy_orig     ErasureCodeJerasureCauchyOrig
    cauchy_good     ErasureCodeJerasureCauchyGood

The bitmatrix-only techniques (liberation, blaum_roth, liber8tion) are
byte-layout-dependent in jerasure and intentionally not reproduced; profiles
naming them get a clear InvalidProfile (vintage note in SURVEY.md §2.1).

Three interchangeable backends execute the same matrices:
    jax     bitplane GF(2) matmul on TPU (ceph_tpu.ops.bitplane)
    oracle  C++ SIMD split-table path (native/gf_oracle.cc — ISA-L analog)
    numpy   pure-python referee (ceph_tpu.gf.reference_codec)
Parity bytes are identical across backends (byte-wise GF semantics).
"""
from __future__ import annotations

import numpy as np

from ...gf.matrix import (
    cauchy_good_coding_matrix,
    cauchy_original_coding_matrix,
    vandermonde_coding_matrix,
)
from ...gf.tables import gf_pow
from ..interface import ErasureCode, InsufficientChunks, InvalidProfile
from ..registry import ErasureCodePlugin

TECHNIQUES = ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good")
_UNSUPPORTED = ("liberation", "blaum_roth", "liber8tion")


def build_coding_matrix(technique: str, k: int, m: int) -> np.ndarray:
    if technique == "reed_sol_van":
        return vandermonde_coding_matrix(k, m).astype(np.uint8)
    if technique == "reed_sol_r6_op":
        # reed_sol.c :: reed_sol_r6_coding_matrix — RAID-6: row0 all ones,
        # row1[j] = 2^j
        if m != 2:
            raise InvalidProfile("technique=reed_sol_r6_op requires m=2")
        mat = np.ones((2, k), dtype=np.uint8)
        mat[1] = [gf_pow(2, j) for j in range(k)]
        return mat
    if technique == "cauchy_orig":
        return cauchy_original_coding_matrix(k, m).astype(np.uint8)
    if technique == "cauchy_good":
        return cauchy_good_coding_matrix(k, m).astype(np.uint8)
    if technique in _UNSUPPORTED:
        raise InvalidProfile(
            f"technique {technique!r} is a jerasure bitmatrix/packet technique "
            "whose parity depends on packetsize byte layout; use reed_sol_van "
            "or cauchy_good (identical fault tolerance, layout-independent parity)"
        )
    raise InvalidProfile(f"unknown technique {technique!r}; known: {TECHNIQUES}")


class RSCodec(ErasureCode):
    """Systematic MDS Reed-Solomon codec over GF(2^8)."""

    def __init__(self, profile: dict | None = None, backend: str = "jax"):
        self.backend = backend
        self._jax_codec = None
        super().__init__(profile)

    def init(self, profile: dict) -> None:
        self.profile = dict(profile)
        self.k = self.parse_int(profile, "k", 2)
        self.m = self.parse_int(profile, "m", 1)
        self.technique = profile.get("technique", "reed_sol_van")
        w = self.parse_int(profile, "w", 8)
        if w != 8:
            raise InvalidProfile(
                f"w={w} unsupported: the TPU bitplane kernel is specialized "
                "for GF(2^8) (w=8), the default in the reference too"
            )
        if self.k < 1 or self.m < 1:
            raise InvalidProfile(f"k={self.k}, m={self.m} must be >= 1")
        self.coding = build_coding_matrix(self.technique, self.k, self.m)
        if self.backend == "jax":
            from ...ops.bitplane import BitplaneCodec

            self._jax_codec = BitplaneCodec(self.coding)

    # -- hot path (reference: ErasureCodeInterface.h :: encode_chunks) ----
    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray:
        data_chunks = np.ascontiguousarray(data_chunks, dtype=np.uint8)
        if self.backend == "jax":
            return np.asarray(self._jax_codec.encode(data_chunks))
        if self.backend == "oracle":
            from ... import native_oracle

            return native_oracle.encode(self.coding, data_chunks, fast=True)
        from ...gf.reference_codec import encode_chunks as np_encode

        return np_encode(self.coding, data_chunks)

    def decode_chunks(self, want_to_read, chunks: dict[int, np.ndarray]):
        avail = sorted(chunks)
        if len(avail) < self.k:
            raise InsufficientChunks(f"need {self.k}, have {len(avail)}")
        use = avail[: self.k]
        shards = np.stack([np.asarray(chunks[r], dtype=np.uint8) for r in use])
        if self.backend == "jax":
            data = np.asarray(self._jax_codec.decode(use, shards))
        elif self.backend == "oracle":
            from ... import native_oracle

            data = native_oracle.decode(self.coding, self.k, use, shards)
        else:
            from ...gf.reference_codec import decode_chunks as np_decode

            out = np_decode(self.coding, self.k, dict(zip(use, shards)), want=list(range(self.k)))
            data = np.stack([out[i] for i in range(self.k)])
        result: dict[int, np.ndarray] = {}
        missing_par = [
            w for w in sorted(set(want_to_read))
            if w >= self.k and w not in chunks
        ]
        if missing_par:
            # one batched apply for every missing parity row (device-path
            # when backend is jax, host referee otherwise)
            rowmat = np.ascontiguousarray(
                self.coding[[w - self.k for w in missing_par]]
            )
            if self.backend == "jax":
                from ...ops.bitplane import apply_matrix_jax

                par = np.asarray(apply_matrix_jax(rowmat, data))
            else:
                from ...gf.reference_codec import apply_matrix

                par = apply_matrix(rowmat, data)
            for i, w in enumerate(missing_par):
                result[w] = par[i]
        for wanted in sorted(set(want_to_read)):
            if wanted in chunks:
                result[wanted] = np.asarray(chunks[wanted], dtype=np.uint8)
            elif wanted < self.k:
                result[wanted] = data[wanted]
        return result


class RSPlugin(ErasureCodePlugin):
    """Registry factory (reference: jerasure/ErasureCodePluginJerasure.cc ::
    ErasureCodePluginJerasure::factory switching on technique)."""

    def __init__(self, backend: str = "jax"):
        self.backend = backend

    def factory(self, profile: dict) -> RSCodec:
        return RSCodec(profile, backend=self.backend)
