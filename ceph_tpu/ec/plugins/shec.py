"""SHEC — Shingled Erasure Code plugin (k, m, c).

Reference: src/erasure-code/shec/ErasureCodeShec.{h,cc} + ShecTableCache —
local parity groups arranged as overlapping "shingles" over the data chunks,
so a single-chunk failure is repaired by reading ~k*c/m chunks instead of k
(SURVEY.md §2.1).  m parities each cover a cyclic window of
ceil(k*c/m) data chunks starting at floor(i*k/m); coefficients inside a
window come from the Cauchy construction (1/(i ^ (m+j))) so overlapping
groups stay independent.

Provenance caveat (SURVEY.md §0): the reference mount was empty, so this
implements the construction from the published SHEC design (Miyamae et al.,
and the reference's documented profile semantics); parity bytes are NOT
claimed byte-identical to the reference plugin's — the *recovery semantics*
(minimum_to_decode search over shingles, c-erasure durability, recovery
efficiency) are what tests pin down.

The decode path solves the windowed linear system over GF(2^8) directly
(gf_solve) and caches the recovery plan per erasure pattern, the role of
ErasureCodeShecTableCache.
"""
from __future__ import annotations

import itertools

import numpy as np

from ...gf.matrix import gf_rank, gf_solve
from ...gf.tables import gf_inv
from ..interface import ErasureCode, InsufficientChunks, InvalidProfile
from ..registry import ErasureCodePlugin


def shec_coding_matrix(k: int, m: int, c: int) -> np.ndarray:
    """m x k matrix with cyclic shingled windows of width ceil(k*c/m)."""
    width = -(-k * c // m)  # ceil(k*c/m)
    mat = np.zeros((m, k), dtype=np.int64)
    for i in range(m):
        start = (i * k) // m
        for off in range(width):
            j = (start + off) % k
            mat[i, j] = gf_inv(i ^ (m + j))
    return mat


class ShecCodec(ErasureCode):
    def __init__(self, profile: dict | None = None):
        self._plan_cache: dict[tuple, tuple] = {}
        self._dm_cache: dict[tuple, np.ndarray] = {}
        super().__init__(profile)

    def init(self, profile: dict) -> None:
        self._plan_cache.clear()  # re-init invalidates cached geometry
        self._dm_cache.clear()
        self.profile = dict(profile)
        self.k = self.parse_int(profile, "k", 4)
        self.m = self.parse_int(profile, "m", 3)
        self.c = self.parse_int(profile, "c", 2)
        if not (1 <= self.c <= self.m <= self.k):
            raise InvalidProfile(
                f"SHEC requires 1 <= c <= m <= k, got k={self.k} m={self.m} c={self.c}"
            )
        if self.k + self.m > 255:
            raise InvalidProfile("k+m must be <= 255")
        self.coding = shec_coding_matrix(self.k, self.m, self.c)
        self.window = -(-self.k * self.c // self.m)

    # -- encode -----------------------------------------------------------
    def supports_parity_delta(self) -> bool:
        return True  # byte-matrix apply, column-local, identity layout

    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray:
        from ...ops.bitplane import apply_matrix_jax

        return np.asarray(
            apply_matrix_jax(self.coding.astype(np.uint8), data_chunks)
        )

    # -- recovery plan search (ErasureCodeShec::minimum_to_decode role) ---
    def _window(self, p: int) -> set[int]:
        return {int(j) for j in np.nonzero(self.coding[p])[0]}

    def _requirements(
        self, want: frozenset[int], available: frozenset[int]
    ) -> tuple[list[int], set[int]]:
        """(data chunks that must be solved for, available window data that
        wanted-parity re-encode additionally reads)."""
        avail_data = {a for a in available if a < self.k}
        want_data_missing = {w for w in want if w < self.k} - available
        want_parity_missing = {
            w - self.k for w in want if w >= self.k and w not in available
        }
        parity_window: set[int] = set()
        for p in want_parity_missing:
            parity_window |= self._window(p)
        solve_targets = sorted(want_data_missing | (parity_window - avail_data))
        return solve_targets, parity_window & avail_data

    def _recovery_plan(
        self, want: frozenset[int], available: frozenset[int]
    ) -> tuple[tuple[int, ...], tuple[int, ...]]:
        """Minimal read set: smallest parity subset whose windows cover the
        solve targets with available data and whose coefficient submatrix has
        full rank, plus the window data wanted parities re-encode from."""
        key = (want, available)
        if key in self._plan_cache:
            return self._plan_cache[key]
        solve_targets, parity_read = self._requirements(want, available)
        avail_parities = sorted(a - self.k for a in available if a >= self.k)
        avail_data = {a for a in available if a < self.k}
        if not solve_targets:
            plan = (tuple(sorted(parity_read)), ())
            self._plan_cache[key] = plan
            return plan
        targets = set(solve_targets)
        for n_par in range(len(solve_targets), len(avail_parities) + 1):
            best: tuple | None = None
            for parities in itertools.combinations(avail_parities, n_par):
                cols: set[int] = set()
                for p in parities:
                    cols |= self._window(p)
                if (cols - targets) - avail_data:
                    continue  # a window needs data that is neither available
                    # nor being solved for
                A = np.stack([self.coding[p, solve_targets] for p in parities])
                if gf_rank(A) < len(solve_targets):
                    continue
                read_data = ((cols - targets) & avail_data) | parity_read
                cost = len(read_data) + n_par
                if best is None or cost < best[0]:
                    best = (cost, tuple(sorted(read_data)), tuple(parities))
            if best is not None:
                plan = (best[1], best[2])
                self._plan_cache[key] = plan
                return plan
        raise InsufficientChunks(
            f"SHEC cannot recover {sorted(want)} from {sorted(available)}"
        )

    def minimum_to_decode(self, want_to_read, available):
        want = frozenset(want_to_read)
        avail = frozenset(available)
        if want <= avail:
            return {c: [(0, -1)] for c in sorted(want)}
        read_data, parities = self._recovery_plan(want, avail)
        chunks = set(read_data) | {self.k + p for p in parities}
        chunks |= want & avail
        return {c: [(0, -1)] for c in sorted(chunks)}

    def _decode_matrix(
        self, want: frozenset[int], avail_t: tuple[int, ...]
    ) -> np.ndarray:
        """[n_want, n_avail] GF(2^8) matrix M with wanted = M @ available.

        The whole SHEC recovery — windowed solve plus parity re-encode —
        is GF-linear in the available chunks, so it collapses to ONE
        cached matrix applied on-device (the ShecTableCache role,
        reference: shec/ErasureCodeShecTableCache.cc, upgraded from
        decode-matrix caching to whole-plan caching)."""
        key = (want, avail_t)
        cached = self._dm_cache.get(key)
        if cached is not None:
            return cached
        from ...gf.tables import GF_MUL_TABLE

        avail = frozenset(avail_t)
        solve_targets, _ = self._requirements(want, avail)
        _, parities = self._recovery_plan(want, avail)
        n_in = len(avail_t)
        pos = {c: i for i, c in enumerate(avail_t)}
        rowX: dict[int, np.ndarray] = {}
        if solve_targets:
            # express each windowed-parity equation's RHS as a coefficient
            # row over the available chunks, then solve for the targets
            A = np.stack([self.coding[p, solve_targets] for p in parities])
            Bcoef = np.zeros((len(parities), n_in), dtype=np.int64)
            for r, p in enumerate(parities):
                Bcoef[r, pos[self.k + p]] ^= 1
                for j in self._window(p):
                    if j in solve_targets:
                        continue
                    Bcoef[r, pos[j]] ^= int(self.coding[p, j])
            X = gf_solve(A, Bcoef)  # [n_targets, n_in]
            for idx, j in enumerate(solve_targets):
                rowX[j] = X[idx].astype(np.int64)

        def data_row(j: int) -> np.ndarray:
            if j in rowX:
                return rowX[j]
            e = np.zeros(n_in, dtype=np.int64)
            e[pos[j]] = 1
            return e

        rows = []
        for w in sorted(want):
            if w in pos:
                e = np.zeros(n_in, dtype=np.int64)
                e[pos[w]] = 1
                rows.append(e)
            elif w < self.k:
                rows.append(data_row(w))
            else:
                p = w - self.k
                r = np.zeros(n_in, dtype=np.int64)
                for j in self._window(p):
                    c = int(self.coding[p, j])
                    r ^= GF_MUL_TABLE[c, data_row(j)]
                rows.append(r)
        M = np.stack(rows).astype(np.uint8)
        self._dm_cache[key] = M
        return M

    def decode_chunks(self, want_to_read, chunks):
        from ...ops.bitplane import apply_matrix_jax

        want = frozenset(want_to_read)
        avail_t = tuple(sorted(chunks))
        M = self._decode_matrix(want, avail_t)
        stacked = np.stack(
            [np.asarray(chunks[c], dtype=np.uint8) for c in avail_t]
        )
        out = np.asarray(apply_matrix_jax(M, stacked))
        return {w: out[i] for i, w in enumerate(sorted(want))}


class ShecPlugin(ErasureCodePlugin):
    """reference: shec/ErasureCodePluginShec.cc."""

    def factory(self, profile: dict) -> ShecCodec:
        return ShecCodec(profile)
