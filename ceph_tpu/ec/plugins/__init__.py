"""Codec plugins: rs (jerasure/isa analog), shec, lrc, clay."""
