"""CLAY — coupled-layer MSR regenerating code plugin (k, m, d).

Reference: src/erasure-code/clay/ErasureCodeClay.{h,cc} — repair of a single
lost chunk reads only sub-chunks from d helper chunks (bandwidth-optimal MSR
point), introducing get_sub_chunk_count() and sub-chunk-range
minimum_to_decode into the codec interface (SURVEY.md §2.1).

Construction (Clay codes, FAST'18, as the reference implements):
- q = d - k + 1, t = (k+m)/q; node (x, y) for x in [0,q), y in [0,t);
  chunk index n = y*q + x; data nodes are n < k.
- Each chunk holds q^t sub-chunks, one per "plane" z, whose base-q digits
  are (z_0..z_{t-1}) (y=0 least significant here).
- Uncoupled symbols U(x,y;z) form, per plane, a codeword of the scalar MDS
  code [I_k; C] (the same jerasure-exact RS generator as the rs plugin).
- Coupling: for x != z_y, the pair P1=(x,y;z), P2=(z_y,y;z') with
  z' = z(y -> x) satisfies C1 = U1 ^ g*U2 and C2 = g*U1 ^ U2 (g = 2;
  det 1^g^2 = (1+g)^2 != 0); for x == z_y ("vertex"), C = U.
- Encode and multi-erasure decode run the layered algorithm: planes in
  increasing intersection-score order, U recovered via pair inversion or
  earlier planes, per-plane MDS decode of erased U, then C of erased nodes
  from U pairs.
- Single-chunk repair with d = k+m-1 (the reference's default d) reads only
  the q^(t-1) planes with z_{y0} = x0 from every survivor — bandwidth
  d/(k*q) of naive (BASELINE.json config 4 measures exactly this).

Scope notes vs the reference: d must satisfy q | (k+m) (the reference pads
with shortened virtual nodes otherwise); bandwidth-optimal repair is
implemented for d = k+m-1 with all survivors as helpers, and falls back to
full decode for other cases.  Parity bytes are internally defined (empty
reference mount, SURVEY.md §0); sub-chunk accounting and repair-bandwidth
semantics are what tests pin.
"""
from __future__ import annotations

import numpy as np

from ...gf.matrix import decode_matrix_for, systematic_generator, vandermonde_coding_matrix

from ...gf.tables import GF_MUL_TABLE, gf_inv
from ..interface import ErasureCode, InsufficientChunks, InvalidProfile
from ..registry import ErasureCodePlugin

GAMMA = 2
_INV_DET = gf_inv(1 ^ GF_MUL_TABLE[GAMMA, GAMMA])  # 1/(1 + g^2)
_INV_G = gf_inv(GAMMA)


def _gmul(c: int, arr: np.ndarray) -> np.ndarray:
    return GF_MUL_TABLE[c, arr]


class ClayCodec(ErasureCode):
    def __init__(self, profile: dict | None = None):
        #: (lost, helpers) -> (repair matrix, stable digest)
        self._repair_mat_cache: dict[tuple, tuple[np.ndarray, str]] = {}
        super().__init__(profile)

    def init(self, profile: dict) -> None:
        self._repair_mat_cache.clear()  # re-init invalidates geometry
        self.profile = dict(profile)
        self.k = self.parse_int(profile, "k", 4)
        self.m = self.parse_int(profile, "m", 2)
        self.d = self.parse_int(profile, "d", self.k + self.m - 1)
        if not (self.k <= self.d <= self.k + self.m - 1):
            raise InvalidProfile(
                f"CLAY requires k <= d <= k+m-1, got k={self.k} m={self.m} d={self.d}"
            )
        self.q = self.d - self.k + 1
        n = self.k + self.m
        if n % self.q:
            raise InvalidProfile(
                f"(k+m)={n} must be divisible by q=d-k+1={self.q} "
                "(the reference pads with shortened nodes; unsupported here)"
            )
        self.t = n // self.q
        self.sub_chunk_count = self.q**self.t
        coding = vandermonde_coding_matrix(self.k, self.m)
        self.generator = systematic_generator(coding)
        self.coding = coding.astype(np.uint8)

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_count

    def get_chunk_size(self, stripe_width: int) -> int:
        base = super().get_chunk_size(stripe_width)
        # chunk must split into q^t sub-chunks of CHUNK_ALIGN-friendly size
        unit = self.sub_chunk_count
        return -(-base // unit) * unit

    # -- geometry ---------------------------------------------------------
    def _node(self, n: int) -> tuple[int, int]:
        return n % self.q, n // self.q

    def _digit(self, z: int, y: int) -> int:
        return (z // self.q**y) % self.q

    def _replace(self, z: int, y: int, x: int) -> int:
        p = self.q**y
        return z - self._digit(z, y) * p + x * p

    # -- layered decode (ErasureCodeClay::decode_layered) -----------------
    def _layered_decode(
        self, C: dict[int, np.ndarray], erased: list[int], sub_len: int
    ) -> dict[int, np.ndarray]:
        """C: node -> [Z, sub_len] known coupled chunks; returns C for erased.

        TPU-native restructuring of ErasureCodeClay::decode_layered: planes
        are grouped by intersection score (all cross-plane dependencies
        point at strictly lower scores), couplings are vectorized numpy
        over each group, and the per-plane MDS decodes collapse into ONE
        on-device bitplane matmul per score group instead of Z host
        apply_matrix calls."""
        nq, t, Z = self.q, self.t, self.sub_chunk_count
        n_nodes = self.k + self.m
        erased_set = set(erased)
        if len(erased_set) > self.m:
            raise InsufficientChunks(f"{len(erased_set)} erasures > m={self.m}")
        from ...ops.bitplane import apply_matrix_jax

        U = np.zeros((n_nodes, Z, sub_len), dtype=np.uint8)
        Cd = np.zeros((n_nodes, Z, sub_len), dtype=np.uint8)
        for node, v in C.items():
            Cd[node] = v
        zs_all = np.arange(Z)
        digits = np.stack(
            [(zs_all // nq**y) % nq for y in range(t)]
        )  # [t, Z]
        scores = np.zeros(Z, dtype=np.int64)
        for y in range(t):
            scores += np.isin(y * nq + digits[y], list(erased_set))
        avail_nodes = sorted(set(range(n_nodes)) - erased_set)
        dm = decode_matrix_for(self.generator, self.k, avail_nodes).astype(np.uint8)
        parity_erased = bool(erased_set & set(range(self.k, n_nodes)))
        for s in range(int(scores.max()) + 1):
            zs = zs_all[scores == s]
            if zs.size == 0:
                continue
            # uncoupled U for available nodes, vectorized over the group
            for node in avail_nodes:
                x, y = self._node(node)
                digs = digits[y, zs]                      # [nZ]
                pnode = y * nq + digs
                zp = zs + (x - digs) * nq**y
                vertex = (digs == x)[:, None]
                partner_ok = (~np.isin(pnode, list(erased_set)))[:, None]
                c1 = Cd[node, zs]
                c2 = Cd[pnode, zp]
                u_pair = _gmul(_INV_DET, c1 ^ _gmul(GAMMA, c2))
                u_part = c1 ^ _gmul(GAMMA, U[pnode, zp])  # zp has score s-1
                U[node, zs] = np.where(
                    vertex, c1, np.where(partner_ok, u_pair, u_part)
                )
            # one batched MDS decode for every plane in the group
            sub = U[avail_nodes[: self.k]][:, zs].reshape(self.k, -1)
            data_u = np.asarray(apply_matrix_jax(dm, sub))
            full = np.zeros((n_nodes, zs.size * sub_len), dtype=np.uint8)
            full[: self.k] = data_u
            if parity_erased:
                full[self.k :] = np.asarray(
                    apply_matrix_jax(self.coding, data_u)
                )
            for node in erased_set:
                U[node, zs] = full[node].reshape(zs.size, sub_len)
        # rebuild coupled C for erased nodes from the complete U
        out: dict[int, np.ndarray] = {}
        for node in erased:
            x, y = self._node(node)
            digs = digits[y]
            pnode = y * nq + digs
            zp = zs_all + (x - digs) * nq**y
            vertex = (digs == x)[:, None]
            out[node] = np.where(
                vertex, U[node], U[node] ^ _gmul(GAMMA, U[pnode, zp])
            )
        return out

    # -- interface --------------------------------------------------------
    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray:
        data_chunks = np.ascontiguousarray(data_chunks, dtype=np.uint8)
        k, L = data_chunks.shape
        assert k == self.k
        Z = self.sub_chunk_count
        if L % Z:
            raise ValueError(f"chunk length {L} not divisible by {Z} sub-chunks")
        sub_len = L // Z
        C = {i: data_chunks[i].reshape(Z, sub_len) for i in range(self.k)}
        parity = self._layered_decode(
            C, list(range(self.k, self.k + self.m)), sub_len
        )
        return np.stack(
            [parity[self.k + i].reshape(L) for i in range(self.m)]
        )

    def decode_chunks(self, want_to_read, chunks):
        have = {int(i): np.asarray(v, dtype=np.uint8) for i, v in chunks.items()}
        L = len(next(iter(have.values())))
        Z = self.sub_chunk_count
        sub_len = L // Z
        erased = sorted(set(range(self.k + self.m)) - set(have))
        lost_wanted = sorted(set(want_to_read) - set(have))
        if not lost_wanted:
            return {w: have[w] for w in want_to_read}
        if len(erased) == 1 and self.d == self.k + self.m - 1 and len(have) >= self.d:
            rebuilt = self._repair_one(have, erased[0], sub_len)
            out = {erased[0]: rebuilt}
        else:
            C = {i: v.reshape(Z, sub_len) for i, v in have.items()}
            dec = self._layered_decode(C, erased, sub_len)
            out = {n: v.reshape(Z * sub_len) for n, v in dec.items()}
        result = {}
        for w in set(want_to_read):
            result[w] = have[w] if w in have else out[w]
        return result

    # -- bandwidth-optimal single repair (d = k+m-1) ----------------------
    def repair_planes(self, lost: int) -> list[int]:
        """Planes read during repair of `lost`: z with z_{y0} == x0."""
        x0, y0 = self._node(lost)
        return [
            z for z in range(self.sub_chunk_count) if self._digit(z, y0) == x0
        ]

    def repair_subchunk_ranges(self, lost: int) -> list[tuple[int, int]]:
        """Contiguous (offset, count) runs of sub-chunk indices helpers read
        (the shape minimum_to_decode reports, reference:
        ErasureCodeClay::minimum_to_decode sub-chunk ranges)."""
        planes = self.repair_planes(lost)
        runs: list[tuple[int, int]] = []
        for z in planes:
            if runs and runs[-1][0] + runs[-1][1] == z:
                runs[-1] = (runs[-1][0], runs[-1][1] + 1)
            else:
                runs.append((z, 1))
        return runs

    def minimum_to_decode(self, want_to_read, available):
        want = set(want_to_read)
        avail = set(available)
        if want <= avail:
            return {c: [(0, -1)] for c in sorted(want)}
        missing = want - avail
        if (
            len(missing) == 1
            and self.d == self.k + self.m - 1
            and len(avail) >= self.d
        ):
            ranges = self.repair_subchunk_ranges(next(iter(missing)))
            plan = {c: list(ranges) for c in sorted(avail)[: self.d]}
            # wanted-and-available chunks are read in full, not just the
            # repair planes a helper contributes (reference: Clay's
            # minimum_to_decode merges want_to_read into the helper set)
            for c in want & avail:
                plan[c] = [(0, -1)]
            return plan
        if len(avail) < self.k:
            raise InsufficientChunks(f"need {self.k} chunks, have {len(avail)}")
        return {c: [(0, -1)] for c in sorted(avail)[: self.k]}

    def repair_matrix(self, lost: int, helpers: tuple[int, ...]) -> np.ndarray:
        """[Z, len(helpers)*nB] GF(2^8) matrix M with
        ``lost_subchunks = M @ fetched``, where `fetched` stacks each
        helper's repair-plane sub-chunk rows in helper order.

        The ENTIRE single-shard repair — pair uncoupling, per-plane MDS
        decode, parity re-encode, recoupling — is GF-linear in the fetched
        bytes, so it collapses into one cached matrix and repair becomes a
        single on-device bitplane/Pallas apply.  (TPU-first restructure of
        ErasureCodeClay::repair's layered host loop; the algebra below IS
        the layered algorithm, run symbolically on coefficient rows
        instead of chunk bytes.)"""
        return self.repair_matrix_entry(lost, helpers)[0]

    def repair_matrix_entry(self, lost: int,
                            helpers: tuple[int, ...]) -> tuple:
        """(repair matrix, its stable digest) — the digest is computed
        once at cache fill and keys the device bitmatrix cache, so the
        recovery path's repeated repair applies stop paying a fresh
        ``M.tobytes()`` host copy per rebuilt chunk (cephdma)."""
        key = (lost, helpers)
        cached = self._repair_mat_cache.get(key)
        if cached is not None:
            return cached
        M = self._build_repair_matrix(lost, helpers)
        from ...ops.bitplane import matrix_digest

        ent = (M, matrix_digest(M))
        self._repair_mat_cache[key] = ent
        return ent

    def _build_repair_matrix(self, lost: int,
                             helpers: tuple[int, ...]) -> np.ndarray:
        from ...gf.reference_codec import apply_matrix as gf_apply

        nq, Z = self.q, self.sub_chunk_count
        n_nodes = self.k + self.m
        x0, y0 = self._node(lost)
        planes = np.asarray(self.repair_planes(lost))
        nB = planes.size
        plane_pos = np.full(Z, -1, dtype=np.int64)
        plane_pos[planes] = np.arange(nB)
        n_in = len(helpers) * nB
        # coefficient rows: Cb[node, b] = unit vector of input position
        # (helper node, repair plane b)
        Cb = np.zeros((n_nodes, nB, n_in), dtype=np.uint8)
        for hi, node in enumerate(helpers):
            Cb[node, np.arange(nB), hi * nB + np.arange(nB)] = 1
        U = np.zeros((n_nodes, nB, n_in), dtype=np.uint8)
        known_u_nodes = []
        for node in helpers:
            x, y = self._node(node)
            if y == y0:
                continue  # column y0 survivors: U unknown in B planes
            known_u_nodes.append(node)
            digs = (planes // nq**y) % nq                  # [nB]
            pnode = y * nq + digs
            zp = planes + (x - digs) * nq**y               # stays in B
            vertex = (digs == x)[:, None]
            c1 = Cb[node]
            c2 = Cb[pnode, plane_pos[zp]]
            U[node] = np.where(
                vertex, c1, _gmul(_INV_DET, c1 ^ _gmul(GAMMA, c2))
            )
        # batched MDS decode: unknown U's are exactly column y0 (q nodes);
        # survivors outside column y0 must supply at least k known U's
        unknown = [y0 * nq + x for x in range(nq)]
        if len(known_u_nodes) < self.k:
            raise InsufficientChunks(
                f"repair needs {self.k} helpers outside column {y0}, "
                f"have {len(known_u_nodes)}"
            )
        dm = decode_matrix_for(
            self.generator, self.k, known_u_nodes
        ).astype(np.uint8)
        sub = U[known_u_nodes[: self.k]].reshape(self.k, -1)
        data_u = gf_apply(dm, sub)
        full = np.zeros((n_nodes, nB * n_in), dtype=np.uint8)
        full[: self.k] = data_u
        full[self.k :] = gf_apply(self.coding, data_u)
        for node in unknown:
            U[node] = full[node].reshape(nB, n_in)
        # rebuild lost chunk: B-planes are vertex (C = U); others via pairs
        zs_all = np.arange(Z)
        dy0 = (zs_all // nq**y0) % nq
        pnode = y0 * nq + dy0                              # [Z]
        zp = zs_all + (x0 - dy0) * nq**y0                  # in B
        zpi = plane_pos[zp]
        u2 = U[pnode, zpi]                                 # [Z, n_in]
        # C2 = g*U1 ^ U2 with P1=(lost;z), P2=(pnode;zp):
        u1 = _gmul(_INV_G, Cb[pnode, zpi] ^ u2)
        M = np.where(
            (dy0 == x0)[:, None], U[lost, zpi], u1 ^ _gmul(GAMMA, u2)
        )
        return M

    def gather_repair_input(
        self, have: dict[int, np.ndarray], lost: int, sub_len: int,
        helpers: tuple[int, ...],
    ) -> np.ndarray:
        """[len(helpers)*nB, sub_len] — each helper's repair-plane
        sub-chunks stacked in helper order (the layout repair_matrix
        contracts over)."""
        Z = self.sub_chunk_count
        planes = np.asarray(self.repair_planes(lost))
        return np.concatenate(
            [have[n].reshape(Z, sub_len)[planes] for n in helpers]
        )

    def _repair_one(
        self, have: dict[int, np.ndarray], lost: int, sub_len: int
    ) -> np.ndarray:
        """Rebuild `lost` reading only the repair planes from all
        survivors: one cached-matrix device apply."""
        from ...ops.bitplane import apply_matrix_jax

        helpers = tuple(sorted(have))
        M, m_key = self.repair_matrix_entry(lost, helpers)
        x = self.gather_repair_input(have, lost, sub_len, helpers)
        out = np.asarray(apply_matrix_jax(M, x, mat_key=m_key))
        return out.reshape(self.sub_chunk_count * sub_len)


class ClayPlugin(ErasureCodePlugin):
    """reference: clay/ErasureCodePluginClay.cc."""

    def factory(self, profile: dict) -> ClayCodec:
        return ClayCodec(profile)
