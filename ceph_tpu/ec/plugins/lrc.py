"""LRC — layered locally-repairable code plugin.

Reference: src/erasure-code/lrc/ErasureCodeLrc.{h,cc} — a composition codec:
the profile gives a `mapping` string positioning every chunk and a list of
`layers`, each layer being its own codec (instantiated through the registry,
"plugin composition", SURVEY.md §2.1) over the positions its own mini-mapping
selects.  Local layers repair single failures reading only their group;
the global layer provides cross-group protection.

Profile forms supported (as in the reference):
- mapping= + layers= (JSON list of [layer_mapping, layer_profile_json])
- k= m= l= sugar: k data + m global parities + one local parity per
  locality group of l chunks (the reference generates mapping/layers from
  k/m/l the same way; reference: ErasureCodeLrc::parse_kml).

Layer mapping characters: D = chunk in this layer (data or parity of an
outer view), c = coding chunk produced by this layer, _ = not in this layer.
"""
from __future__ import annotations

import json

import numpy as np

from ..interface import ErasureCode, InsufficientChunks, InvalidProfile
from ..registry import ErasureCodePlugin


class LrcCodec(ErasureCode):
    def init(self, profile: dict) -> None:
        self.profile = dict(profile)
        if "mapping" in profile and "layers" in profile:
            mapping = profile["mapping"]
            layers = profile["layers"]
            if isinstance(layers, str):
                layers = json.loads(layers)
        elif all(x in profile for x in ("k", "m", "l")):
            mapping, layers = self._generate_kml(
                self.parse_int(profile, "k", 4),
                self.parse_int(profile, "m", 2),
                self.parse_int(profile, "l", 3),
            )
        else:
            raise InvalidProfile(
                "lrc profile needs mapping=+layers= or k=+m=+l="
            )
        self.mapping = mapping
        self.n = len(mapping)
        self.k = sum(1 for ch in mapping if ch == "D")
        self.m = self.n - self.k
        self._build_layers(layers)

    def _generate_kml(self, k: int, m: int, l: int):
        """ErasureCodeLrc::parse_kml shape: data+global parities split into
        groups of l, one local parity appended per group."""
        if (k + m) % l:
            raise InvalidProfile(f"k+m={k + m} must be divisible by l={l}")
        groups = (k + m) // l
        mapping = ""
        pos = 0
        for _ in range(groups):
            mapping += "".join(
                "D" if pos + i < k else "_" for i in range(l)
            )
            pos += l
            mapping += "_"  # local parity slot
        # globals occupy the '_' data slots after k
        chars = list(mapping)
        # mark global parity slots: the first m non-D slots inside groups
        marked = 0
        for i, ch in enumerate(chars):
            if ch == "_" and (i + 1) % (l + 1) != 0 and marked < m:
                chars[i] = "D"  # globals act as data for local layers
                marked += 1
        mapping = "".join(chars)
        layers = []
        # global layer: RS over the k data producing m globals
        gmap = "".join(
            "D" if (i + 1) % (l + 1) != 0 and self._is_data_slot(i, k, l) else
            ("c" if (i + 1) % (l + 1) != 0 and chars[i] == "D" and not self._is_data_slot(i, k, l) else "_")
            for i in range(len(chars))
        )
        layers.append([gmap, {"plugin": "jax", "technique": "cauchy_good"}])
        # local layers: one XOR parity per group
        for g in range(groups):
            lmap = ["_"] * len(chars)
            base = g * (l + 1)
            for i in range(l):
                if chars[base + i] == "D":
                    lmap[base + i] = "D"
            lmap[base + l] = "c"
            layers.append(
                ["".join(lmap), {"plugin": "jax", "technique": "reed_sol_van"}]
            )
        # outer mapping: D for true data, _ for every parity
        outer = "".join(
            "D" if self._is_data_slot(i, k, l) and chars[i] == "D" else "_"
            for i in range(len(chars))
        )
        return outer, layers

    @staticmethod
    def _is_data_slot(i: int, k: int, l: int) -> bool:
        group, off = divmod(i, l + 1)
        if off == l:
            return False
        return group * l + off < k

    def _build_layers(self, layers) -> None:
        from ..registry import ErasureCodePluginRegistry

        reg = ErasureCodePluginRegistry.instance()
        self.layers = []
        for lmap, lprofile in layers:
            if isinstance(lprofile, str):
                lprofile = json.loads(lprofile) if lprofile.strip().startswith("{") else dict(
                    kv.split("=", 1) for kv in lprofile.split()
                )
            if len(lmap) != self.n:
                raise InvalidProfile(
                    f"layer mapping {lmap!r} length != chunk count {self.n}"
                )
            d_pos = [i for i, ch in enumerate(lmap) if ch == "D"]
            c_pos = [i for i, ch in enumerate(lmap) if ch == "c"]
            lp = dict(lprofile)
            lp["k"] = str(len(d_pos))
            lp["m"] = str(len(c_pos))
            codec = reg.factory(lp)
            self.layers.append((d_pos, c_pos, codec))
        if not self.layers:
            raise InvalidProfile("lrc needs at least one layer")

    def get_chunk_count(self) -> int:
        return self.n

    def get_data_chunk_count(self) -> int:
        return self.k

    # -- encode: apply layers in order (ErasureCodeLrc::encode_chunks) ----
    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray:
        data_chunks = np.ascontiguousarray(data_chunks, dtype=np.uint8)
        L = data_chunks.shape[1]
        buf = np.zeros((self.n, L), dtype=np.uint8)
        d_idx = [i for i, ch in enumerate(self.mapping) if ch == "D"]
        for src, dst in enumerate(d_idx):
            buf[dst] = data_chunks[src]
        for d_pos, c_pos, codec in self.layers:
            parity = codec.encode_chunks(buf[d_pos])
            for r, dst in enumerate(c_pos):
                buf[dst] = parity[r]
        non_data = [i for i in range(self.n) if i not in d_idx]
        return buf[non_data]

    def chunk_index_map(self) -> tuple[list[int], list[int]]:
        d_idx = [i for i, ch in enumerate(self.mapping) if ch == "D"]
        return d_idx, [i for i in range(self.n) if i not in d_idx]

    def _pos_of_shard(self, shard: int) -> int:
        d_idx, p_idx = self.chunk_index_map()
        return d_idx[shard] if shard < self.k else p_idx[shard - self.k]

    def _shard_of_pos(self, pos: int) -> int:
        d_idx, p_idx = self.chunk_index_map()
        if pos in d_idx:
            return d_idx.index(pos)
        return self.k + p_idx.index(pos)

    def minimum_to_decode(self, want_to_read, available):
        """Prefer the smallest layer that can repair (local repair first) —
        the LRC point (reference: ErasureCodeLrc::minimum_to_decode walks
        layers).  A layer repairs a member from any k_layer of its other
        members (MDS within the layer), and repaired positions chain into
        later repairs without being read."""
        want = set(want_to_read)
        avail = set(available)
        if want <= avail:
            return {c: [(0, -1)] for c in sorted(want)}
        missing_pos = {self._pos_of_shard(s) for s in want - avail}
        avail_pos = {self._pos_of_shard(s) for s in avail}
        layers_by_size = sorted(self.layers, key=lambda t: len(t[0]) + len(t[1]))
        read_pos: set[int] = set()
        repaired: set[int] = set()
        unresolved = set(missing_pos)
        while unresolved:
            progress = False
            for mp in sorted(unresolved):
                for d_pos, c_pos, _codec in layers_by_size:
                    members = set(d_pos) | set(c_pos)
                    if mp not in members:
                        continue
                    usable = (members - {mp}) & (avail_pos | repaired)
                    if len(usable) < len(d_pos):
                        continue
                    take = sorted(usable)[: len(d_pos)]
                    read_pos |= set(take) & avail_pos
                    repaired.add(mp)
                    unresolved.remove(mp)
                    progress = True
                    break
                if progress:
                    break
            if not progress:
                raise InsufficientChunks(
                    f"lrc cannot repair positions {sorted(unresolved)} "
                    f"from {sorted(avail_pos)}"
                )
        chunks = {self._shard_of_pos(p) for p in read_pos} | (want & avail)
        return {c: [(0, -1)] for c in sorted(chunks)}

    def decode_chunks(self, want_to_read, chunks):
        """Iterative layered repair: run layers until wanted chunks appear."""
        buf: dict[int, np.ndarray] = {
            self._pos_of_shard(s): np.asarray(v, dtype=np.uint8)
            for s, v in chunks.items()
        }
        want_pos = {self._pos_of_shard(s) for s in set(want_to_read)}
        for _ in range(len(self.layers) + 1):
            if want_pos <= set(buf):
                break
            progress = False
            for d_pos, c_pos, codec in self.layers:
                members = d_pos + c_pos
                missing = [p for p in members if p not in buf]
                if not missing:
                    continue
                have = {i: buf[p] for i, p in enumerate(members) if p in buf}
                if len(have) < len(d_pos):
                    continue
                try:
                    out = codec.decode_chunks(set(range(len(members))), have)
                except (InsufficientChunks, np.linalg.LinAlgError):
                    continue  # this layer can't help yet; a later pass may
                for i, p in enumerate(members):
                    if p not in buf and i in out:
                        buf[p] = np.asarray(out[i], dtype=np.uint8)
                        progress = True
            if not progress:
                break
        missing = want_pos - set(buf)
        if missing:
            raise InsufficientChunks(f"lrc could not rebuild positions {sorted(missing)}")
        return {s: buf[self._pos_of_shard(s)] for s in set(want_to_read)}


class LrcPlugin(ErasureCodePlugin):
    """reference: lrc/ErasureCodePluginLrc.cc."""

    def factory(self, profile: dict) -> LrcCodec:
        return LrcCodec(profile)
