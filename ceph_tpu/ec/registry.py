"""Plugin registry — profile-driven codec selection.

Re-design of the reference's dlopen plugin registry (reference:
src/erasure-code/ErasureCodePlugin.{h,cc} :: ErasureCodePluginRegistry —
factory(plugin_name, profile, &ec_impl) selecting libec_<plugin>.so via the
exported __erasure_code_init).  Python entry points replace dlopen: a plugin
is a factory object registered under its profile name; `plugin=jax` in an EC
profile selects the TPU codec exactly the way `plugin=isa` selects ISA-L in
the reference.  The same idiom backs the reference's compressor registry
(src/compressor/CompressionPlugin.h), confirming the seam (SURVEY.md §2.1).

Profiles are per-pool key=value maps, NOT daemon config (reference:
SURVEY.md §5.6) — e.g. {"plugin": "jax", "technique": "cauchy_good",
"k": "8", "m": "4"}.  `factory()` validates by instantiating, which is
precisely how OSDMonitor validates `osd erasure-code-profile set`
(reference: src/mon/OSDMonitor.cc).
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from threading import Lock

from .interface import ErasureCodeInterface, InvalidProfile


class ErasureCodePlugin(ABC):
    """Factory for codec instances (reference: ErasureCodePlugin.h ::
    ErasureCodePlugin::factory)."""

    @abstractmethod
    def factory(self, profile: dict) -> ErasureCodeInterface: ...


class ErasureCodePluginRegistry:
    """Singleton name -> plugin map (reference: ErasureCodePlugin.cc ::
    ErasureCodePluginRegistry::instance / add / factory)."""

    _instance: "ErasureCodePluginRegistry | None" = None
    _instance_lock = Lock()

    def __init__(self):
        self._plugins: dict[str, ErasureCodePlugin] = {}
        self._lock = Lock()

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
                _register_defaults(cls._instance)
            return cls._instance

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        with self._lock:
            if name in self._plugins:
                raise KeyError(f"erasure code plugin {name!r} already registered")
            self._plugins[name] = plugin

    def remove(self, name: str) -> None:
        with self._lock:
            self._plugins.pop(name, None)

    def get(self, name: str) -> ErasureCodePlugin | None:
        return self._plugins.get(name)

    def names(self) -> list[str]:
        return sorted(self._plugins)

    def factory(self, profile: dict) -> ErasureCodeInterface:
        """Instantiate the codec a profile names (reference:
        ErasureCodePluginRegistry::factory).  Raises InvalidProfile for an
        unknown plugin or a profile the plugin rejects."""
        name = profile.get("plugin", "jax")
        plugin = self._plugins.get(name)
        if plugin is None:
            raise InvalidProfile(
                f"unknown erasure code plugin {name!r}; known: {self.names()}"
            )
        return plugin.factory(dict(profile))


def _register_defaults(reg: ErasureCodePluginRegistry) -> None:
    # Imported lazily to avoid import cycles; each module registers the
    # analog of one reference plugin family (SURVEY.md §2.1 inventory).
    from .plugins.rs import RSPlugin

    reg.add("jax", RSPlugin(backend="jax"))          # TPU fast path
    reg.add("oracle", RSPlugin(backend="oracle"))    # C++ CPU baseline (ISA-L analog)
    reg.add("numpy", RSPlugin(backend="numpy"))      # pure-python referee
    # jerasure/isa spellings accepted for drop-in profile compatibility:
    # both map to codecs with identical byte-wise parity (see
    # native/gf_oracle.cc header note on parity semantics).
    reg.add("jerasure", RSPlugin(backend="oracle"))
    reg.add("isa", RSPlugin(backend="oracle"))
    try:
        from .plugins.shec import ShecPlugin

        reg.add("shec", ShecPlugin())
    except ImportError:  # pragma: no cover
        pass
    try:
        from .plugins.lrc import LrcPlugin

        reg.add("lrc", LrcPlugin())
    except ImportError:  # pragma: no cover
        pass
    try:
        from .plugins.clay import ClayPlugin

        reg.add("clay", ClayPlugin())
    except ImportError:  # pragma: no cover
        pass
