"""Erasure-code codec layer: interface, base, registry, plugins, stripe math.

TPU-native rebuild of the reference's src/erasure-code subsystem
(SURVEY.md §2.1).
"""
from .interface import (
    ErasureCode,
    ErasureCodeInterface,
    InsufficientChunks,
    InvalidProfile,
)
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry
from .stripe import StripeInfo

__all__ = [
    "ErasureCode",
    "ErasureCodeInterface",
    "ErasureCodePlugin",
    "ErasureCodePluginRegistry",
    "InsufficientChunks",
    "InvalidProfile",
    "StripeInfo",
]
