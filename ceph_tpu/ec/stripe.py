"""Stripe math — ECUtil::stripe_info_t re-done for batched TPU launches.

Reference: src/osd/ECUtil.h :: stripe_info_t — an object is laid out in
stripes of stripe_width = k * chunk_size bytes; chunk i of every stripe lands
on shard i.  The TPU consequence (SURVEY.md §5.7): shard j of an object is
the concatenation of chunk j of every stripe, so whole-object encode is ONE
[k, object_size/k] kernel launch with the stripe axis folded into the shard
length — no per-stripe loop exists anywhere.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class StripeInfo:
    """stripe_unit = chunk bytes per stripe; k = data chunks per stripe."""

    k: int
    stripe_unit: int

    @property
    def stripe_width(self) -> int:
        return self.k * self.stripe_unit

    def object_stripes(self, object_size: int) -> int:
        """Number of stripes covering an object (last may be padded)."""
        return -(-object_size // self.stripe_width)

    def shard_size(self, object_size: int) -> int:
        return self.object_stripes(object_size) * self.stripe_unit

    def logical_to_stripe(self, offset: int) -> tuple[int, int]:
        """logical byte offset -> (stripe number, offset within stripe)."""
        return divmod(offset, self.stripe_width)

    def chunk_of(self, offset: int) -> tuple[int, int]:
        """logical byte offset -> (shard id, byte offset within that shard)."""
        stripe, within = self.logical_to_stripe(offset)
        chunk, chunk_off = divmod(within, self.stripe_unit)
        return chunk, stripe * self.stripe_unit + chunk_off

    def shard_layout(self, data: bytes) -> np.ndarray:
        """Object bytes -> [k, shard_size] shard matrix (zero padded).

        This is the transpose-free layout: byte b of the object goes to
        shard chunk_of(b) — done with one reshape/transpose pass.
        """
        size = len(data)
        n_stripes = max(1, self.object_stripes(size))
        buf = np.zeros(n_stripes * self.stripe_width, dtype=np.uint8)
        buf[:size] = np.frombuffer(data, dtype=np.uint8)
        # [stripes, k, unit] -> [k, stripes, unit] -> [k, shard]
        arr = buf.reshape(n_stripes, self.k, self.stripe_unit)
        return np.ascontiguousarray(arr.transpose(1, 0, 2)).reshape(self.k, -1)

    def unshard(self, shards: np.ndarray, object_size: int) -> bytes:
        """[k, shard_size] -> original object bytes."""
        k, shard_size = shards.shape
        assert k == self.k
        n_stripes = shard_size // self.stripe_unit
        arr = shards.reshape(k, n_stripes, self.stripe_unit).transpose(1, 0, 2)
        return arr.reshape(-1)[:object_size].tobytes()
