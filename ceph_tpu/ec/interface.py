"""ErasureCodeInterface + ErasureCode base — the codec contract.

TPU-native re-design of the reference's codec interface and shared base class
(reference: src/erasure-code/ErasureCodeInterface.h :: ErasureCodeInterface —
init/get_chunk_count/get_chunk_size/minimum_to_decode/encode/decode/
decode_concat — and src/erasure-code/ErasureCode.{h,cc} :: ErasureCode, which
gives all plugins the shared chunk padding (encode_prepare), the default
first-k minimum_to_decode, and decode_concat).

Differences from the reference, by design:
- The host boundary type is numpy uint8 arrays / bytes instead of
  ceph::buffer::list; device residency is an implementation detail of each
  plugin (the JAX plugins keep chunks on the TPU).
- Chunk ids are plain ints 0..k+m-1 (shard ids); chunk_mapping supported.
- Sub-chunks (CLAY) are expressed exactly as the reference's
  get_sub_chunk_count() / minimum_to_decode sub-chunk ranges.
"""
from __future__ import annotations

from abc import ABC, abstractmethod

import numpy as np


class ErasureCodeInterface(ABC):
    """Pure-virtual contract (reference: ErasureCodeInterface.h)."""

    @abstractmethod
    def init(self, profile: dict) -> None: ...

    @abstractmethod
    def get_chunk_count(self) -> int: ...

    @abstractmethod
    def get_data_chunk_count(self) -> int: ...

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        """1 for MDS codes; >1 for CLAY (reference: ErasureCodeInterface.h ::
        get_sub_chunk_count, introduced for the CLAY plugin)."""
        return 1

    @abstractmethod
    def get_chunk_size(self, stripe_width: int) -> int: ...

    @abstractmethod
    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> dict[int, list[tuple[int, int]]]:
        """Map chunk -> list of (offset, length) sub-chunk ranges to fetch.

        MDS codes return the full chunk range; CLAY returns sub-chunk ranges
        (reference: ErasureCodeInterface.h :: minimum_to_decode; SHEC/CLAY
        make this nontrivial, SURVEY.md §3.2)."""

    @abstractmethod
    def encode(self, want_to_encode: set[int], data: bytes) -> dict[int, np.ndarray]: ...

    @abstractmethod
    def encode_chunks(self, data_chunks: np.ndarray) -> np.ndarray: ...

    @abstractmethod
    def decode(
        self, want_to_read: set[int], chunks: dict[int, np.ndarray], chunk_size: int
    ) -> dict[int, np.ndarray]: ...

    def get_chunk_mapping(self) -> list[int]:
        return []

    def supports_parity_delta(self) -> bool:
        """True iff encode_chunks is BYTE-COLUMN-LOCAL and chunk
        placement is the identity split: parity byte at column c depends
        only on the k data bytes at column c.  That is exactly the
        property the OSD's partial-stripe RMW parity-delta relies on
        (delta window encode XORed into stored parity).  Packet-based
        bitmatrix techniques, sub-chunked codes (CLAY), and
        position-remapped codes (LRC) must return False — for them the
        OSD falls back to full-stripe re-encode."""
        return False

    def decode_concat(self, chunks: dict[int, np.ndarray]) -> bytes:
        """Reassemble the original byte stream from data chunks (reference:
        ErasureCode.cc :: decode_concat)."""
        k = self.get_data_chunk_count()
        chunk_size = len(next(iter(chunks.values())))
        decoded = self.decode(set(range(k)), chunks, chunk_size)
        return b"".join(
            np.asarray(decoded[i], dtype=np.uint8).tobytes() for i in range(k)
        )


class ErasureCode(ErasureCodeInterface):
    """Shared plugin logic (reference: src/erasure-code/ErasureCode.cc).

    Subclasses set self.k / self.m in init() and implement encode_chunks /
    decode_chunks; everything else (padding, defaults) lives here.
    """

    #: alignment quantum for chunk sizes; 64 keeps chunks word- and
    #: lane-friendly on both CPU (SIMD tails) and TPU (lanes)
    CHUNK_ALIGN = 64

    def __init__(self, profile: dict | None = None):
        self.k = 0
        self.m = 0
        self.profile: dict = {}
        if profile is not None:
            self.init(profile)

    # -- geometry ---------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_chunk_size(self, stripe_width: int) -> int:
        """ceil(stripe_width / k) aligned up (reference: per-plugin
        get_chunk_size, e.g. ErasureCodeJerasure.cc aligns to k*w*packetsize;
        here the alignment is CHUNK_ALIGN bytes)."""
        padded = -(-stripe_width // self.k)
        return -(-padded // self.CHUNK_ALIGN) * self.CHUNK_ALIGN

    # -- defaults ---------------------------------------------------------
    def minimum_to_decode(self, want_to_read, available):
        """Default MDS policy (reference: ErasureCode.cc ::
        _minimum_to_decode): wanted chunks that are present are read
        directly; otherwise the first k available chunks."""
        want_to_read = set(want_to_read)
        available = set(available)
        if want_to_read <= available:
            chosen = want_to_read
        else:
            if len(available) < self.k:
                raise InsufficientChunks(
                    f"need {self.k} chunks, only {len(available)} available"
                )
            chosen = set(sorted(available)[: self.k])
        return {c: [(0, -1)] for c in sorted(chosen)}

    def encode_prepare(self, data: bytes, chunk_size: int) -> np.ndarray:
        """Zero-pad to k*chunk_size and split into [k, chunk_size]
        (reference: ErasureCode.cc :: encode_prepare)."""
        buf = np.zeros(self.k * chunk_size, dtype=np.uint8)
        raw = np.frombuffer(data, dtype=np.uint8)
        if raw.size > buf.size:
            raise ValueError(f"object of {raw.size} B exceeds stripe of {buf.size} B")
        buf[: raw.size] = raw
        return buf.reshape(self.k, chunk_size)

    def encode(self, want_to_encode, data: bytes):
        chunk_size = self.get_chunk_size(len(data))
        chunks = self.encode_prepare(data, chunk_size)
        parity = np.asarray(self.encode_chunks(chunks), dtype=np.uint8)
        all_chunks = {i: chunks[i] for i in range(self.k)}
        all_chunks.update({self.k + i: parity[i] for i in range(self.m)})
        return {i: all_chunks[i] for i in sorted(want_to_encode)}

    def decode(self, want_to_read, chunks, chunk_size):
        """Default decode via decode_chunks when anything wanted is missing
        (reference: ErasureCode.cc :: _decode)."""
        want_to_read = set(want_to_read)
        have = set(chunks)
        if want_to_read <= have:
            return {i: np.asarray(chunks[i], dtype=np.uint8) for i in want_to_read}
        # no k-of-n precondition here: locality codecs (SHEC/LRC/CLAY) can
        # decode from fewer than k chunks; each decode_chunks raises
        # InsufficientChunks itself when the set really is too small
        return self.decode_chunks(want_to_read, chunks)

    def decode_chunks(self, want_to_read, chunks):  # pragma: no cover - abstract
        raise NotImplementedError

    # -- profile helpers --------------------------------------------------
    def parse_int(self, profile: dict, key: str, default: int) -> int:
        v = profile.get(key, default)
        try:
            return int(v)
        except (TypeError, ValueError) as e:
            raise InvalidProfile(f"profile {key}={v!r} is not an integer") from e


class InvalidProfile(ValueError):
    """Profile rejected (the analog of OSDMonitor's profile validation
    failure, reference: src/mon/OSDMonitor.cc handling of
    `osd erasure-code-profile set`)."""


class InsufficientChunks(ValueError):
    """Fewer than k chunks available for decode."""
