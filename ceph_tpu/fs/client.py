"""FSClient — the filesystem client (reference: src/client/Client.cc;
SURVEY.md §2.6 "CephFS").

Metadata ops go to the MDS over the messenger; file data is striped
directly into the data pool through the striper (the MDS never sees file
bytes).  Path resolution walks components with ``lookup`` from the root
inode, exactly the reference's path-walk, with a small dentry cache
invalidated on every namespace mutation.

    fs = FSClient(cct, rados, mds_addr)
    fs.mount()
    fs.mkdir("/a")
    f = fs.open("/a/hello", create=True)
    f.write(b"world")
    f.read(0, 5)
    fs.listdir("/a")
"""
from __future__ import annotations

import threading

import time as _time
import uuid

from ..client.striper import ExtentIO, StripePolicy
from ..msg import Dispatcher, Messenger
from .mds import ROOT_INO
from .messages import MClientReply, MClientRequest, MClientSession

_ERR = {
    -2: FileNotFoundError,
    -17: FileExistsError,
    -20: NotADirectoryError,
    -21: IsADirectoryError,
    -39: OSError,  # ENOTEMPTY
}


class FSError(OSError):
    pass


class FileHandle:
    """Open file: striped data I/O + size writeback to the MDS (the
    cap-flush analog — reference: Client::_write updating inode size)."""

    def __init__(self, fs: "FSClient", inode: dict):
        self.fs = fs
        self.inode = dict(inode)
        layout = self.inode.get("layout") or {}
        self.policy = StripePolicy(
            object_size=layout.get("object_size", 1 << 22),
            stripe_unit=layout.get("stripe_unit", 1 << 16),
            stripe_count=layout.get("stripe_count", 4),
        )
        self.io = fs._data_io(layout.get("pool"))
        # reference object naming: {ino:x}.{objectno:08x}; the striper's
        # ExtentIO carries the RMW/sparse/truncate mechanics (logical size
        # lives in the MDS inode, not a sidecar)
        ino = self.inode["ino"]
        self._ext = ExtentIO(
            self.io, lambda objectno: f"{ino:x}.{objectno:08x}", self.policy
        )

    @property
    def ino(self) -> int:
        return self.inode["ino"]

    def size(self) -> int:
        return int(self.inode.get("size", 0))

    def write(self, data: bytes, off: int = 0) -> int:
        self._ext.write(data, off)
        # size/mtime writeback — the cap-flush analog
        attrs = {"ino": self.ino, "mtime": _time.time()}
        if off + len(data) > self.size():
            attrs["size"] = off + len(data)
        self.inode = self.fs._request("setattr", attrs)
        return len(data)

    def read(self, off: int = 0, length: int | None = None) -> bytes:
        size = self.size()
        if off >= size:
            return b""
        if length is None or off + length > size:
            length = size - off
        return self._ext.read(off, length)

    def truncate(self, size: int) -> None:
        old = self.size()
        if size < old:
            self._ext.truncate_data(old, size)
        self.inode = self.fs._request(
            "setattr", {"ino": self.ino, "size": size, "mtime": _time.time()}
        )


class FSClient(Dispatcher):
    def __init__(self, cct, rados, mds_addr: tuple[str, int],
                 name: str = "client.fs"):
        self.cct = cct
        self.rados = rados  # data-pool I/O rides the librados client
        self.mds_addr = tuple(mds_addr)
        self.name = name
        self.messenger = Messenger.create(cct, name)
        self.messenger.add_dispatcher(self)
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._tid = 0
        # per-process session id: the MDS keys its reply cache on
        # (session, tid) so a retried request after a connection reset is
        # answered from the cache instead of re-executed (at-most-once for
        # non-idempotent namespace ops)
        self._session = uuid.uuid4().hex
        self._replies: dict[int, tuple[int, object]] = {}
        self._session_open = False
        self._conn = None
        self._dcache: dict[tuple[int, str], dict] = {}
        self._ios: dict[str, object] = {}

    # -- session -----------------------------------------------------------
    def mount(self, timeout: float = 10.0) -> None:
        self.messenger.start()
        self._conn = self.messenger.connect(self.mds_addr)
        # the session id (not the display name) is the identity: the MDS
        # keys its per-session reply cache and open-session set on it, so
        # open/close and every request must all use the SAME identifier
        self._conn.send_message(
            MClientSession(op="request_open", client=self._session)
        )
        with self._lock:
            if not self._cond.wait_for(lambda: self._session_open, timeout):
                raise TimeoutError("MDS session open timed out")

    def unmount(self) -> None:
        try:
            if self._conn is not None:
                self._conn.send_message(
                    MClientSession(op="request_close", client=self._session)
                )
        except (OSError, ConnectionError):
            pass
        self.messenger.shutdown()

    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MClientSession):
            with self._lock:
                if msg.op == "open":
                    self._session_open = True
                self._cond.notify_all()
            return True
        if isinstance(msg, MClientReply):
            with self._lock:
                self._replies[msg.tid] = (msg.retval, msg.result)
                self._cond.notify_all()
            return True
        return False

    def ms_handle_reset(self, conn) -> None:
        with self._lock:
            if conn is self._conn:
                self._conn = None
            self._cond.notify_all()

    # -- RPC ---------------------------------------------------------------
    def _request(self, op: str, args: dict, timeout: float = 10.0):
        with self._lock:
            self._tid += 1
            tid = self._tid
        for attempt in range(3):
            with self._lock:
                conn = self._conn
            try:
                if conn is None:
                    conn = self.messenger.connect(self.mds_addr)
                    with self._lock:
                        self._conn = conn
                conn.send_message(
                    MClientRequest(
                        tid=tid, op=op, args=args, session=self._session
                    )
                )
            except (OSError, ConnectionError):
                with self._lock:
                    self._conn = None
                continue
            with self._lock:
                if self._cond.wait_for(
                    lambda: tid in self._replies or self._conn is None,
                    timeout,
                ) and tid in self._replies:
                    rv, result = self._replies.pop(tid)
                    break
        else:
            raise FSError(f"MDS request {op} failed after retries")
        if rv < 0:
            exc = _ERR.get(rv, FSError)
            raise exc(f"{op} {args}: errno {rv} ({result})")
        if op in ("create", "mkdir", "unlink", "rmdir", "rename", "link"):
            # link changes the TARGET inode's nlink too, so cached
            # lookups of any of its paths would go stale
            self._dcache.clear()
        elif op == "setattr":
            # setattr changes no dentries — evict only entries caching the
            # touched inode so data-write size/mtime writebacks don't nuke
            # every cached path lookup
            ino = args.get("ino")
            with self._lock:
                for key in [
                    k for k, v in self._dcache.items()
                    if v.get("ino") == ino
                ]:
                    del self._dcache[key]
        return result

    # -- path machinery ----------------------------------------------------
    @staticmethod
    def _split(path: str) -> list[str]:
        parts = [p for p in path.strip("/").split("/") if p]
        return parts

    def _lookup(self, parent: int, name: str) -> dict:
        key = (parent, name)
        hit = self._dcache.get(key)
        if hit is not None:
            return hit
        inode = self._request("lookup", {"parent": parent, "name": name})
        self._dcache[key] = inode
        return inode

    def _resolve(self, path: str) -> dict:
        inode = {"ino": ROOT_INO, "type": "dir"}
        for name in self._split(path):
            if inode["type"] != "dir":
                raise NotADirectoryError(path)
            inode = self._lookup(inode["ino"], name)
        return inode

    def _resolve_parent(self, path: str) -> tuple[int, str]:
        parts = self._split(path)
        if not parts:
            raise FSError("path refers to the root")
        parent = self._resolve("/".join(parts[:-1]))
        if parent["type"] != "dir":
            raise NotADirectoryError(path)
        return parent["ino"], parts[-1]

    def _data_io(self, pool: str | None):
        pool = pool or "cephfs_data"
        if pool not in self._ios:
            self._ios[pool] = self.rados.open_ioctx(pool)
        return self._ios[pool]

    # -- public API --------------------------------------------------------
    def mkdir(self, path: str) -> dict:
        parent, name = self._resolve_parent(path)
        return self._request("mkdir", {"parent": parent, "name": name})

    def listdir(self, path: str = "/") -> dict:
        inode = self._resolve(path)
        if inode["type"] != "dir":
            raise NotADirectoryError(path)
        return self._request("readdir", {"ino": inode["ino"]})

    def stat(self, path: str) -> dict:
        return self._resolve(path)

    def open(self, path: str, create: bool = False,
             layout: dict | None = None) -> FileHandle:
        if create:
            parent, name = self._resolve_parent(path)
            try:
                inode = self._request(
                    "create",
                    {"parent": parent, "name": name, "layout": layout},
                )
            except FileExistsError:
                inode = self._resolve(path)
        else:
            inode = self._resolve(path)
        if inode["type"] == "dir":
            raise IsADirectoryError(path)
        return FileHandle(self, inode)

    def _purge_data(self, inode: dict) -> None:
        """Remove a dead file's data objects (reference: the MDS purge
        queue; here the client that held the last ref does it inline)."""
        fh = FileHandle(self, inode)
        fh._ext.purge(fh.size())

    def link(self, src: str, dst: str) -> dict:
        """Hardlink (reference: Client::link -> MDS remote dentry): both
        paths resolve to the SAME inode afterwards; data lives until the
        last link goes."""
        inode = self._resolve(src)
        parent, name = self._resolve_parent(dst)
        return self._request(
            "link", {"parent": parent, "name": name, "ino": inode["ino"]}
        )

    def unlink(self, path: str) -> None:
        parent, name = self._resolve_parent(path)
        inode = self._request("unlink", {"parent": parent, "name": name})
        # purge only on the LAST link (reference: the purge queue fires
        # at nlink 0; surviving hardlinks keep the data objects)
        if inode.get("type") == "file" and inode.get("nlink_after", 0) == 0:
            self._purge_data(inode)

    def rmdir(self, path: str) -> None:
        parent, name = self._resolve_parent(path)
        self._request("rmdir", {"parent": parent, "name": name})

    def rename(self, src: str, dst: str) -> None:
        sdir, sname = self._resolve_parent(src)
        ddir, dname = self._resolve_parent(dst)
        result = self._request(
            "rename",
            {"srcdir": sdir, "sname": sname, "dstdir": ddir, "dname": dname},
        )
        # a replaced destination file's data objects are purged by the
        # client holding the last reference (the MDS purge-queue analog,
        # as in unlink)
        replaced = (result or {}).get("replaced")
        if (
            replaced is not None and replaced.get("type") == "file"
            and replaced.get("nlink_after", 0) == 0
        ):
            self._purge_data(replaced)

    def write_file(self, path: str, data: bytes) -> None:
        fh = self.open(path, create=True)
        if fh.size():
            fh.truncate(0)
        fh.write(data)

    def read_file(self, path: str) -> bytes:
        return self.open(path).read()
