"""FSClient — the filesystem client (reference: src/client/Client.cc;
SURVEY.md §2.6 "CephFS").

Metadata ops go to the MDS over the messenger; file data is striped
directly into the data pool through the striper (the MDS never sees file
bytes).  Path resolution walks components with ``lookup`` from the root
inode, exactly the reference's path-walk, with a small dentry cache
invalidated on every namespace mutation.

    fs = FSClient(cct, rados, mds_addr)
    fs.mount()
    fs.mkdir("/a")
    f = fs.open("/a/hello", create=True)
    f.write(b"world")
    f.read(0, 5)
    fs.listdir("/a")
"""
from __future__ import annotations

import threading

import time as _time
import uuid

from ..client.striper import ExtentIO, StripePolicy
from ..msg import Dispatcher, Messenger
from .mds import ROOT_INO
from .messages import (
    MClientCaps,
    MClientReply,
    MClientRequest,
    MClientSession,
)

_ERR = {
    -122: OSError,  # EDQUOT (directory quota)
    -2: FileNotFoundError,
    -17: FileExistsError,
    -20: NotADirectoryError,
    -21: IsADirectoryError,
    -39: OSError,  # ENOTEMPTY
}


class FSError(OSError):
    pass


class FileHandle:
    """Open file: striped data I/O + capability-gated metadata writeback
    (reference: Client::_write under Fw/Fb caps).

    With the "w" cap (exclusive opener) size/mtime updates BUFFER locally
    — one cap flush on close/revoke instead of a synchronous setattr per
    write.  Without it (contended file), every write syncs attrs to the
    MDS exactly like the pre-caps behavior.  With "r" the cached inode
    serves size() without a getattr; uncapped handles refresh from the
    MDS so another client's flushed size is visible."""

    def __init__(self, fs: "FSClient", inode: dict):
        self.fs = fs
        self.inode = dict(inode)
        layout = self.inode.get("layout") or {}
        self.policy = StripePolicy(
            object_size=layout.get("object_size", 1 << 22),
            stripe_unit=layout.get("stripe_unit", 1 << 16),
            stripe_count=layout.get("stripe_count", 4),
        )
        self.io = fs._data_io(layout.get("pool"))
        # reference object naming: {ino:x}.{objectno:08x}; the striper's
        # ExtentIO carries the RMW/sparse/truncate mechanics (logical size
        # lives in the MDS inode, not a sidecar)
        ino = self.inode["ino"]
        self._ext = ExtentIO(
            self.io, lambda objectno: f"{ino:x}.{objectno:08x}", self.policy
        )
        # at-snap view (".snap/<name>/file"): reads resolve clones at
        # this id, mutations are refused
        self.snapid: int | None = self.inode.pop("_snapid", None)
        seq = int(self.inode.pop("snap_seq", 0) or 0)
        if seq:
            fs._snap_seqs[ino] = max(fs._snap_seqs.get(ino, 0), seq)
        if self.snapid is None:
            fs._register_handle(self)

    def _refresh_snapc(self) -> None:
        if self.snapid is not None:
            raise FSError(30, "snapshot is read-only")  # EROFS
        self._ext.snapc_seq = max(self.fs._snap_seqs.get(self.ino, 0),
                                  self.fs._snap_floor)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def ino(self) -> int:
        return self.inode["ino"]

    def _caps(self) -> str:
        return self.fs._caps_of(self.ino)

    def size(self) -> int:
        if self.snapid is not None:
            # frozen at mksnap: the manifest inode IS the truth
            return int(self.inode.get("size", 0))
        ent = self.fs._cap_entry(self.ino)
        if ent is not None and ent["dirty"].get("size") is not None:
            return int(ent["dirty"]["size"])
        if not self._caps():
            # no cap: another client may hold w — ask the MDS (which
            # syncs writers) rather than trusting the stale local copy
            try:
                self.inode = self.fs._request(
                    "getattr", {"ino": self.ino})
            except OSError:
                pass  # unlinked-but-open: serve the last known attrs
        return int(self.inode.get("size", 0))

    def write(self, data: bytes, off: int = 0) -> int:
        self._refresh_snapc()
        self._ext.write(data, off)
        new_end = off + len(data)
        ent = self.fs._cap_entry(self.ino)
        if ent is not None and "w" in ent["caps"]:
            # Fb: buffer the attr update; flushed on close/revoke
            d = ent["dirty"]
            if new_end > max(int(self.inode.get("size", 0)),
                             int(d.get("size") or 0)):
                d["size"] = new_end
            d["mtime"] = _time.time()
            return len(data)
        attrs = {"ino": self.ino, "mtime": _time.time()}
        if new_end > self.size():
            attrs["size"] = new_end
        self.inode = self.fs._request("setattr", attrs)
        return len(data)

    def read(self, off: int = 0, length: int | None = None) -> bytes:
        size = self.size()
        if off >= size:
            return b""
        if length is None or off + length > size:
            length = size - off
        return self._ext.read(off, length, snapid=self.snapid)

    def truncate(self, size: int) -> None:
        self._refresh_snapc()
        old = self.size()
        if size < old:
            self._ext.truncate_data(old, size)
        self.fs._flush_caps(self.ino)  # a buffered larger size is stale now
        self.inode = self.fs._request(
            "setattr", {"ino": self.ino, "size": size, "mtime": _time.time()}
        )

    def close(self) -> None:
        """Flush buffered attrs and release caps (reference:
        Client::_release_fh -> cap release)."""
        if self.snapid is not None:
            return  # snap view: no caps were taken
        self.fs._close_handle(self)


class FSClient(Dispatcher):
    def __init__(self, cct, rados, mds_addr: tuple[str, int],
                 name: str = "client.fs"):
        self.cct = cct
        self.rados = rados  # data-pool I/O rides the librados client
        self.mds_addr = tuple(mds_addr)
        self.name = name
        self.messenger = Messenger.create(cct, name)
        self.messenger.add_dispatcher(self)
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._tid = 0
        # per-process session id: the MDS keys its reply cache on
        # (session, tid) so a retried request after a connection reset is
        # answered from the cache instead of re-executed (at-most-once for
        # non-idempotent namespace ops)
        self._session = uuid.uuid4().hex
        self._replies: dict[int, tuple[int, object]] = {}
        self._session_open = False
        self._conn = None
        # multi-rank routing (round-4 verdict item #8): per-ino rank
        # hints learned from MDS redirects + per-rank connections with
        # their own open sessions.  A failed rank's conn is dropped and
        # the request falls back to rank 0 (which, after a takeover,
        # either serves or re-redirects).
        self._rank_addrs: dict[int, tuple] = {0: tuple(mds_addr)}
        self._rank_conns: dict[int, object] = {}
        self._ino_rank: dict[int, int] = {}
        self._dcache: dict[tuple[int, str], dict] = {}
        self._ios: dict[str, object] = {}
        # capability state (reference: Client::caps): ino -> {"caps",
        # "dirty" {size, mtime}, "count" open handles}.  In-memory; a
        # connection reset drops every cap (reconnect-window analog) but
        # keeps the dirty attrs, which then flush synchronously.
        self._caps_state: dict[int, dict] = {}
        # ino -> newest realm snapid (from open replies and revoke
        # pushes): the self-managed snap context for data writes
        self._snap_seqs: dict[int, int] = {}
        # floor for OUR OWN handles opened before a mksnap WE issued:
        # the MDS cannot revoke-push the new seq to the requester (its
        # connection thread is inside the mksnap request), so the reply
        # seeds this instead.  Over-stamping an unrelated write mints a
        # harmless orphan clone; under-stamping would lose the snapshot.
        self._snap_floor = 0

    # -- session -----------------------------------------------------------
    def mount(self, timeout: float = 10.0) -> None:
        self.messenger.start()
        self._conn = self.messenger.connect(self.mds_addr)
        self._rank_conns[0] = self._conn
        # the session id (not the display name) is the identity: the MDS
        # keys its per-session reply cache and open-session set on it, so
        # open/close and every request must all use the SAME identifier
        self._conn.send_message(
            MClientSession(op="request_open", client=self._session)
        )
        with self._lock:
            if not self._cond.wait_for(lambda: self._session_open, timeout):
                raise TimeoutError("MDS session open timed out")

    def unmount(self) -> None:
        for ino in list(self._caps_state):
            try:
                self._flush_caps(ino, release=True)
            except (OSError, FSError):
                pass
        try:
            if self._conn is not None:
                self._conn.send_message(
                    MClientSession(op="request_close", client=self._session)
                )
        except (OSError, ConnectionError):
            pass
        self.messenger.shutdown()

    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MClientSession):
            with self._lock:
                if msg.op == "open":
                    self._session_open = True
                self._cond.notify_all()
            return True
        if isinstance(msg, MClientReply):
            with self._lock:
                self._replies[msg.tid] = (msg.retval, msg.result)
                self._cond.notify_all()
            return True
        if isinstance(msg, MClientCaps) and msg.op == "revoke":
            # MDS recall: flush dirty attrs, drop to the granted set, ack
            # with a "flush" carrying whatever was buffered (reference:
            # Client::handle_cap_grant's revoke branch)
            with self._lock:
                ent = self._caps_state.get(msg.ino)
                dirty = dict(ent["dirty"]) if ent else {}
                if ent is not None:
                    ent["caps"] = msg.caps or ""
                    ent["dirty"] = {}
                seq = (msg.attrs or {}).get("snap_seq")
                if seq:
                    # a mksnap bumped our realm: stamp every later data
                    # write so the OSD clones pre-snap bytes
                    self._snap_seqs[msg.ino] = max(
                        self._snap_seqs.get(msg.ino, 0), int(seq))
            try:
                conn.send_message(MClientCaps(
                    op="flush", client=self._session, ino=msg.ino,
                    caps=msg.caps or "", cap_seq=msg.cap_seq,
                    attrs=dirty or None,
                ))
            except (OSError, ConnectionError):
                pass
            return True
        return False

    def ms_handle_reset(self, conn) -> None:
        with self._lock:
            if conn is self._conn:
                self._conn = None
            for r, c in list(self._rank_conns.items()):
                if c is conn:
                    self._rank_conns.pop(r, None)
            # every cap dies with the session connection; buffered attrs
            # survive locally and MUST reach the restarted MDS — it holds
            # our writer registration in its sessionmap and blocks attr
            # readers on our reconnect flush (reference: the client
            # reconnect phase re-asserting caps after MDS failover)
            dirty = {}
            for ino, ent in self._caps_state.items():
                if "w" in ent["caps"] and ent["dirty"]:
                    dirty[ino] = dict(ent["dirty"])
                ent["caps"] = ""
            self._cond.notify_all()
        if dirty:
            threading.Thread(  # noqa: CL13 — fire-and-forget by design: the reconnect flush retries against the restarting MDS and self-terminates on its own deadline
                target=self._reconnect_flush, args=(dirty,), daemon=True
            ).start()

    def _reconnect_flush(self, dirty: dict, timeout: float = 15.0) -> None:
        """Push buffered attrs at the (restarted) MDS until a send lands
        or the deadline passes — flushes are absolute-valued and
        idempotent, so resending is safe."""
        import time as _t

        deadline = _t.monotonic() + timeout
        pending = dict(dirty)
        while pending and _t.monotonic() < deadline:
            try:
                conn = self.messenger.connect(self.mds_addr)
                for ino in list(pending):
                    conn.send_message(MClientCaps(
                        op="flush", client=self._session, ino=ino,
                        caps="", cap_seq=0, attrs=pending[ino],
                    ))
                    pending.pop(ino)
            except (OSError, ConnectionError):
                _t.sleep(0.5)

    # -- RPC ---------------------------------------------------------------
    def _conn_for_rank(self, rank: int):
        """Connection to an MDS rank, opened (with a session hello) on
        first use.  None = no known address / connect failed."""
        with self._lock:
            conn = self._conn if rank == 0 else self._rank_conns.get(rank)
        if conn is not None:
            return conn
        addr = self._rank_addrs.get(rank)
        if addr is None:
            return None
        try:
            conn = self.messenger.connect(tuple(addr))
            conn.send_message(
                MClientSession(op="request_open", client=self._session)
            )
        except (OSError, ConnectionError):
            return None
        with self._lock:
            if rank == 0:
                self._conn = conn
            self._rank_conns[rank] = conn
        return conn

    def _drop_rank_conn(self, rank: int) -> None:
        with self._lock:
            self._rank_conns.pop(rank, None)
            if rank == 0:
                self._conn = None

    def _request(self, op: str, args: dict, timeout: float = 10.0):
        with self._lock:
            self._tid += 1
            tid = self._tid
        # multi-rank routing: anchor ino -> rank hint (learned from
        # redirects); unknown anchors start at rank 0, whose redirect
        # teaches us the owner
        anchor = args.get("parent") or args.get("srcdir") or args.get("ino")
        rank = self._ino_rank.get(anchor, 0) if anchor is not None else 0
        rv = result = None
        for attempt in range(6):
            conn = self._conn_for_rank(rank)
            if conn is None:
                # rank unreachable: try any OTHER known rank — after a
                # takeover the survivor serves (or re-redirects) every
                # subtree, including a dead rank 0's
                alt = next(
                    (r for r in sorted(self._rank_addrs)
                     if r != rank and self._conn_for_rank(r) is not None),
                    None,
                )
                if alt is not None:
                    rank = alt
                    continue
                _time.sleep(0.3)  # nothing reachable: brief wait
                rank = 0
                continue
            try:
                conn.send_message(
                    MClientRequest(
                        tid=tid, op=op, args=args, session=self._session
                    )
                )
            except (OSError, ConnectionError):
                self._drop_rank_conn(rank)
                rank = 0
                continue
            with self._lock:
                got = self._cond.wait_for(
                    lambda: tid in self._replies, timeout
                ) and tid in self._replies
                if got:
                    rv, result = self._replies.pop(tid)
            if not got:
                # dead or deposed rank: fall back to rank 0 (post-
                # takeover it either serves or redirects afresh)
                self._drop_rank_conn(rank)
                if anchor is not None:
                    self._ino_rank.pop(anchor, None)
                rank = 0
                continue
            if rv == -116 and isinstance(result, dict):
                if result.get("exdev"):
                    rv, result = -18, "cross-subtree rename"  # EXDEV
                    break
                if "rank" in result:
                    rank = int(result["rank"])
                    if result.get("addr"):
                        self._rank_addrs[rank] = tuple(result["addr"])
                    if anchor is not None:
                        self._ino_rank[anchor] = rank
                    continue  # resend at the owner
            break
        else:
            raise FSError(f"MDS request {op} failed after retries")
        # tag inodes with the rank that served them: follow-up ops
        # anchored on a fresh ino (open/getattr/readdir of a just-created
        # entry) must route to its owner, which rank 0 cannot resolve for
        # inos it has never cached
        if rank != 0 and rv == 0:
            with self._lock:
                if isinstance(result, dict):
                    if "ino" in result:
                        self._ino_rank[result["ino"]] = rank
                    else:  # readdir: {name: inode}
                        for v in result.values():
                            if isinstance(v, dict) and "ino" in v:
                                self._ino_rank[v["ino"]] = rank
        if rv < 0:
            exc = _ERR.get(rv, FSError)
            raise exc(f"{op} {args}: errno {rv} ({result})")
        if op in ("create", "mkdir", "unlink", "rmdir", "rename", "link"):
            # link changes the TARGET inode's nlink too, so cached
            # lookups of any of its paths would go stale
            self._dcache.clear()
        elif op in ("setattr", "setxattr"):
            # attr ops change no dentries — evict only entries caching
            # the touched inode so writebacks/tagging don't nuke every
            # cached path lookup
            ino = args.get("ino")
            with self._lock:
                for key in [
                    k for k, v in self._dcache.items()
                    if v.get("ino") == ino
                ]:
                    del self._dcache[key]
        return result

    # -- path machinery ----------------------------------------------------
    @staticmethod
    def _split(path: str) -> list[str]:
        parts = [p for p in path.strip("/").split("/") if p]
        return parts

    def _lookup(self, parent: int, name: str) -> dict:
        key = (parent, name)
        hit = self._dcache.get(key)
        if hit is not None:
            return hit
        inode = self._request("lookup", {"parent": parent, "name": name})
        self._dcache[key] = inode
        return inode

    def _resolve(self, path: str) -> dict:
        inode = {"ino": ROOT_INO, "type": "dir"}
        for name in self._split(path):
            if inode["type"] != "dir":
                raise NotADirectoryError(path)
            inode = self._lookup(inode["ino"], name)
        return inode

    def _resolve_parent(self, path: str) -> tuple[int, str]:
        parts = self._split(path)
        if not parts:
            raise FSError("path refers to the root")
        parent = self._resolve("/".join(parts[:-1]))
        if parent["type"] != "dir":
            raise NotADirectoryError(path)
        return parent["ino"], parts[-1]

    def _data_io(self, pool: str | None):
        pool = pool or "cephfs_data"
        if pool not in self._ios:
            self._ios[pool] = self.rados.open_ioctx(pool)
        return self._ios[pool]

    # -- capabilities ------------------------------------------------------
    def _cap_entry(self, ino: int) -> dict | None:
        return self._caps_state.get(ino)

    def _caps_of(self, ino: int) -> str:
        ent = self._caps_state.get(ino)
        return ent["caps"] if ent else ""

    def _register_handle(self, fh: "FileHandle") -> None:
        with self._lock:
            ent = self._caps_state.setdefault(
                fh.ino, {"caps": "", "dirty": {}, "count": 0}
            )
            caps = fh.inode.pop("caps", None)
            if caps is not None:
                ent["caps"] = caps
            ent["count"] += 1

    def _flush_caps(self, ino: int, release: bool = False) -> None:
        """Write buffered size/mtime back to the MDS (cap flush).  Uses a
        plain setattr request (journaled identically to the revoke-ack
        flush) so it also covers the cap-lost-on-reset path."""
        with self._lock:
            ent = self._caps_state.get(ino)
            if ent is None:
                return
            dirty, ent["dirty"] = ent["dirty"], {}
            caps = ent["caps"]
            if release:
                self._caps_state.pop(ino, None)
        if dirty.get("size") is not None or dirty.get("mtime") is not None:
            self._request("setattr", {"ino": ino, **dirty})
        if release and caps:
            try:
                conn = self._conn
                if conn is not None:
                    conn.send_message(MClientCaps(
                        op="release", client=self._session, ino=ino,
                        caps="", cap_seq=0,
                    ))
            except (OSError, ConnectionError):
                pass

    def _close_handle(self, fh: "FileHandle") -> None:
        with self._lock:
            ent = self._caps_state.get(fh.ino)
            if ent is None:
                return
            ent["count"] -= 1
            last = ent["count"] <= 0
        self._flush_caps(fh.ino, release=last)

    # -- public API --------------------------------------------------------
    def _snap_split(self, path: str):
        """(dir_path, snap_name, rest) for paths crossing a ".snap"
        component (reference: the client's magic snapdir), else None."""
        parts = self._split(path)
        if ".snap" not in parts:
            return None
        i = parts.index(".snap")
        return ("/".join(parts[:i]),
                parts[i + 1] if len(parts) > i + 1 else None,
                "/".join(parts[i + 2:]))

    def _snapid_of(self, dino: int, snap: str) -> int:
        snaps = self._request("lssnap", {"ino": dino})
        ent = snaps.get(snap)
        if ent is None:
            raise FileNotFoundError(f"no snapshot {snap!r}")
        return int(ent["snapid"])

    def mkdir(self, path: str) -> dict:
        sp = self._snap_split(path)
        if sp is not None:
            dirp, snap, rest = sp
            if not snap or rest:
                raise FSError(22, f"bad snapshot path {path!r}")
            dino = self._resolve(dirp)["ino"]
            # flush + release our own caps first: the MDS syncs OTHER
            # sessions' writers itself, but a revoke aimed at us would
            # deadlock against our in-flight mksnap request (one
            # connection, one dispatch thread) and time out with stale
            # sizes in the manifest
            for cino in list(self._caps_state):
                self._flush_caps(cino, release=True)
            out = self._request("mksnap", {"ino": dino, "name": snap})
            with self._lock:
                self._snap_floor = max(self._snap_floor,
                                       int(out.get("snapid", 0)))
            return out
        parent, name = self._resolve_parent(path)
        return self._request("mkdir", {"parent": parent, "name": name})

    def _overlay_dirty(self, inode: dict) -> dict:
        """Merge this client's own buffered (cap-dirty) attrs into an MDS
        inode — a stat must see our unflushed writes (reference: the
        client fills stat from its own caps when it holds them)."""
        ent = self._caps_state.get(inode.get("ino"))
        if not ent or not ent["dirty"]:
            return inode
        out = dict(inode)
        for k in ("size", "mtime"):
            if ent["dirty"].get(k) is not None:
                out[k] = ent["dirty"][k]
        return out

    def listdir(self, path: str = "/") -> dict:
        sp = self._snap_split(path)
        if sp is not None:
            dirp, snap, rest = sp
            dino = self._resolve(dirp)["ino"]
            if snap is None:
                # `ls dir/.snap` — the snapshots themselves, as dirs
                snaps = self._request("lssnap", {"ino": dino})
                return {n: {"type": "dir", "ino": dino,
                            "snapid": s["snapid"],
                            "mtime": s.get("created")}
                        for n, s in sorted(snaps.items())}
            sid = self._snapid_of(dino, snap)
            out = self._request("snapls", {"ino": dino, "snapid": sid,
                                           "rel": rest})
            return {n: self._public_inode(i)
                    for n, i in sorted(out.items())}
        inode = self._resolve(path)
        if inode["type"] != "dir":
            raise NotADirectoryError(path)
        out = self._request("readdir", {"ino": inode["ino"]})
        return {
            n: self._public_inode(self._overlay_dirty(i))
            if isinstance(i, dict) else i
            for n, i in (out or {}).items()
        }

    def setxattr(self, path: str, name: str, value: bytes) -> None:
        """User extended attribute (reference: Client::setxattr)."""
        import base64

        inode = self._resolve(path)
        self._request("setxattr", {
            "ino": inode["ino"], "name": name,
            "val": base64.b64encode(bytes(value)).decode(),
        })  # _request evicts this ino's dentry-cache entries

    def getxattr(self, path: str, name: str) -> bytes:
        import base64

        inode = self._resolve(path)
        raw = self._request("getxattrs",
                            {"ino": inode["ino"], "name": name})
        if name not in (raw or {}):
            raise FSError(f"no xattr {name!r} on {path!r}")
        return base64.b64decode(raw[name])

    def listxattr(self, path: str) -> dict:
        import base64

        inode = self._resolve(path)
        raw = self._request("getxattrs", {"ino": inode["ino"]})
        return {n: base64.b64decode(v) for n, v in (raw or {}).items()}

    def removexattr(self, path: str, name: str) -> None:
        inode = self._resolve(path)
        self._request("setxattr", {
            "ino": inode["ino"], "name": name, "val": None,
        })  # _request evicts this ino's dentry-cache entries

    @staticmethod
    def _public_inode(inode: dict) -> dict:
        """Inode view for stat/listdir: the embedded xattrs dict carries
        WIRE-encoded (b64) values — the xattr surface is
        getxattr/listxattr, which decode; leaking the raw map would hand
        consumers encoded junk (review r5)."""
        return {k: v for k, v in inode.items() if k != "xattrs"}

    def stat(self, path: str) -> dict:
        sp = self._snap_split(path)
        if sp is not None:
            dirp, snap, rest = sp
            dino = self._resolve(dirp)["ino"]
            if snap is None:
                return {"type": "dir", "ino": dino, "name": ".snap"}
            sid = self._snapid_of(dino, snap)
            return self._public_inode(self._request(
                "snapstat", {"ino": dino, "snapid": sid, "rel": rest}))
        return self._public_inode(
            self._overlay_dirty(self._resolve(path)))

    def open(self, path: str, create: bool = False,
             layout: dict | None = None, want: str = "rw") -> FileHandle:
        """`want` asks for capabilities: "rw" (buffer attrs while the
        sole opener) or "r" (cache attrs alongside other readers).  The
        MDS may grant less under contention."""
        sp = self._snap_split(path)
        if sp is not None:
            dirp, snap, rest = sp
            if create or not snap or not rest:
                raise FSError(30, "snapshot is read-only")  # EROFS
            dino = self._resolve(dirp)["ino"]
            sid = self._snapid_of(dino, snap)
            inode = self._request(
                "snapstat", {"ino": dino, "snapid": sid, "rel": rest})
            if inode.get("type") == "dir":
                raise IsADirectoryError(path)
            node = dict(inode)
            node["_snapid"] = sid
            return FileHandle(self, node)
        if create:
            parent, name = self._resolve_parent(path)
            try:
                inode = self._request(
                    "create",
                    {"parent": parent, "name": name, "layout": layout},
                )
            except FileExistsError:
                inode = self._resolve(path)
        else:
            inode = self._resolve(path)
        if inode["type"] == "dir":
            raise IsADirectoryError(path)
        # explicit open RPC: grants caps (and flushes competing writers)
        inode = self._request(
            "open", {"ino": inode["ino"], "want": want})
        return FileHandle(self, dict(inode))

    def _purge_data(self, inode: dict) -> None:
        """Remove a dead file's data objects (reference: the MDS purge
        queue; here the client that held the last ref does it inline).
        Under a live snapshot the removes carry the realm seq, so the
        OSD clones each object before deleting the head — the at-snap
        view survives the unlink."""
        seq = int(inode.get("snap_seq", 0) or 0)
        fh = FileHandle(self, dict(inode))
        try:
            fh._ext.snapc_seq = max(
                seq, self._snap_seqs.get(inode["ino"], 0),
                self._snap_floor)
            fh._ext.purge(int(fh.inode.get("size", 0)))
        finally:
            fh.close()

    def link(self, src: str, dst: str) -> dict:
        """Hardlink (reference: Client::link -> MDS remote dentry): both
        paths resolve to the SAME inode afterwards; data lives until the
        last link goes."""
        inode = self._resolve(src)
        parent, name = self._resolve_parent(dst)
        return self._request(
            "link", {"parent": parent, "name": name, "ino": inode["ino"]}
        )

    def set_subtree(self, path: str, rank: int) -> dict:
        """Pin a top-level directory to an MDS rank (the `mds export`
        analog; multi-active, round-4 verdict item #8)."""
        return self._request("set_subtree", {"path": path, "rank": rank})

    def unlink(self, path: str) -> None:
        parent, name = self._resolve_parent(path)
        inode = self._request("unlink", {"parent": parent, "name": name})
        # purge only on the LAST link (reference: the purge queue fires
        # at nlink 0; surviving hardlinks keep the data objects)
        if inode.get("type") == "file" and inode.get("nlink_after", 0) == 0:
            self._purge_data(inode)

    def rmdir(self, path: str) -> None:
        sp = self._snap_split(path)
        if sp is not None:
            dirp, snap, rest = sp
            if not snap or rest:
                raise FSError(22, f"bad snapshot path {path!r}")
            dino = self._resolve(dirp)["ino"]
            self._request("rmsnap", {"ino": dino, "name": snap})
            return
        parent, name = self._resolve_parent(path)
        self._request("rmdir", {"parent": parent, "name": name})

    def rename(self, src: str, dst: str) -> None:
        sdir, sname = self._resolve_parent(src)
        ddir, dname = self._resolve_parent(dst)
        result = self._request(
            "rename",
            {"srcdir": sdir, "sname": sname, "dstdir": ddir, "dname": dname},
        )
        # a replaced destination file's data objects are purged by the
        # client holding the last reference (the MDS purge-queue analog,
        # as in unlink)
        replaced = (result or {}).get("replaced")
        if (
            replaced is not None and replaced.get("type") == "file"
            and replaced.get("nlink_after", 0) == 0
        ):
            self._purge_data(replaced)

    def write_file(self, path: str, data: bytes) -> None:
        with self.open(path, create=True) as fh:
            if fh.size():
                fh.truncate(0)
            fh.write(data)

    def read_file(self, path: str) -> bytes:
        with self.open(path, want="r") as fh:
            return fh.read()
