"""MDSDaemon — metadata server for the FS layer (reference: src/mds/MDSRank,
MDCache, MDLog, CInode/CDir/CDentry; SURVEY.md §2.6 "CephFS").

Faithful structural choices:

- The namespace lives in RADOS objects in a *metadata pool*: one dirfrag
  object per directory (``dir.{ino:x}``) whose OMAP holds one key per
  dentry with the child inode embedded in the value — the reference's
  dirfrag omap layout (src/mds/CDir.cc stores dentries as omap keys of
  the dir object; primary dentry embeds the inode, src/mds/CDentry.h).
  Hardlinks are REMOTE dentries ({"remote": ino} stubs) resolving to
  the primary via the backpointer map; the primary inode carries nlink,
  and removing the primary while links remain promotes a recorded
  remote stub to primary (src/mds/CDentry.h remote linkage; the
  promotion the reference performs at link-merge time).
- Updates are journaled before dirfrags are flushed (src/mds/MDLog.cc:
  EUpdate events into journal segments stored as RADOS objects); a
  restarted MDS replays segments newer than the last flush point, so
  namespace mutations survive an MDS crash without per-op dirfrag
  writeback.
- One big lock serializes metadata ops — the reference's ``mds_lock``.
- File *data* never passes through the MDS: clients stripe it directly
  into the data pool (src/client/Client.cc writes via the Objecter).
  File size/mtime come back to the MDS as a ``setattr`` — the cap-flush
  analog.
"""
from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict

from ..client.rados import Rados
from ..msg import Dispatcher, Messenger
from .messages import (
    MClientCaps,
    MClientReply,
    MClientRequest,
    MClientSession,
)

ROOT_INO = 1


def assemble_rank_rows(io, now: float | None = None) -> list[dict]:
    """MDS rank table rows from the metadata pool's registry/beacons/
    subtree map — ONE assembler shared by `ceph fs status` and the
    dashboard's /api/fs so the two surfaces cannot drift (the same
    sharing pattern as status_module.assemble_osd_rows)."""
    if now is None:
        now = time.time()
    try:
        ranks = {int(k): tuple(json.loads(v))
                 for k, v in (io.omap_get("mds_ranks") or {}).items()}
    except IOError:
        return []
    try:
        beacons = {int(k): json.loads(v)
                   for k, v in (io.omap_get("mds_beacons") or {}).items()}
    except IOError:
        beacons = {}  # beacons unreadable must not hide live ranks
    try:
        subs = json.loads(io.read("mds_subtrees"))
    except (IOError, ValueError):
        subs = {}
    rows = []
    for rank in sorted(ranks):
        if rank not in beacons:
            state = "no-beacon"
        elif now - beacons[rank] <= MDSDaemon.BEACON_GRACE:
            state = "active"
        else:
            state = f"stale({now - beacons[rank]:.0f}s)"
        host, port = ranks[rank]
        rows.append({
            "rank": rank, "state": state, "addr": f"{host}:{port}",
            "subtrees": sorted(
                f"/{n}" for n, o in subs.items() if int(o) == rank
            ),
        })
    return rows


class MDSDaemon(Dispatcher):
    """Active MDS rank (reference: src/mds/MDSDaemon.cc + MDSRank.cc).

    Multi-active (round-4 verdict item #8): each rank journals to its own
    segment chain and owns a set of ROOT-LEVEL subtrees recorded in the
    shared `mds_subtrees` object (the subtree-export analog, coarse:
    whole top-level directories).  Ops anchored in another rank's subtree
    are answered with a redirect carrying the owner's address; clients
    re-route and cache.  Rank liveness rides per-rank beacon keys in the
    metadata pool; when a rank's beacon goes stale the lowest surviving
    rank absorbs it — replays the dead rank's journal, adopts its
    subtrees, and rewrites the maps — so the namespace survives a rank
    failure without an external orchestrator (the mon/standby role,
    collapsed into peer takeover).  Cross-subtree renames return -EXDEV
    (the reference forwards slave requests between ranks; out of scope).
    Ino allocation is partitioned per rank (disjoint 2^40 ranges) so two
    ranks can never mint the same ino.
    """

    BEACON_INTERVAL = 1.0
    BEACON_GRACE = 3.0
    SUBTREE_TTL = 2.0

    def __init__(
        self,
        cct,
        mon_addrs,
        metadata_pool: str = "cephfs_meta",
        data_pool: str = "cephfs_data",
        bind_addr: tuple[str, int] | None = None,
        rank: int = 0,
    ):
        self.cct = cct
        self.rank = int(rank)
        self._bind_addr = tuple(bind_addr) if bind_addr else None
        self.mon_addrs = mon_addrs
        self.metadata_pool = metadata_pool
        self.data_pool = data_pool
        self.messenger = Messenger.create(cct, "mds")
        self.messenger.add_dispatcher(self)
        self.messenger.auth_gen_provider = lambda: (
            self._rados.mc.osdmap.auth_gens.get("mds", 1)
            if self._rados is not None and self._rados.mc.osdmap is not None
            else 1
        )
        self.addr: tuple[str, int] | None = None
        self._lock = threading.RLock()  # the mds_lock
        # in-memory cache (MDCache): dirfrags + ino backpointers
        self.dirs: dict[int, dict[str, dict]] = {}
        self.backptr: dict[int, tuple[int, str]] = {}  # ino -> (parent, name)
        # hardlink reverse map: ino -> remote-stub dentry locations
        self.remotes: dict[int, set[tuple[int, str]]] = {}
        self.next_ino = ROOT_INO + 1
        # SnapServer counter (reference: src/mds/SnapServer — rank-scoped
        # here: snapid = rank<<20 | n, so every rank mints globally
        # unique ids and a realm's ids stay monotonic because a subtree
        # lives on one rank)
        self.snap_counter = 0
        self._dirty: set[int] = set()  # dirfrags awaiting flush
        # per-dirfrag dentry deltas (name -> inode | None=removed): the
        # flush writes only changed omap keys, not the whole directory
        # (reference: CDir commits dirty dentries, not full dirfrags)
        self._dirty_names: dict[int, dict[str, dict | None]] = {}
        # dirfrags needing a full clear+rewrite (newly created dirs,
        # whose omap object must exist even when empty so _load finds it)
        self._dirty_full: set[int] = set()
        self._seg_seq = 0   # current journal segment (MDLog)
        self._seg_idx = 0   # next event slot within the segment
        self._first_seg = 0
        self._sessions: set[str] = set()
        # per-session bounded tid -> (rv, result) reply cache: resent
        # requests after a connection reset are answered, not re-executed.
        # Bounded PER SESSION (reference: Session::have_completed_request
        # is per-Session) so one busy client can't evict another session's
        # in-flight retry window
        self._reply_cache: OrderedDict[str, OrderedDict] = OrderedDict()
        # client capabilities (reference: Capability.h + the Locker's
        # per-inode filelock): ino -> {session: {"caps": "rw"|"r"|"",
        # "seq": n}}.  "w" implies the holder may BUFFER size/mtime
        # (Fw|Fb), "r" implies it may cache attrs (Fr|Fc); in-memory
        # only — clients treat a connection reset as cap loss and fall
        # back to synchronous writeback (the reconnect-window analog).
        self.caps: dict[int, dict[str, dict]] = {}
        self._caps_cond = threading.Condition(self._lock)
        # session -> live connection, for pushing revokes (the Session's
        # Connection in the reference)
        self._session_conns: dict[str, object] = {}
        # persisted writer-cap registry (the SessionMap analog,
        # reference: src/mds/SessionMap.cc stored in the metadata pool):
        # ino -> [sessions holding w].  A restarted MDS reads it and
        # makes attr reads of those inos WAIT for the writer's reconnect
        # flush (the mds_reconnect_timeout window) before serving, so
        # buffered sizes survive MDS failover; writers that never return
        # are evicted at the deadline.
        self._writers: dict[int, list[str]] = {}
        self._reconnect: dict[int, list[str]] = {}  # prior incarnation's
        self._reconnect_deadline = 0.0
        self._rados: Rados | None = None
        self._io = None
        # multi-rank state: cached subtree map (top-level name -> rank)
        # + known rank addresses, both backed by shared pool objects
        self._subtrees: dict[str, int] = {}
        self._subtrees_read = 0.0
        self._rank_addrs: dict[int, tuple[str, int]] = {}
        self._beacon_stop = threading.Event()
        self._beacon_thread: threading.Thread | None = None

    # -- per-rank object naming (rank 0 keeps the legacy names so old
    # metadata pools replay unchanged) -----------------------------------
    def _rk(self, name: str) -> str:
        return name if self.rank == 0 else f"{name}.r{self.rank}"

    @property
    def _jprefix(self) -> str:
        return "journal." if self.rank == 0 else f"journal.r{self.rank}."

    @staticmethod
    def _jseg(oid: str, prefix: str) -> int | None:
        """Segment number of a journal oid under `prefix`, or None when
        the oid belongs to another rank's chain (rank 0's bare prefix
        also matches 'journal.rN.*' — filter those)."""
        rest = oid[len(prefix):]
        seg = rest.split(".", 1)[0]
        try:
            return int(seg, 16)
        except ValueError:
            return None

    # -- persistence helpers ----------------------------------------------
    def _obj_read(self, oid: str) -> dict | list | None:
        try:
            return json.loads(self._io.read(oid))
        except (IOError, ValueError):
            return None

    def _obj_write(self, oid: str, body) -> None:
        self._io.write_full(oid, json.dumps(body).encode())

    def _load(self) -> None:
        """Boot: load the flushed namespace, then replay journal segments
        (reference: MDCache::open_root + MDLog::replay)."""
        head = self._obj_read(self._rk("mds_head")) or {}
        self._first_seg = int(head.get("first_seg", 0))
        self._seg_seq = self._first_seg
        ino_tbl = self._obj_read(self._rk("mds_inotable")) or {}
        self.next_ino = int(ino_tbl.get(
            "next_ino", ROOT_INO + 1 + self.rank * (1 << 40)))
        self.snap_counter = int(ino_tbl.get("snap_counter", 0))
        for oid in self._io.list_objects():
            if not oid.startswith("dir."):
                continue
            ino = int(oid[4:], 16)
            try:
                kv = self._io.omap_get(oid)
            except IOError:
                kv = {}
            if kv:
                self.dirs[ino] = {
                    name: json.loads(v) for name, v in kv.items()
                }
                continue
            # legacy format (rounds <= 2 kept dirfrags as a JSON blob in
            # the object DATA): migrate instead of silently loading an
            # empty directory and losing the namespace (advisor r3).
            # Migrate NOW — omap written first, blob cleared after — a
            # stale blob left behind would resurrect deleted entries the
            # next time this directory's omap goes empty (review r4)
            legacy = self._obj_read(oid)
            if legacy:
                self.dirs[ino] = dict(legacy)
                self._io.omap_set(oid, {
                    name: json.dumps(inode).encode()
                    for name, inode in legacy.items()
                })
                self._io.write_full(oid, b"")
                self.cct.dout(
                    "mds", 1,
                    f"migrated legacy dirfrag {oid} "
                    f"({len(legacy)} entries) to omap",
                )
            else:
                self.dirs[ino] = {}
        if ROOT_INO not in self.dirs:
            self.dirs[ROOT_INO] = {}
            self._dirty.add(ROOT_INO)
            self._dirty_full.add(ROOT_INO)
        # backptrs must exist BEFORE replay: a replayed setattr resolves
        # its inode through backptr, and inodes living in flushed dirfrags
        # are invisible to it otherwise (their size/mtime updates would be
        # silently dropped, then the post-replay flush would trim the
        # journal and make the loss permanent)
        self._rebuild_backptrs()
        # replay: events are idempotent state setters, applied in order;
        # one RADOS object per event (see _journal)
        seq = self._first_seg
        while True:
            idx = 0
            while True:
                ev = self._obj_read(f"{self._jprefix}{seq:08x}.{idx:04x}")
                if ev is None:
                    break
                self._apply(ev)
                idx += 1
            if idx == 0:
                break
            seq += 1
        self._seg_seq = seq
        self._seg_idx = 0
        self._flush()
        # sessionmap: writer sessions from the previous incarnation get a
        # reconnect window to re-flush their buffered attrs before attr
        # reads of their inos are served (reference: the MDS reconnect
        # phase driven by the persisted SessionMap)
        sm = self._obj_read(self._rk("mds_sessionmap")) or {}
        self._reconnect = {
            int(k, 16): list(v) for k, v in sm.items() if v
        }
        if self._reconnect:
            self._reconnect_deadline = time.monotonic() + float(
                self.cct.conf.get("mds_reconnect_timeout")
            )

    def _rebuild_backptrs(self) -> None:
        """Primary dentries (embedded inode) feed backptr; remote stubs
        ({"remote": ino}) feed the hardlink reverse map (reference:
        CDentry primary vs remote linkage)."""
        self.backptr = {}
        self.remotes = {}
        for dino, entries in self.dirs.items():
            for name, inode in entries.items():
                if "remote" in inode:
                    self.remotes.setdefault(
                        inode["remote"], set()).add((dino, name))
                else:
                    self.backptr[inode["ino"]] = (dino, name)

    def _resolve_entry(self, entry: dict | None) -> dict | None:
        """Follow a remote (hardlink) stub to its primary inode; primary
        entries return as-is (reference: CDentry::get_linkage)."""
        if entry is None or "remote" not in entry:
            return entry
        return self._inode_of(entry["remote"])

    def _flush(self) -> None:
        """Flush dirty dirfrags + inotable, then trim the journal
        (reference: MDLog segment expiry writing back dirty CDirs)."""
        for ino in sorted(self._dirty):
            oid = f"dir.{ino:x}"
            if ino not in self.dirs:
                try:
                    self._io.remove(oid)
                except IOError:
                    pass
                continue
            if ino in self._dirty_full:
                # new dirfrag: create its omap object (clear creates via
                # touch) and write everything
                self._io.omap_clear(oid)
                if self.dirs[ino]:
                    self._io.omap_set(oid, {
                        name: json.dumps(inode).encode()
                        for name, inode in self.dirs[ino].items()
                    })
                continue
            # delta flush: only the dentries that changed since the last
            # flush — O(change), not O(directory)
            ops = self._dirty_names.get(ino, {})
            sets = {n: json.dumps(i).encode()
                    for n, i in ops.items() if i is not None}
            rms = [n for n, i in ops.items() if i is None]
            if sets:
                self._io.omap_set(oid, sets)
            if rms:
                self._io.omap_rm_keys(oid, rms)
        self._dirty.clear()
        self._dirty_names.clear()
        self._dirty_full.clear()
        self._obj_write(self._rk("mds_inotable"),
                        {"next_ino": self.next_ino,
                         "snap_counter": self.snap_counter})
        self._first_seg = self._seg_seq
        self._obj_write(self._rk("mds_head"), {"first_seg": self._first_seg})
        # trim: every event object of now-expired segments
        for oid in self._io.list_objects():
            if not oid.startswith(self._jprefix):
                continue
            seg = self._jseg(oid, self._jprefix)
            if seg is not None and seg < self._first_seg:
                try:
                    self._io.remove(oid)
                except IOError:
                    pass

    def _journal(self, ev: dict) -> None:
        """Persist one event as its own RADOS object (write-ahead: durable
        before the reply).  One object per event because the object store
        is whole-object — rewriting a growing segment object per op would
        be O(n^2) bytes per segment."""
        self._obj_write(
            f"{self._jprefix}{self._seg_seq:08x}.{self._seg_idx:04x}", ev
        )
        self._seg_idx += 1  # noqa: CL2 — journal path runs under _lock (dispatch)

    def _commit(self, ev: dict) -> None:
        """Journal, apply, then roll the segment if full.  The roll's
        dirfrag flush must come AFTER apply — flushing between journal and
        apply would trim the segment holding an event the dirfrags don't
        yet contain, losing it."""
        self._journal(ev)
        self._apply(ev)
        max_ev = self.cct.conf.get("mds_journal_segment_events")
        if self._seg_idx >= max_ev:
            self._seg_idx = 0
            self._seg_seq += 1  # noqa: CL2 — journal path runs under _lock (dispatch)
            self._flush()

    # -- event application (shared by live ops and replay) ----------------
    def _mark(self, dino: int, name: str, inode: dict | None) -> None:
        """Record one dentry delta for the flush (None = removed)."""
        self._dirty.add(dino)
        if dino not in self._dirty_full:
            self._dirty_names.setdefault(dino, {})[name] = inode

    def _apply(self, ev: dict) -> None:
        kind = ev["e"]
        if kind == "link":  # create/mkdir: insert dentry with embedded inode
            parent, name, inode = ev["parent"], ev["name"], ev["inode"]
            self.dirs.setdefault(parent, {})[name] = inode
            if inode["type"] == "dir":
                self.dirs.setdefault(inode["ino"], {})
                self._dirty.add(inode["ino"])
                self._dirty_full.add(inode["ino"])  # create the omap obj
            self.backptr[inode["ino"]] = (parent, name)
            self.next_ino = max(self.next_ino, inode["ino"] + 1)  # noqa: CL2 — _apply runs under _lock or single-threaded replay
            self._mark(parent, name, inode)
        elif kind == "link_remote":  # hardlink: remote stub + nlink SET
            parent, name, ino = ev["parent"], ev["name"], ev["ino"]
            stub = {"remote": ino, "type": "file"}
            self.dirs.setdefault(parent, {})[name] = stub
            self.remotes.setdefault(ino, set()).add((parent, name))
            self._mark(parent, name, stub)
            inode = self._inode_of(ino)
            bp = self.backptr.get(ino)
            if inode is not None and bp is not None:
                # ABSOLUTE value from the event, not +1: replay against
                # already-flushed state must stay idempotent (review r4)
                inode["nlink"] = ev["nlink"]
                self._mark(bp[0], bp[1], inode)
        elif kind == "unlink":
            parent, name = ev["parent"], ev["name"]
            entry = self.dirs.get(parent, {}).pop(name, None)
            self._mark(parent, name, None)
            if "stub_ino" in ev:
                # a hardlink stub died: the primary's nlink is SET to the
                # journaled value (idempotent replay)
                ino = ev["stub_ino"]
                self.remotes.get(ino, set()).discard((parent, name))
                inode = self._inode_of(ino)
                bp = self.backptr.get(ino)
                if inode is not None and bp is not None:
                    inode["nlink"] = ev["primary_nlink"]
                    self._mark(bp[0], bp[1], inode)
            else:
                if entry is not None and "remote" not in entry:
                    self.backptr.pop(entry["ino"], None)
                    if entry["type"] == "dir":
                        self.dirs.pop(entry["ino"], None)
                        self._dirty.add(entry["ino"])
                # primary dentry died but hardlinks remain: the recorded
                # stub becomes primary.  The FULL promoted inode rides in
                # the event so replay applies even when the source dentry
                # was already flushed away (entry None — review r4)
                pinode = ev.get("promote_inode")
                if pinode is not None:
                    pdino, pname = ev["promote"]
                    pinode = dict(pinode)
                    self.dirs.setdefault(pdino, {})[pname] = pinode
                    self.remotes.get(pinode["ino"], set()).discard(
                        (pdino, pname))
                    self.backptr[pinode["ino"]] = (pdino, pname)
                    self._mark(pdino, pname, pinode)
        elif kind == "rename":
            sdir, sname = ev["srcdir"], ev["sname"]
            ddir, dname = ev["dstdir"], ev["dname"]
            entry = self.dirs.get(sdir, {}).pop(sname, None)
            # src removal marked BEFORE the dst set so a same-path rename
            # nets out to the set, not the removal
            self._mark(sdir, sname, None)
            if entry is None:
                # replay against partially-flushed dirfrags: the source
                # dentry was already flushed away (crash inside _flush
                # between the src and dst omap writes).  The event carries
                # the full moved entry so the rename still applies —
                # without this the moved dentry and any replaced-primary
                # promotion would be lost, then the post-replay flush
                # would trim the journal and make the loss permanent
                entry = ev.get("moved_entry")
            if entry is not None:
                replaced = self.dirs.setdefault(ddir, {}).get(dname)
                # replay idempotency: when the dst dirfrag was already
                # flushed with the moved entry before the crash, the
                # "replaced" dentry IS the moved entry — tearing it down
                # would destroy the moved directory's children (the
                # post-replay flush would then delete the dirfrag object
                # permanently) or double-apply a stub clobber.  Identity
                # compares the linkage target, covering both primary
                # dentries and remote stubs.
                def _ident(d):
                    return d.get("remote", d.get("ino"))

                if replaced is not None and _ident(replaced) == _ident(entry):
                    replaced = None
                if replaced is not None and "remote" in replaced:
                    # clobbering a hardlink stub: its primary lives on
                    # with the journaled ABSOLUTE nlink
                    rino = replaced["remote"]
                    self.remotes.get(rino, set()).discard((ddir, dname))
                    rinode = self._inode_of(rino)
                    bp = self.backptr.get(rino)
                    if (rinode is not None and bp is not None
                            and "replaced_nlink" in ev):
                        rinode["nlink"] = ev["replaced_nlink"]
                        self._mark(bp[0], bp[1], rinode)
                elif replaced is not None:
                    self.backptr.pop(replaced["ino"], None)
                    if replaced["type"] == "dir":  # empty dir replaced
                        self.dirs.pop(replaced["ino"], None)
                        self._dirty.add(replaced["ino"])
                pinode = ev.get("promote_inode")
                if pinode is not None:
                    pdino, pname = ev["promote_replaced"]
                    pinode = dict(pinode)
                    self.dirs.setdefault(pdino, {})[pname] = pinode
                    self.remotes.get(pinode["ino"], set()).discard(
                        (pdino, pname))
                    self.backptr[pinode["ino"]] = (pdino, pname)
                    self._mark(pdino, pname, pinode)
                self.dirs[ddir][dname] = entry
                if "remote" in entry:
                    ino = entry["remote"]
                    self.remotes.setdefault(ino, set()).discard(
                        (sdir, sname))
                    self.remotes.setdefault(ino, set()).add((ddir, dname))
                else:
                    self.backptr[entry["ino"]] = (ddir, dname)
                self._mark(ddir, dname, entry)
        elif kind == "mksnap":
            dino, name = ev["ino"], ev["name"]
            inode = self._inode_of(dino)
            if inode is not None:
                inode.setdefault("snaps", {})[name] = {
                    "snapid": ev["snapid"], "created": ev["created"],
                }
                bp = self.backptr.get(dino)
                if bp:
                    self._mark(bp[0], bp[1], inode)
            self.snap_counter = max(self.snap_counter,  # noqa: CL2 — _apply runs under _lock or single-threaded replay
                                    ev["snapid"] & 0xFFFFF)
        elif kind == "rmsnap":
            dino, name = ev["ino"], ev["name"]
            inode = self._inode_of(dino)
            if inode is not None and name in (inode.get("snaps") or {}):
                del inode["snaps"][name]
                bp = self.backptr.get(dino)
                if bp:
                    self._mark(bp[0], bp[1], inode)
        elif kind == "setattr":
            ino = ev["ino"]
            bp = self.backptr.get(ino)
            if bp is not None:
                inode = self.dirs[bp[0]][bp[1]]
                self._mark(bp[0], bp[1], inode)
                for f in ("size", "mtime"):
                    if ev.get(f) is not None:
                        inode[f] = ev[f]
                self._dirty.add(bp[0])
        elif kind == "setxattr":
            # user extended attributes on the inode (reference:
            # Server::handle_client_setxattr — xattrs live in the
            # CInode, journaled like any metadata update).  val None
            # removes (removexattr).
            ino = ev["ino"]
            bp = self.backptr.get(ino)
            if bp is not None:
                inode = self.dirs[bp[0]][bp[1]]
                xattrs = inode.setdefault("xattrs", {})
                if ev["val"] is None:
                    xattrs.pop(ev["name"], None)
                else:
                    xattrs[ev["name"]] = ev["val"]
                self._mark(bp[0], bp[1], inode)
                self._dirty.add(bp[0])

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._rados = Rados(self.cct, self.mon_addrs,
                            name=f"mds.{self.rank}")
        self._rados.connect(timeout=30.0)
        self._io = self._rados.open_ioctx(self.metadata_pool)
        with self._lock:
            self._load()
        self.addr = self.messenger.bind(
            self._bind_addr or ("127.0.0.1", 0)
        )
        self.messenger.start()
        # register this rank + first beacon (omap keys: per-rank writers
        # never clobber each other), then watch sibling beacons
        try:
            self._io.omap_set("mds_ranks", {
                str(self.rank): json.dumps(list(self.addr)).encode()
            })
            self._beacon_once()
        except IOError:
            pass
        self._beacon_stop.clear()
        self._beacon_thread = threading.Thread(
            target=self._beacon_loop, name=f"mds.{self.rank}-beacon",
            daemon=True,
        )
        self._beacon_thread.start()

    def shutdown(self) -> None:
        with self._lock:
            try:
                self._flush()
            except Exception as e:
                self.cct.dout("mds", 0,
                              f"mds.{self.rank} shutdown flush failed "
                              f"(continuing to hard_kill): {e!r}")
        self.hard_kill()

    def hard_kill(self) -> None:
        """Stop WITHOUT the shutdown flush — crash simulation for failover
        tests: the journal alone must carry unflushed namespace state
        (and the beacon stops cold, so a surviving rank takes over)."""
        self._beacon_stop.set()
        if self._beacon_thread is not None:
            # the wait() wakes on the stop event; joined before the
            # transport it beacons through goes away
            self._beacon_thread.join(timeout=5)
        try:
            self.messenger.shutdown()
        except Exception as e:
            self.cct.dout("mds", 0,
                          f"mds.{self.rank} messenger shutdown raised: "
                          f"{e!r}")
        if self._rados is not None:
            try:
                self._rados.shutdown()
            except Exception as e:
                self.cct.dout("mds", 0,
                              f"mds.{self.rank} rados shutdown raised: "
                              f"{e!r}")
        # the context goes last: its admin socket serves debug commands
        # right up until the daemon is gone
        self.cct.shutdown()

    # -- multi-rank: beacons, subtree map, takeover ------------------------
    def _beacon_once(self) -> None:
        self._io.omap_set("mds_beacons", {
            str(self.rank): json.dumps(time.time()).encode()
        })

    def _beacon_loop(self) -> None:
        """Liveness beacon + sibling watch (the mon beacon/MDSMap laning,
        collapsed to pool state).  The LOWEST surviving rank absorbs a
        rank whose beacon went stale — one deterministic taker, no race."""
        while not self._beacon_stop.wait(timeout=self.BEACON_INTERVAL):
            try:
                self._beacon_once()
                ranks = self._read_ranks()
                if len(ranks) <= 1:
                    continue
                beacons = {
                    int(k): json.loads(v)
                    for k, v in (self._io.omap_get("mds_beacons") or {}).items()
                }
                now = time.time()
                live = [r for r in ranks
                        if now - beacons.get(r, 0) <= self.BEACON_GRACE]
                if self.rank != min(live, default=self.rank):
                    continue
                for r in sorted(ranks):
                    if r != self.rank and r not in live:
                        self.cct.dout(
                            "mds", 1,
                            f"mds.{self.rank}: rank {r} beacon stale; "
                            f"absorbing")
                        self.absorb_rank(r)
            except IOError:
                continue  # pool unreachable this tick; keep beating

    def _read_ranks(self) -> dict[int, tuple[str, int]]:
        try:
            kv = self._io.omap_get("mds_ranks") or {}
        except IOError:
            return dict(self._rank_addrs)
        self._rank_addrs = {
            int(k): tuple(json.loads(v)) for k, v in kv.items()
        }
        return dict(self._rank_addrs)

    def _load_subtrees(self, force: bool = False) -> dict[str, int]:
        if force or time.monotonic() - self._subtrees_read > self.SUBTREE_TTL:
            old = self._subtrees
            self._subtrees = {
                k: int(v)
                for k, v in (self._obj_read("mds_subtrees") or {}).items()
            }
            self._subtrees_read = time.monotonic()
            # a subtree newly assigned to US must be re-read from the
            # pool: our boot-time cache predates the old owner's flush
            for name, owner in self._subtrees.items():
                if owner == self.rank and old.get(name) != self.rank:
                    self.adopt_subtree(name)
        return self._subtrees

    def _top_name(self, ino: int) -> str | None:
        """Top-level directory name an ino lives under (None = at/above
        root, always rank 0's)."""
        name = None
        seen = 0
        while ino != ROOT_INO:
            bp = self.backptr.get(ino)
            if bp is None:
                return name
            ino, name = bp
            seen += 1
            if seen > 1000:  # corrupt backptr cycle guard
                return name
        return name

    def _owner_rank(self, ino: int) -> int:
        if ino == ROOT_INO:
            return 0  # root itself is always rank 0's (review r5: this
            # must not fall into the unknown-ino refresh below)
        top = self._top_name(ino)
        if top is None:
            # unknown ino: our cache may predate a subtree newly
            # assigned to US — refresh the map (which adopts and
            # rebuilds backptrs) and retry the walk.  Without this, a
            # rank whose first look at a redirected op happens after
            # its TTL window ping-pongs the client back to rank 0
            # forever (capstone test).  Rate-limited to one forced
            # refresh per TTL so an ino we can NEVER resolve (it lives
            # in another rank's subtree) doesn't cost a pool read per op.
            now = time.monotonic()
            if now - getattr(self, "_last_forced_subtrees", 0.0)                     > self.SUBTREE_TTL:
                self._last_forced_subtrees = now
                self._load_subtrees(force=True)
                top = self._top_name(ino)
            if top is None:
                return 0  # genuinely not ours: rank 0 owns unknowns
        return self._load_subtrees().get(top, 0)

    def absorb_rank(self, r: int) -> None:
        """Take over a dead rank: reload its FLUSHED dirfrags from the
        pool, replay its journal over them (the events are idempotent
        state setters), adopt its subtrees, and retire its per-rank
        objects (reference: the rank-replacement phase of MDSMap
        transitions, journal-replay included).

        The reload must come first: the dead rank flushed (and trimmed
        its journal) at segment rolls AFTER we booted, so our cached
        copies of its dirfrags can be stale in ways the remaining
        journal no longer covers."""
        jprefix = "journal." if r == 0 else f"journal.r{r}."
        head_name = "mds_head" if r == 0 else f"mds_head.r{r}"
        with self._lock:
            subs0 = {
                k: int(v)
                for k, v in (self._obj_read("mds_subtrees") or {}).items()
            }
            if r == 0:
                # rank 0 implicitly owns root + every unpinned top-level
                # dir: refresh root from the pool, then every top-level
                # subtree not owned by a DIFFERENT live rank
                try:
                    kv = self._io.omap_get(f"dir.{ROOT_INO:x}")
                except IOError:
                    kv = {}
                self.dirs[ROOT_INO] = {
                    n: json.loads(v) for n, v in kv.items()
                }
                self._rebuild_backptrs()
                for name, entry in list(self.dirs[ROOT_INO].items()):
                    if entry.get("type") != "dir":
                        continue
                    # only the DEAD rank's dirs (unpinned default to 0);
                    # our own subtrees' cache may hold unflushed state
                    # the pool copy would clobber
                    if subs0.get(name, 0) == r:
                        self.adopt_subtree(name)
            else:
                for name, owner in subs0.items():
                    if owner == r:
                        self.adopt_subtree(name)
            head = self._obj_read(head_name) or {}
            seq = int(head.get("first_seg", 0))
            while True:
                idx = 0
                while True:
                    ev = self._obj_read(f"{jprefix}{seq:08x}.{idx:04x}")
                    if ev is None:
                        break
                    self._apply(ev)
                    idx += 1
                if idx == 0:
                    break
                seq += 1
            self._flush()
            subs = {
                k: int(v)
                for k, v in (self._obj_read("mds_subtrees") or {}).items()
            }
            changed = False
            for name, owner in subs.items():
                if owner == r:
                    subs[name] = self.rank
                    changed = True
            if changed:
                self._obj_write("mds_subtrees", subs)
            self._load_subtrees(force=True)
            try:
                self._io.omap_rm_keys("mds_ranks", [str(r)])
                self._io.omap_rm_keys("mds_beacons", [str(r)])
            except IOError:
                pass
            # retire the dead rank's journal chain (absorbed into our
            # flushed state) so a revived daemon cannot replay it twice
            for oid in list(self._io.list_objects()):
                if oid.startswith(jprefix) and \
                        self._jseg(oid, jprefix) is not None:
                    try:
                        self._io.remove(oid)
                    except IOError:
                        pass
        self.cct.dout("mds", 1, f"mds.{self.rank}: absorbed rank {r}")

    def adopt_subtree(self, name: str) -> None:
        """Reload a subtree's dirfrags from the pool (called when a
        subtree is assigned to this rank AFTER boot: our cached copy may
        predate the previous owner's flush)."""
        with self._lock:
            if self.rank != 0:
                # our ROOT cache may predate the subtree's creation (root
                # is rank 0's); refresh its dentry from the pool.  Rank 0
                # never does this — its own root is the authority and may
                # hold unflushed state.
                try:
                    kv = self._io.omap_get(f"dir.{ROOT_INO:x}")
                except IOError:
                    kv = {}
                if name in kv:
                    self.dirs.setdefault(ROOT_INO, {})[name] = \
                        json.loads(kv[name])
            root_entry = self.dirs.get(ROOT_INO, {}).get(name)
            if root_entry is None or root_entry.get("type") != "dir":
                return
            todo = [root_entry["ino"]]
            while todo:
                ino = todo.pop()
                try:
                    kv = self._io.omap_get(f"dir.{ino:x}")
                except IOError:
                    kv = {}
                self.dirs[ino] = {
                    n: json.loads(v) for n, v in kv.items()
                }
                for inode in self.dirs[ino].values():
                    if inode.get("type") == "dir":
                        todo.append(inode["ino"])
            self._rebuild_backptrs()

    # -- op handling -------------------------------------------------------
    def _inode_of(self, ino: int) -> dict | None:
        if ino == ROOT_INO:
            return {"ino": ROOT_INO, "type": "dir", "size": 0, "mtime": 0.0}
        bp = self.backptr.get(ino)
        return None if bp is None else self.dirs[bp[0]][bp[1]]

    def _snap_seq_of(self, ino: int) -> int:
        """Newest snapid governing `ino` — max over its ancestor realms
        (reference: SnapRealm::get_newest_seq).  Drives the snap
        context clients stamp on data writes."""
        seq = 0
        seen = set()
        cur = ino
        while cur and cur not in seen:
            seen.add(cur)
            inode = self._inode_of(cur)
            if inode:
                for s in (inode.get("snaps") or {}).values():
                    seq = max(seq, int(s["snapid"]))
            bp = self.backptr.get(cur)
            if bp is None:
                break
            cur = bp[0]
        return seq

    def _is_under(self, ino: int, top: int) -> bool:
        seen = set()
        cur = ino
        while cur not in seen:
            if cur == top:
                return True
            seen.add(cur)
            bp = self.backptr.get(cur)
            if bp is None:
                return False
            cur = bp[0]
        return False

    def _walk_subtree(self, dino: int, rel: str = ""):
        """Yield (relpath, inode) for every entry under `dino`,
        resolving hardlink stubs; cycles cannot form (dirs are never
        hardlinked)."""
        for name, ent in sorted((self.dirs.get(dino) or {}).items()):
            inode = self._resolve_entry(ent)
            if inode is None:
                continue
            path = f"{rel}/{name}" if rel else name
            yield path, inode
            if inode.get("type") == "dir":
                yield from self._walk_subtree(inode["ino"], path)

    def _alloc_ino(self) -> int:
        ino = self.next_ino
        self.next_ino += 1  # noqa: CL2 — every caller reaches here via _handle, under _lock
        return ino

    # -- capabilities (reference: src/mds/Locker.cc issue/revoke flow) -----
    def _cap_writers(self, ino: int, but: str | None = None) -> list[str]:
        return [
            s for s, c in self.caps.get(ino, {}).items()
            if "w" in c["caps"] and s != but
        ]

    def _persist_writers(self) -> None:
        """Write the SessionMap analog: every session holding w — current
        grants plus prior-incarnation sessions still inside their
        reconnect window (a second crash must keep waiting for them)."""
        merged: dict[str, list[str]] = {}
        for src in (self._writers, self._reconnect):
            for ino, sessions in src.items():
                if sessions:
                    cur = merged.setdefault(f"{ino:x}", [])
                    cur.extend(s for s in sessions if s not in cur)
        self._obj_write(self._rk("mds_sessionmap"), merged)

    def _set_writer(self, ino: int, session: str, on: bool) -> None:
        cur = self._writers.setdefault(ino, [])
        if on and session not in cur:
            cur.append(session)
        elif not on and session in cur:
            cur.remove(session)
        else:
            return
        if not cur:
            self._writers.pop(ino, None)
        self._persist_writers()

    def _await_reconnect(self, ino: int) -> None:
        """Block attr access to an ino whose prior-incarnation writer has
        not re-flushed yet (the reconnect phase, per-inode); the deadline
        evicts writers that never came back — their buffered attrs are
        lost, exactly what evicting a dead client costs upstream."""
        if not self._reconnect.get(ino):
            return
        remain = self._reconnect_deadline - time.monotonic()
        if remain > 0:
            self._caps_cond.wait_for(
                lambda: not self._reconnect.get(ino), timeout=remain
            )
        if self._reconnect.get(ino):
            self._reconnect.pop(ino, None)
            self._persist_writers()
            self.cct.dout(
                "mds", 1, f"evicted unreconnected writer(s) of ino {ino:x}"
            )

    def _revoke_caps(self, ino: int, session: str, keep: str,
                     timeout: float = 5.0,
                     attrs: dict | None = None) -> None:
        """Push a revoke to `session` and wait for its flush-ack (the
        Locker's revoke path).  Waiting releases the mds_lock (condition
        wait), so the client's MClientCaps flush can be applied by the
        messenger thread.  A client that never acks is force-downgraded —
        the session-eviction analog: its buffered size/mtime are lost,
        exactly what evicting a dead client costs upstream."""
        holders = self.caps.get(ino, {})
        ent = holders.get(session)
        if ent is None:
            return
        if set(ent["caps"]) <= set(keep):
            # nothing to revoke — but an attrs payload (the mksnap
            # realm-seq push) must still reach sessions parked at ""
            # (MIX-degraded writers), else they keep writing with a
            # stale snap context and clobber the snapshot
            if attrs:
                conn = self._session_conns.get(session)
                if conn is not None:
                    try:
                        conn.send_message(MClientCaps(
                            op="revoke", client=session, ino=ino,
                            caps=ent["caps"], cap_seq=ent.get("seq", 0),
                            attrs=attrs,
                        ))
                    except (OSError, ConnectionError):
                        pass
            return
        ent["seq"] = ent.get("seq", 0) + 1
        conn = self._session_conns.get(session)
        if conn is not None:
            try:
                conn.send_message(MClientCaps(
                    op="revoke", client=session, ino=ino, caps=keep,
                    cap_seq=ent["seq"], attrs=attrs,
                ))
            except (OSError, ConnectionError):
                conn = None
        if conn is None:
            ent["caps"] = keep  # dead session: force-drop
            if "w" not in keep:
                self._set_writer(ino, session, False)
            return
        self._caps_cond.wait_for(
            lambda: set(holders.get(session, {"caps": ""})["caps"])
            <= set(keep),
            timeout=timeout,
        )
        ent = holders.get(session)
        if ent is not None and not set(ent["caps"]) <= set(keep):
            ent["caps"] = keep  # ack timeout: evict the cap
            if "w" not in keep:
                self._set_writer(ino, session, False)

    def _grant_caps(self, ino: int, session: str | None, want: str) -> str:
        """Grant rules (the filelock state machine, collapsed): exclusive
        writer gets rw (buffer+cache); a second opener forces MIX — every
        holder drops to uncached sync I/O ("" for writers, "r" readers);
        readers coexist caching ("r").  Degraded holders are not
        re-upgraded when contention ends until they reopen (the reference
        re-issues caps eagerly; out of scope)."""
        if session is None:
            return ""
        self._await_reconnect(ino)
        holders = self.caps.setdefault(ino, {})
        others = {s: c for s, c in holders.items() if s != session}
        if want == "rw":
            if others:
                for s in list(others):
                    self._revoke_caps(ino, s, "")
                grant = ""
            else:
                grant = "rw"
        else:
            for s in self._cap_writers(ino, but=session):
                self._revoke_caps(ino, s, "r")
            grant = "r"
        prev = holders.get(session)
        holders[session] = {"caps": grant,
                            "seq": (prev or {}).get("seq", 0)}
        self._set_writer(ino, session, "w" in grant)
        return grant

    def _sync_writers(self, ino: int, but: str | None = None) -> None:
        """Flush other sessions' buffered size/mtime before serving an
        attr read or destroying the inode (Locker::simple_sync).  Also
        holds attr reads for a prior incarnation's writer still inside
        the reconnect window."""
        self._await_reconnect(ino)
        for s in self._cap_writers(ino, but=but):
            self._revoke_caps(ino, s, "r")

    def _invalidate_readers(self, ino: int, but: str | None = None) -> None:
        """Recall other sessions' attr caches after an attr change they
        did not make (the Fc recall a setattr triggers in the Locker) —
        their next size() re-fetches from the MDS."""
        for s, c in list(self.caps.get(ino, {}).items()):
            if s != but and "r" in c["caps"]:
                self._revoke_caps(ino, s, "")

    def _drop_ino_caps(self, ino: int) -> None:
        self.caps.pop(ino, None)
        self._reconnect.pop(ino, None)
        if self._writers.pop(ino, None) is not None:
            self._persist_writers()

    def _check_redirect(self, op: str, a: dict) -> dict | None:
        """Ownership gate (multi-rank): an op anchored in another rank's
        subtree is redirected to its owner (reference: the MDS forwards
        requests to the auth MDS of the dentry; here the client re-sends).
        Cross-subtree renames are handled in the rename op itself."""
        if len(self._rank_addrs) <= 1 and not self._load_subtrees():
            return None  # single-rank: never redirect
        anchor = a.get("parent")
        if op == "rename":
            anchor = a.get("srcdir")
        elif anchor is None:
            anchor = a.get("ino")
        if anchor is None:
            return None
        owner = self._owner_rank(int(anchor))
        if owner == self.rank:
            if op == "rename":
                downer = self._owner_rank(int(a.get("dstdir", anchor)))
                if downer != self.rank:
                    return {"exdev": True}
            return None
        addr = self._read_ranks().get(owner)
        if addr is None:
            # owner not registered (mid-takeover): serve locally rather
            # than bounce the client forever
            return None
        return {"rank": owner, "addr": list(addr)}

    # -- directory quotas (reference: CephFS quota realms — the
    # ceph.quota.max_files / ceph.quota.max_bytes vxattrs on a dir bound
    # its SUBTREE; upstream enforces via client quota realms, here the
    # MDS enforces at create/setattr time) --------------------------------
    def _quota_of(self, inode: dict, name: str) -> int:
        import base64

        raw = (inode.get("xattrs") or {}).get(name)
        if raw is None:
            return 0
        try:
            return int(base64.b64decode(raw))
        except (ValueError, TypeError):
            return 0

    def _subtree_usage(self, ino: int) -> tuple[int, int]:
        """(entries, bytes) under a directory, recursive.  `entries`
        counts files AND subdirectories — the rentries semantics
        max_files bounds upstream (rfiles + rsubdirs).  O(subtree) — the
        reference keeps rstats on every CInode for O(1); the walk is the
        honest simple form at this scale and only runs for dirs on a
        quota ancestor chain."""
        files = 0
        nbytes = 0
        todo = [ino]
        while todo:
            d = todo.pop()
            for entry in self.dirs.get(d, {}).values():
                files += 1
                if "remote" in entry:
                    continue
                if entry.get("type") == "dir":
                    todo.append(entry["ino"])
                else:
                    nbytes += int(entry.get("size", 0))
        return files, nbytes

    def _quota_ancestors(self, ino: int):
        """Yield (dir_ino, dir_inode) for each ancestor dir (incl. ino
        itself when a dir) carrying any quota xattr."""
        cur = ino
        seen = 0
        while cur != ROOT_INO and seen < 1000:
            seen += 1
            inode = self._inode_of(cur)
            if inode is None:
                return
            if inode.get("type") == "dir" and (
                self._quota_of(inode, "ceph.quota.max_files")
                or self._quota_of(inode, "ceph.quota.max_bytes")
            ):
                yield cur, inode
            bp = self.backptr.get(cur)
            if bp is None:
                return
            cur = bp[0]

    def _quota_realm(self, ino: int) -> tuple:
        """Identity of the quota realm containing `ino`: the tuple of
        quota-carrying ancestor dirs.  Renames across different realms
        are refused with EXDEV (upstream CephFS does the same), which is
        what keeps rename from teleporting usage past a quota."""
        return tuple(d for d, _i in self._quota_ancestors(ino))

    def _quota_check_create(self, parent: int) -> int:
        """0 ok, -122 when creating one more entry would cross a
        max_files quota on any ancestor."""
        for dino, inode in self._quota_ancestors(parent):
            limit = self._quota_of(inode, "ceph.quota.max_files")
            if limit:
                files, _b = self._subtree_usage(dino)
                if files + 1 > limit:
                    return -122
        return 0

    def _quota_check_size(self, ino: int, new_size) -> int:
        """0 ok, -122 when growing a file would cross a max_bytes quota
        on any ancestor."""
        if new_size is None:
            return 0
        inode = self._inode_of(ino)
        if inode is None:
            return 0
        delta = int(new_size) - int(inode.get("size", 0))
        if delta <= 0:
            return 0
        for dino, q in self._quota_ancestors(ino):
            limit = self._quota_of(q, "ceph.quota.max_bytes")
            if limit:
                _f, nbytes = self._subtree_usage(dino)
                if nbytes + delta > limit:
                    return -122
        return 0

    def _handle(self, op: str, a: dict, session: str | None = None):
        """Returns (retval, result).  Negative errnos follow the reference
        (-2 ENOENT, -17 EEXIST, -20 ENOTDIR, -21 EISDIR, -39 ENOTEMPTY)."""
        if op == "set_subtree":
            # `mds export`-analog: pin a ROOT-LEVEL directory to a rank.
            # Rank 0 is the authority for the subtree map (single writer)
            if self.rank != 0:
                return -116, {"rank": 0,
                              "addr": list(self._read_ranks().get(0) or [])}
            name = a["path"].strip("/")
            if "/" in name or not name:
                return -22, "subtree must be a top-level directory"
            entry = self.dirs.get(ROOT_INO, {}).get(name)
            if entry is None or entry.get("type") != "dir":
                return -2, None
            target = int(a["rank"])
            if target not in self._read_ranks():
                return -22, f"no active rank {target}"
            # flush OUR dirty state first so the new owner reads current
            # dirfrags when it adopts
            self._flush()
            subs = {
                k: int(v)
                for k, v in (self._obj_read("mds_subtrees") or {}).items()
            }
            subs[name] = target
            self._obj_write("mds_subtrees", subs)
            self._load_subtrees(force=True)
            return 0, {"path": f"/{name}", "rank": target}
        if op == "lookup":
            entries = self.dirs.get(a["parent"])
            if entries is None:
                return -2, None
            inode = self._resolve_entry(entries.get(a["name"]))
            if inode is None:
                return -2, None
            if inode.get("type") == "file":
                # fresh size: flush other sessions' buffered attrs
                self._sync_writers(inode["ino"], but=session)
                inode = self._resolve_entry(entries.get(a["name"]))
            return 0, inode
        if op == "getattr":
            inode = self._inode_of(a["ino"])
            if inode is None:
                return -2, None
            if inode.get("type") == "file":
                self._sync_writers(a["ino"], but=session)
                inode = self._inode_of(a["ino"])
            return 0, inode
        if op == "readdir":
            entries = self.dirs.get(a["ino"])
            if entries is None:
                return -20, None
            return 0, {
                n: self._resolve_entry(i) for n, i in sorted(entries.items())
            }
        if op == "link":
            # hardlink (reference: Server::handle_client_link — a remote
            # dentry referencing an existing file inode); directories are
            # refused like link(2) does
            parent, name, ino = a["parent"], a["name"], a["ino"]
            if parent not in self.dirs:
                return -20, None
            if name in self.dirs[parent]:
                return -17, None
            inode = self._inode_of(ino)
            if inode is None:
                return -2, None
            if inode["type"] == "dir":
                return -1, None  # EPERM
            if self._quota_check_create(parent) != 0:
                return -122, "directory quota exceeded (max_files)"
            self._commit({"e": "link_remote", "parent": parent,
                          "name": name, "ino": ino,
                          "nlink": inode.get("nlink", 1) + 1})
            return 0, self._inode_of(ino)
        if op in ("create", "mkdir"):
            parent = a["parent"]
            if parent not in self.dirs:
                return -20, None
            if a["name"] in self.dirs[parent]:
                return -17, self.dirs[parent][a["name"]]
            if self._quota_check_create(parent) != 0:
                return -122, "directory quota exceeded (max_files)"
            inode = {
                "ino": self._alloc_ino(),
                "type": "dir" if op == "mkdir" else "file",
                "size": 0,
                "mtime": time.time(),
            }
            if op == "create":
                inode["layout"] = a.get("layout") or {
                    "pool": self.data_pool,
                    "object_size": 1 << 22,
                    "stripe_unit": 1 << 16,
                    "stripe_count": 4,
                }
            self._commit({"e": "link", "parent": parent,
                          "name": a["name"], "inode": inode})
            return 0, inode
        if op in ("unlink", "rmdir"):
            parent, name = a["parent"], a["name"]
            entry = self.dirs.get(parent, {}).get(name)
            inode = self._resolve_entry(entry)
            if inode is None:
                return -2, None
            if inode.get("type") == "file":
                # buffered sizes must land before the returned inode is
                # used to purge data extents
                self._sync_writers(inode["ino"], but=session)
                inode = self._resolve_entry(entry)
            if op == "rmdir":
                if inode["type"] != "dir":
                    return -20, None
                if self.dirs.get(inode["ino"]):
                    return -39, None
            elif inode["type"] == "dir":
                return -21, None
            ev = {"e": "unlink", "parent": parent, "name": name}
            nlink_after = inode.get("nlink", 1) - 1
            if entry is not None and "remote" in entry:
                # stub removal: journal the primary's resulting nlink as
                # an ABSOLUTE value (idempotent replay)
                ev["stub_ino"] = entry["remote"]
                ev["primary_nlink"] = max(nlink_after, 1)
            elif (
                entry is not None and inode["type"] == "file"
                and nlink_after >= 1
            ):
                # primary dentry dies but hardlinks remain: promote a
                # deterministic remote stub to primary, with the FULL
                # promoted inode in the event so replay works even after
                # partial flushes (review r4)
                rem = sorted(self.remotes.get(inode["ino"], set()))
                if rem:
                    ev["promote"] = list(rem[0])
                    ev["promote_inode"] = dict(
                        inode, nlink=max(nlink_after, 1)
                    )
            self._commit(ev)
            if inode.get("type") == "file" and nlink_after <= 0:
                self._drop_ino_caps(inode["ino"])
            # nlink_after tells the client whether it holds the LAST
            # reference (purge) or a survivor keeps the data alive;
            # snap_seq makes that purge CLONE under a live snapshot
            # instead of destroying the at-snap view
            return 0, dict(inode, nlink_after=max(nlink_after, 0),
                           snap_seq=self._snap_seq_of(parent))
        if op == "rename":
            sdir, sname = a["srcdir"], a["sname"]
            if self._quota_realm(sdir) != self._quota_realm(a["dstdir"]):
                # crossing a quota realm would teleport usage past the
                # bound un-checked; the reference refuses with EXDEV
                return -18, "rename across quota realms"
            entry = self.dirs.get(sdir, {}).get(sname)
            inode = self._resolve_entry(entry)
            if inode is None:
                return -2, None
            dst = self.dirs.get(a["dstdir"])
            if dst is None:
                return -20, None
            dst_entry = dst.get(a["dname"])
            existing = self._resolve_entry(dst_entry)
            if existing is not None and existing.get("type") == "file":
                # replaced file's buffered size must land before its
                # inode is handed back for data purge
                self._sync_writers(existing["ino"], but=session)
                existing = self._resolve_entry(dst_entry)
            if existing is not None:
                if existing["ino"] == inode["ino"]:
                    return 0, {"moved": inode, "replaced": None}
                # POSIX replacement matrix: file over dir = EISDIR; dir
                # over file = ENOTDIR; dir over non-empty dir = ENOTEMPTY
                if existing["type"] == "dir":
                    if inode["type"] != "dir":
                        return -21, None
                    if self.dirs.get(existing["ino"]):
                        return -39, None
                elif inode["type"] == "dir":
                    return -20, None
            if inode["type"] == "dir":
                # reject moving a directory under itself (would detach the
                # subtree — reference: MDCache path-traversal rename checks)
                cur = a["dstdir"]
                while cur != ROOT_INO:
                    if cur == inode["ino"]:
                        return -22, None  # EINVAL
                    bp = self.backptr.get(cur)
                    if bp is None:
                        break
                    cur = bp[0]
            ev = {"e": "rename", "srcdir": sdir, "sname": sname,
                  "dstdir": a["dstdir"], "dname": a["dname"],
                  # full moved entry (primary inode or remote stub) so
                  # replay is self-contained against flushed-away sources
                  "moved_entry": dict(entry)}
            replaced_nlink_after = None
            if existing is not None:
                replaced_nlink_after = existing.get("nlink", 1) - 1
                if dst_entry is not None and "remote" in dst_entry:
                    ev["replaced_nlink"] = max(replaced_nlink_after, 1)
                elif (
                    existing["type"] == "file"
                    and replaced_nlink_after >= 1
                ):
                    rem = sorted(self.remotes.get(existing["ino"], set()))
                    if rem:
                        ev["promote_replaced"] = list(rem[0])
                        ev["promote_inode"] = dict(
                            existing, nlink=max(replaced_nlink_after, 1)
                        )
            self._commit(ev)
            # a replaced file's inode goes back to the caller so the
            # client holding the LAST reference can purge its data
            # objects (purge-queue analog); surviving hardlinks keep it
            replaced = None
            if existing is not None:
                # snap_seq: the destination realm governs the purge —
                # under a live snapshot the deletes must clone (same
                # contract as the unlink reply)
                replaced = dict(
                    existing, nlink_after=max(replaced_nlink_after, 0),
                    snap_seq=self._snap_seq_of(a["dstdir"]),
                )
                if (
                    existing.get("type") == "file"
                    and replaced_nlink_after <= 0
                ):
                    self._drop_ino_caps(existing["ino"])
            return 0, {"moved": inode, "replaced": replaced}
        if op == "setattr":
            inode = self._inode_of(a["ino"])
            if inode is None:
                return -2, None
            if self._quota_check_size(a["ino"], a.get("size")) != 0:
                return -122, "directory quota exceeded (max_bytes)"
            # a sync setattr from one session must not be overwritten by
            # another session's later cap flush of stale buffered attrs
            self._sync_writers(a["ino"], but=session)
            self._commit({"e": "setattr", "ino": a["ino"],
                          "size": a.get("size"), "mtime": a.get("mtime")})
            # and other sessions' cached attrs are stale now
            self._invalidate_readers(a["ino"], but=session)
            return 0, self._inode_of(a["ino"])
        if op == "setxattr":
            # value b64 (or None to remove); root has no dentry to carry
            # xattrs, like the reference refuses most root setattrs here
            ino = a["ino"]
            inode = None if ino == ROOT_INO else self._inode_of(ino)
            if inode is None:
                return -2, None
            if a.get("val") is None and a["name"] not in (
                inode.get("xattrs") or {}
            ):
                return -61, None  # ENODATA: removing a missing xattr
            self._commit({"e": "setxattr", "ino": ino,
                          "name": a["name"], "val": a.get("val")})
            # other sessions' cached attrs are stale now (same contract
            # as setattr — review r5)
            self._invalidate_readers(ino, but=session)
            return 0, self._inode_of(ino)
        if op == "getxattrs":
            inode = self._inode_of(a["ino"])
            if inode is None:
                return -2, None
            xattrs = dict(inode.get("xattrs") or {})
            if a.get("name") is not None:  # single-key fetch
                name = a["name"]
                return 0, ({name: xattrs[name]} if name in xattrs else {})
            return 0, xattrs
        if op == "mksnap":
            # reference: Server::handle_client_mksnap + SnapServer
            # allocation.  The at-snap NAMESPACE freezes in a manifest
            # object (relpath -> inode copy); at-snap DATA rides the
            # OSD's clone-on-write, driven by the realm seq clients
            # stamp on writes from here on.
            dino, name = int(a["ino"]), a["name"]
            inode = self._inode_of(dino)
            if inode is None or inode.get("type") != "dir":
                return -20, None
            if dino == ROOT_INO:
                return -22, "snapshot of the root is not allowed"
            if name in (inode.get("snaps") or {}):
                return -17, f"snapshot {name!r} exists"
            if not name or "/" in name or name.startswith("."):
                return -22, f"bad snapshot name {name!r}"
            # a subtree delegated to another rank under this dir would
            # make the manifest partial — refuse like cross-realm rename
            if self.rank == 0:
                for top, r in self._load_subtrees().items():
                    if r != self.rank:
                        ent = self.dirs.get(ROOT_INO, {}).get(top)
                        tino = ent and self._resolve_entry(ent)
                        if tino and self._is_under(tino["ino"], dino):
                            return -18, (f"subtree /{top} is on rank "
                                         f"{r}; snapshot there")
            self.snap_counter += 1  # noqa: CL2 — _handle runs under _lock (dispatch)
            sid = (self.rank << 20) | self.snap_counter
            # push the realm seq to every cap holder under the dir
            # BEFORE freezing the manifest: keep="" both flushes their
            # buffered sizes (fresh manifest) and delivers the seq, so
            # by the time the namespace freezes every acked writer
            # stamps its next data write and clones pre-snap bytes.
            # The window for a NON-acking writer is its revoke timeout.
            for cino in list(self.caps):
                if not self._is_under(cino, dino):
                    continue
                self._await_reconnect(cino)
                for sess in list(self.caps.get(cino, {})):
                    self._revoke_caps(cino, sess, "",
                                      attrs={"snap_seq": sid})
            manifest = {"": dict(inode)}
            for path, node in self._walk_subtree(dino):
                manifest[path] = dict(node)
            self._obj_write(f"snapmeta.{dino:x}.{sid:x}", manifest)
            self._commit({"e": "mksnap", "ino": dino, "name": name,
                          "snapid": sid, "created": time.time()})
            self._flush()  # counter + dirfrag durable with the manifest
            return 0, {"snapid": sid, "name": name}
        if op == "rmsnap":
            dino, name = int(a["ino"]), a["name"]
            inode = self._inode_of(dino)
            if inode is None or inode.get("type") != "dir":
                return -20, None
            s = (inode.get("snaps") or {}).get(name)
            if s is None:
                return -2, None
            # journal FIRST: a crash after the manifest delete but
            # before the event would leave a listed-but-unreadable
            # snapshot; the orphan manifest object is merely garbage
            self._commit({"e": "rmsnap", "ino": dino, "name": name})
            try:
                self._io.remove(f"snapmeta.{dino:x}.{int(s['snapid']):x}")
            except IOError:
                pass
            return 0, {"name": name}
        if op == "lssnap":
            inode = self._inode_of(int(a["ino"]))
            if inode is None or inode.get("type") != "dir":
                return -20, None
            return 0, dict(inode.get("snaps") or {})
        if op == "snapstat":
            manifest = self._obj_read(
                f"snapmeta.{int(a['ino']):x}.{int(a['snapid']):x}")
            if manifest is None:
                return -2, None
            node = manifest.get(a.get("rel", ""))
            return (0, node) if node is not None else (-2, None)
        if op == "snapls":
            manifest = self._obj_read(
                f"snapmeta.{int(a['ino']):x}.{int(a['snapid']):x}")
            if manifest is None:
                return -2, None
            rel = a.get("rel", "")
            if rel and rel not in manifest:
                return -2, None
            if rel and manifest[rel].get("type") != "dir":
                return -20, None
            prefix = f"{rel}/" if rel else ""
            out = {}
            for path, node in manifest.items():
                if path and path.startswith(prefix) \
                        and "/" not in path[len(prefix):]:
                    out[path[len(prefix):]] = node
            return 0, out
        if op == "open":
            inode = self._inode_of(a["ino"])
            if inode is None:
                return -2, None
            if inode["type"] == "dir":
                return -21, None
            want = a.get("want", "rw")
            if want == "rw" and any(
                self._quota_of(q, "ceph.quota.max_bytes")
                for _d, q in self._quota_ancestors(a["ino"])
            ):
                # under a byte quota, writes must stay SYNCHRONOUS so
                # the setattr path can enforce max_bytes — a w cap would
                # buffer sizes past the bound and flush them un-checked
                # (the reference's client enforces in its quota realm;
                # we centralize at the MDS).  Writers holding caps from
                # BEFORE the quota xattr landed keep them until reopen —
                # the documented enforcement window.
                want = "r"
            caps = self._grant_caps(
                inode["ino"], session, want
            )
            # grant may have flushed a writer: re-read the inode
            return 0, dict(self._inode_of(a["ino"]), caps=caps,
                           snap_seq=self._snap_seq_of(a["ino"]))
        return -95, f"unknown op {op!r}"  # EOPNOTSUPP

    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MClientSession):
            # build the ack under the lock, send it after release: the
            # messenger write blocks on the peer socket (CL1)
            reply = None
            with self._lock:
                if msg.op == "request_open":
                    self._sessions.add(msg.client)
                    self._session_conns[msg.client] = conn
                    reply = MClientSession(op="open", client=msg.client)
                elif msg.op == "request_close":
                    self._sessions.discard(msg.client)
                    self._session_conns.pop(msg.client, None)
                    # a closed session retires its completed-request set
                    # (reference: Session teardown) — without this the
                    # per-session caches grow with every client ever seen
                    self._reply_cache.pop(msg.client, None)
                    # and surrenders every capability it still holds
                    for ino, holders in self.caps.items():
                        if "w" in holders.get(msg.client, {}).get("caps", ""):
                            self._set_writer(ino, msg.client, False)
                        holders.pop(msg.client, None)
                    self._caps_cond.notify_all()
                    reply = MClientSession(op="close", client=msg.client)
            if reply is not None:
                conn.send_message(reply)
            return True
        if isinstance(msg, MClientCaps):
            with self._lock:
                holders = self.caps.get(msg.ino, {})
                ent = holders.get(msg.client)
                if msg.op == "flush":
                    # dirty writeback + revoke ack (the cap-flush): apply
                    # the buffered attrs only while the sender still holds
                    # w — a raced revoke already force-dropped it — or
                    # while it is a RECONNECTING writer from the previous
                    # incarnation (its w cap is recorded in the persisted
                    # sessionmap, not in memory)
                    recon = msg.client in (
                        self._reconnect.get(msg.ino) or []
                    )
                    attrs = msg.attrs or {}
                    if (
                        ((ent is not None and "w" in ent["caps"]) or recon)
                        and (attrs.get("size") is not None
                             or attrs.get("mtime") is not None)
                        and self._inode_of(msg.ino) is not None
                    ):
                        self._commit({
                            "e": "setattr", "ino": msg.ino,
                            "size": attrs.get("size"),
                            "mtime": attrs.get("mtime"),
                        })
                    if recon:
                        pend = self._reconnect.get(msg.ino, [])
                        if msg.client in pend:
                            pend.remove(msg.client)
                        if not pend:
                            self._reconnect.pop(msg.ino, None)
                        self._persist_writers()
                    # seq gate (advisor r4): the downgrade half of a flush
                    # only applies when it acks the CURRENT revoke — a
                    # delayed ack from an earlier revoke (e.g. after the
                    # 5s force-drop and a subsequent re-grant) must not
                    # clobber the newer grant and silently strip a writer
                    # that still buffers.  The attr flush above always
                    # applies (flushes are absolute-valued).  seq == 0 is
                    # NOT an ack: it is the client's reconnect flush
                    # (client.py _reconnect_flush), whose unconditional
                    # cap drop must keep working.  Reference:
                    # Locker::handle_client_caps drops stale-seq cap acks.
                    stale = (
                        ent is not None
                        and msg.cap_seq is not None
                        and 0 < msg.cap_seq < ent.get("seq", 0)
                    )
                    if ent is not None and not stale:
                        had_w = "w" in ent["caps"]
                        ent["caps"] = msg.caps or ""
                        if had_w and "w" not in ent["caps"]:
                            self._set_writer(msg.ino, msg.client, False)
                elif msg.op == "release":
                    if ent is not None and "w" in ent["caps"]:
                        self._set_writer(msg.ino, msg.client, False)
                    holders.pop(msg.client, None)
                self._caps_cond.notify_all()
            return True
        if isinstance(msg, MClientRequest):
            sess = msg.session or msg.src
            with self._lock:
                # track the session's live connection for cap revokes
                if sess in self._sessions:
                    self._session_conns[sess] = conn
                cache = self._reply_cache.setdefault(sess, OrderedDict())
                # LRU over SESSIONS too: clients that vanish without a
                # request_close (crash, connection loss) must not leak
                # their cache forever.  Only sessions no longer OPEN are
                # evicted — dropping a live session's cache would
                # re-expose it to the replay re-execution this exists
                # to prevent; all-open caches may exceed the soft cap.
                self._reply_cache.move_to_end(sess)
                while len(self._reply_cache) > 64:
                    victim = next(
                        (s for s in self._reply_cache
                         if s not in self._sessions), None)
                    if victim is None:
                        break
                    self._reply_cache.pop(victim)
                if msg.tid in cache:
                    rv, result = cache[msg.tid]
                else:
                    redirect = self._check_redirect(msg.op, msg.args or {})
                    if redirect is not None:
                        # NOT cached: after a takeover the same tid must
                        # re-execute here instead of replaying the stale
                        # redirect.  The reply rides the shared send below
                        # so the socket write happens outside _lock (CL1).
                        rv, result = -116, redirect
                    else:
                        try:
                            rv, result = self._handle(
                                msg.op, msg.args or {}, session=sess
                            )
                        except Exception as e:  # op bug must not kill the daemon
                            self.cct.dout(
                                "mds", 0, f"mds op {msg.op} failed: {e!r}"
                            )
                            rv, result = -5, repr(e)  # EIO
                        cache[msg.tid] = (rv, result)
                        while len(cache) > 512:
                            cache.popitem(last=False)
            conn.send_message(
                MClientReply(tid=msg.tid, retval=rv, result=result)
            )
            return True
        return False
