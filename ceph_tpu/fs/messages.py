"""MDS wire messages (reference: src/messages/MClientSession.h,
MClientRequest.h, MClientReply.h).  Type codes follow the reference's
CEPH_MSG_CLIENT_* numbering.
"""
from __future__ import annotations

from ..mon.messages import _JsonMessage
from ..msg.message import register_message


@register_message
class MClientSession(_JsonMessage):
    """Client <-> MDS session control (reference: MClientSession ops
    REQUEST_OPEN/OPEN/REQUEST_CLOSE/CLOSE)."""

    MSG_TYPE = 22  # CEPH_MSG_CLIENT_SESSION
    FIELDS = ("op", "client")


@register_message
class MClientRequest(_JsonMessage):
    """Metadata op to the MDS (reference: MClientRequest).

    op: lookup | getattr | readdir | create | mkdir | unlink | rmdir |
        rename | setattr | open
    args: op-specific {parent, name, ino, srcdir, sname, dstdir, dname,
        size, mtime, mode}.  `session` is a per-client-process id: the MDS
    keys a bounded reply cache on (session, tid) so resent requests after
    a connection reset are answered, not re-executed (the reference's
    completed-requests session tracking).
    """

    MSG_TYPE = 24  # CEPH_MSG_CLIENT_REQUEST
    FIELDS = ("tid", "op", "args", "session")


@register_message
class MClientReply(_JsonMessage):
    """reference: MClientReply — retval + op-specific result body."""

    MSG_TYPE = 26  # CEPH_MSG_CLIENT_REPLY
    FIELDS = ("tid", "retval", "result")


@register_message
class MClientCaps(_JsonMessage):
    """Capability traffic (reference: MClientCaps — CEPH_CAP_OP_GRANT /
    REVOKE / FLUSH / FLUSHSNAP_ACK family).

    op: "revoke" (MDS -> client: drop to `caps`, flush dirty state, ack)
        | "flush" (client -> MDS: dirty size/mtime writeback + revoke ack)
        | "release" (client -> MDS: closing, drop all caps on ino)
    caps: remaining cap string ("rw", "r", "") — the Fw/Fb vs Fr/Fc
    split collapses to w implies buffer, r implies cache.
    `attrs` carries the flushed {size, mtime} on "flush".

    `cap_seq` is the Locker's per-cap revoke sequence (reference:
    MClientCaps::seq) — deliberately NOT named `seq`: the framing attr
    `seq` is stamped with the connection sequence by send_message
    BEFORE the payload encodes, so a payload field of the same name is
    silently clobbered on the wire (cephlint CL6 field-shadow)."""

    MSG_TYPE = 23  # CEPH_MSG_CLIENT_CAPS
    FIELDS = ("op", "client", "ino", "caps", "cap_seq", "attrs")
