"""CephFS-analog filesystem layer (reference: src/mds + src/client;
SURVEY.md §2.6).

Architecture mirrors the reference's split: an MDS daemon owns the
namespace (metadata in a RADOS metadata pool, journaled), while clients
do file data I/O directly against the data pool through the striper —
the MDS never touches file bytes.
"""
from .client import FSClient
from .mds import MDSDaemon

__all__ = ["FSClient", "MDSDaemon"]
