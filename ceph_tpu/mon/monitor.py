"""Monitor daemon (reference: src/mon/Monitor.{h,cc}; SURVEY.md §2.5).

One Monitor = messenger + Elector + Paxos + PaxosServices (OSDMonitor).
The monmap is static for a cluster's lifetime (the reference can grow it;
vstart-style clusters here fix it at boot).  Peons forward nothing: a
command sent to a peon is NACKed with the leader's rank and the client
redials (the reference routes instead — same outcome, simpler machinery).

Subscriptions (reference: Monitor::handle_subscribe): a client subscribes
to "osdmap" from an epoch; every commit pushes the new full maps to all
subscribers.
"""
from __future__ import annotations

import queue
import threading
import time

from ..common.failpoint import FailpointCrash, failpoint
from ..common.lockdep import make_lock
from ..msg import Dispatcher, Messenger, MPing
from ..msg.messenger import POLICY_LOSSLESS_PEER
from ..osd.osdmap import OSDMap
from ..store.kv import KeyValueDB, MemKV
from .elector import Elector
from .messages import (
    MMonCommand,
    MMonCommandAck,
    MMonElection,
    MMonPaxos,
    MMonSubscribe,
    MOSDAlive,
    MOSDBoot,
    MOSDFailure,
    MOSDMapMsg,
)
from .osd_monitor import OSDMonitor

STATE_PROBING = "probing"
STATE_ELECTING = "electing"
STATE_LEADER = "leader"
STATE_PEON = "peon"


class MonMap:
    """reference: src/mon/MonMap.h — name → rank (sorted) + address, plus
    the cluster fsid that fences off foreign-cluster daemons."""

    def __init__(self, addrs: dict[str, tuple[str, int]], fsid: str | None = None):
        import uuid

        self.addrs = dict(addrs)
        self._names = sorted(addrs)  # rank order = sorted names
        self.fsid = fsid or str(uuid.uuid4())

    def ranks(self) -> list[int]:
        return list(range(len(self._names)))

    def name_of(self, rank: int) -> str:
        return self._names[rank]

    def rank_of(self, name: str) -> int | None:
        try:
            return self._names.index(name)
        except ValueError:
            return None

    def addr_of(self, rank: int) -> tuple[str, int]:
        return self.addrs[self._names[rank]]

    def size(self) -> int:
        return len(self._names)


class Monitor(Dispatcher):
    def __init__(
        self,
        cct,
        name: str,  # bare mon name, e.g. "a"
        monmap: MonMap,
        store: KeyValueDB | None = None,
        initial_osdmap: OSDMap | None = None,
    ):
        self.cct = cct
        self.name = name
        self.monmap = monmap
        rank = monmap.rank_of(name)
        if rank is None:
            raise ValueError(f"mon {name!r} not in monmap")
        self.rank = rank
        self.store = store if store is not None else MemKV()
        self.state = STATE_PROBING
        self.leader_rank: int | None = None
        self.quorum: list[int] = []
        self.messenger = Messenger.create(cct, f"mon.{name}")
        self.messenger.default_policy = POLICY_LOSSLESS_PEER
        self.messenger.add_dispatcher(self)
        self.messenger.auth_gen_provider = lambda: (
            self.osdmon.osdmap.auth_gens.get("mon", 1)
            if getattr(self, "osdmon", None) is not None
            and self.osdmon.osdmap is not None else 1
        )
        self.messenger.bind(monmap.addr_of(rank))
        self.elector = Elector(self)
        from .paxos import Paxos

        self.paxos = Paxos(self, self.store)
        self.osdmon = OSDMonitor(self, initial_osdmap)
        # conn -> next osdmap epoch wanted
        self._subs: dict[object, int] = {}
        self._subs_lock = make_lock("mon::subs")
        # (client, session, tid) -> completed command result, so a retried
        # command (ack lost / slow proposal) is answered, not re-executed
        self._cmd_results: dict[tuple, tuple[int, object]] = {}
        self._cmd_inflight: set[tuple] = set()
        self._cmd_lock = make_lock("mon::cmd")
        # All cross-connection sends go through sender threads.  Paxos
        # and elector handlers run on connection reader threads (holding
        # that connection's session lock) and take subsystem locks; if
        # those subsystems also sent directly while holding their locks,
        # the two lock orders would deadlock (session→subsystem vs
        # subsystem→session).  Queueing breaks the cycle.  One queue+thread
        # PER PEER (plus one for subscriber publishes): a single shared
        # sender dialing a dead-but-not-refusing peer would stall every
        # queued election/paxos message behind a 10 s connect timeout,
        # livelocking quorum formation (advisor r1 finding).
        self._sendqs: dict[object, "queue.Queue"] = {}
        self._send_threads: list[threading.Thread] = []
        self._sendq_lock = make_lock("mon::sendq")
        # serializes election-outcome state writes against shutdown's
        # reset: win/lose_election (reader threads) vs shutdown
        self._state_lock = make_lock("mon::state")
        self._tick_thread: threading.Thread | None = None
        self._stop_event = threading.Event()

    @property
    def _stopped(self) -> bool:
        return self._stop_event.is_set()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.messenger.start()
        self.elector.start_election()
        self._tick_thread = threading.Thread(
            target=self._tick_loop, name=f"mon.{self.name}-tick", daemon=True
        )
        self._tick_thread.start()

    def shutdown(self) -> None:
        self._stop_event.set()
        # a stopped mon must not keep reporting itself leader: harness
        # code (LocalCluster._leader) and peers probing state would
        # otherwise keep consulting a corpse's stale map view.  Under
        # _state_lock AFTER setting the stop event: an election outcome
        # that raced past the event check holds the lock while writing,
        # so this reset strictly follows it — and any later outcome sees
        # the event and returns
        with self._state_lock:
            self.state = STATE_PROBING
            self.leader_rank = None
        try:
            self.elector.stop()
        except Exception as e:
            self.cct.dout("mon", 0,
                          f"mon.{self.name} elector stop raised "
                          f"(continuing teardown): {e!r}")
        with self._sendq_lock:
            for q in self._sendqs.values():
                q.put(None)
            threads = list(self._send_threads)
        if (self._tick_thread is not None
                and self._tick_thread is not threading.current_thread()):
            # current_thread guard: an injected tick crash shuts the mon
            # down from the tick thread itself (joining self raises).
            # Joined BEFORE the messenger goes away: the tick loop
            # sends through it (teardown reverses bring-up)
            self._tick_thread.join(timeout=5)
        try:
            self.messenger.shutdown()
        except Exception as e:
            self.cct.dout("mon", 0,
                          f"mon.{self.name} messenger shutdown raised: "
                          f"{e!r}")
        for t in threads:
            t.join(timeout=5)
        close = getattr(self.store, "close", None)
        if close:
            try:
                close()
            except Exception as e:
                self.cct.dout("mon", 0,
                              f"mon.{self.name} store close raised: {e!r}")
        # the context goes last: its admin socket serves debug commands
        # right up until the daemon is gone
        self.cct.shutdown()

    def _sendq_for(self, key) -> "queue.Queue":
        """Per-peer (or 'publish') queue, sender thread created lazily."""
        with self._sendq_lock:
            q = self._sendqs.get(key)
            if q is None:
                q = queue.Queue()
                if self._stopped:
                    # racing with shutdown(): park messages in a dead queue
                    # instead of spawning a thread nobody will ever join
                    return q
                self._sendqs[key] = q
                t = threading.Thread(
                    target=self._send_loop, args=(key, q),
                    name=f"mon.{self.name}-send-{key}", daemon=True,
                )
                self._send_threads.append(t)
                t.start()
            return q

    def _send_loop(self, key, q: "queue.Queue") -> None:
        while True:
            try:
                # bounded wait: a mon killed without draining its send
                # queues (thrasher hard-kill) never enqueues the None
                # sentinel, and an unbounded get() would leak this
                # thread; the timeout re-checks _stopped instead
                item = q.get(timeout=5.0)
            except queue.Empty:
                if self._stopped:
                    return
                continue
            if item is None or self._stopped:
                return
            try:
                if key == "publish":
                    self._publish_osdmap_now()
                else:
                    self.messenger.connect(
                        self.monmap.addr_of(key)
                    ).send_message(item)
            except (OSError, ConnectionError):
                pass  # elections / paxos timeouts handle the silence
            except Exception as e:
                self.cct.dout("mon", 0, f"mon.{self.name} send failed: {e!r}")

    def _tick_loop(self) -> None:
        interval = self.cct.conf.get("mon_tick_interval")
        while not self._stop_event.wait(interval):
            if self._stopped:
                return
            try:
                self.tick()
            except FailpointCrash:
                # injected daemon death: a dead tick loop alone would
                # leave a ZOMBIE that still answers election proposes
                # (and, as lowest rank, keeps winning while never
                # driving maps) — take the whole mon down so the quorum
                # genuinely re-forms without it
                self.cct.dout("mon", 0,
                              f"mon.{self.name} crashed (injected)")
                try:
                    self.shutdown()
                except Exception as e:
                    self.cct.dout("mon", 0,
                                  f"mon.{self.name} crash-shutdown "
                                  f"raised: {e!r}")
                return
            except Exception as e:
                self.cct.dout("mon", 0, f"mon.{self.name} tick failed: {e!r}")

    def tick(self) -> None:
        # "mon.tick": delay simulates a stalled mon (missed lease-probe
        # windows); error skips the tick via _tick_loop's handler
        failpoint("mon.tick", cct=self.cct, entity=f"mon.{self.name}")
        # one consistent snapshot under mon::state — the tick thread
        # racing election-outcome writes read state/leader_rank unlocked
        # (cephrace CR1 Monitor.leader_rank)
        with self._state_lock:
            state, leader_rank = self.state, self.leader_rank
        if state == STATE_LEADER:
            self.osdmon.tick()
        elif state == STATE_PEON and leader_rank is not None:
            # leader liveness probe: a dead leader triggers re-election
            # (reference: peons' lease timeout; SURVEY.md §5.3)
            try:
                conn = self.messenger.connect(
                    self.monmap.addr_of(leader_rank)
                )
                conn.send_message(MPing("leader-probe"))
            except (OSError, ConnectionError):
                self.cct.dout("mon", 1, f"mon.{self.name}: leader unreachable")
                self.elector.start_election()

    # -- election plumbing (Elector callbacks) ----------------------------
    def majority(self) -> int:
        return self.monmap.size() // 2 + 1

    def other_ranks(self) -> list[int]:
        return [r for r in self.monmap.ranks() if r != self.rank]

    def rank_of(self, entity_name: str) -> int | None:
        if not entity_name.startswith("mon."):
            return None
        return self.monmap.rank_of(entity_name[4:])

    def set_electing(self) -> None:
        # every other state write serializes under mon::state; this one
        # ran bare (under only the elector's lock) until cephrace caught
        # it racing an is_leader probe
        with self._state_lock:
            self.state = STATE_ELECTING

    def win_election(self, epoch: int, quorum: list[int]) -> None:
        with self._state_lock:
            # a victory dispatched on a reader thread mid-shutdown must
            # not resurrect the corpse as leader: shutdown sets the stop
            # event BEFORE taking this lock for its reset, so either we
            # see the event here, or our writes land before the reset
            if self._stop_event.is_set():
                return
            self.state = STATE_LEADER
            self.leader_rank = self.rank
            self.quorum = quorum
        self.cct.dout(
            "mon", 1, f"mon.{self.name} won election epoch {epoch}, quorum {quorum}"
        )
        # leader_init blocks on the collect round; run it off the elector's
        # calling thread (often a reader holding a session lock)
        threading.Thread(  # noqa: CL13 — fire-and-forget by design: leader_init must leave the elector's reader thread (session-lock order) and checks _stopped itself
            target=self._leader_init_async, args=(epoch,),
            name=f"mon.{self.name}-leader-init", daemon=True,
        ).start()

    def _leader_init_async(self, epoch: int) -> None:
        try:
            if self.paxos.leader_init() and self.is_leader():
                self.osdmon.refresh()
                self.osdmon.on_elected_leader()
                self.publish_osdmap()
        except Exception as e:
            self.cct.dout("mon", 0, f"leader init failed: {e!r}")

    def lose_election(self, epoch: int, leader: int, quorum: list[int]) -> None:
        with self._state_lock:
            if self._stop_event.is_set():
                return
            self.state = STATE_PEON
            self.leader_rank = leader
            self.quorum = quorum

    def is_leader(self) -> bool:
        # under mon::state: election outcomes and shutdown write state
        # under this lock, and an unlocked probe here was the first race
        # cephrace caught in a live run (CR1 Monitor.state)
        with self._state_lock:
            return self.state == STATE_LEADER

    def send_mon(self, rank: int, msg) -> None:
        """Queue a message to a peer mon; safe to call while holding any
        subsystem lock (the sender thread does the socket work)."""
        if hasattr(msg, "fsid"):
            msg.fsid = self.monmap.fsid
        self._sendq_for(rank).put(msg)

    # -- paxos callback ----------------------------------------------------
    def on_paxos_commit(self, version: int) -> None:
        self.osdmon.refresh()
        self.publish_osdmap()

    # -- subscriptions -----------------------------------------------------
    def publish_osdmap(self) -> None:
        """Queue a push of new epochs to subscribers (runs on the sender
        thread — callers may hold the paxos lock)."""
        self._sendq_for("publish").put(True)

    def _publish_osdmap_now(self) -> None:
        cur = self.osdmon.epoch
        if cur == 0:
            return
        with self._subs_lock:
            subs = list(self._subs.items())
        for conn, want in subs:
            if want > cur:
                continue
            maps = {}
            for e in range(want, cur + 1):
                j = self.osdmon.get_map_json(e)
                if j is not None:
                    maps[str(e)] = j
            if not maps:
                continue
            try:
                conn.send_message(MOSDMapMsg(maps=maps))
                with self._subs_lock:
                    if conn in self._subs:
                        self._subs[conn] = cur + 1
            except (OSError, ConnectionError):
                with self._subs_lock:
                    self._subs.pop(conn, None)

    # -- dispatch ----------------------------------------------------------
    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, (MMonElection, MMonPaxos)):
            # fsid fence: a zombie mon of another cluster incarnation that
            # reconnects to a reused port must not poison elections/paxos
            # (reference: every daemon checks the cluster fsid)
            if msg.fsid != self.monmap.fsid:
                return True
        if isinstance(msg, MMonElection):
            self.elector.handle(conn, msg)
        elif isinstance(msg, MMonPaxos):
            self.paxos.handle(conn, msg)
        elif isinstance(msg, MMonCommand):
            self._handle_command(conn, msg)
        elif isinstance(msg, MMonSubscribe):
            self._handle_subscribe(conn, msg)
        elif isinstance(msg, MOSDBoot):
            if self.is_leader():
                self.osdmon.handle_boot(msg.osd, (msg.host, msg.port))
            else:
                self._forward_to_leader(msg)
        elif isinstance(msg, MOSDFailure):
            # pin the original reporter before any peon→leader forward so
            # corroboration counts distinct OSDs, not forwarding mons
            if not msg.reporter:
                msg.reporter = msg.src
            if self.is_leader():
                self.osdmon.handle_failure(msg.target, msg.reporter)
            else:
                self._forward_to_leader(msg)
        elif isinstance(msg, MOSDAlive):
            # same reporter pinning + leader routing as MOSDFailure: the
            # retraction must drain the LEADER's corroboration set, and
            # must count as the original OSD, not a forwarding peon
            if not msg.reporter:
                msg.reporter = msg.src
            if self.is_leader():
                self.osdmon.handle_alive(msg.target, msg.reporter)
            else:
                self._forward_to_leader(msg)
        elif isinstance(msg, MPing):
            pass
        else:
            return False
        return True

    def _forward_to_leader(self, msg) -> None:
        """Peons route daemon reports to the leader (reference: Monitor
        forward_request_leader).  Payload fields carry everything the
        OSDMonitor needs (incl. MOSDFailure.reporter, pinned above), so a
        fresh message with copied fields is a faithful forward."""
        with self._state_lock:
            leader = self.leader_rank
        if leader is None or leader == self.rank:
            return
        fresh = type(msg)(**{f: getattr(msg, f) for f in msg.FIELDS})
        self.send_mon(leader, fresh)

    def ms_handle_reset(self, conn) -> None:
        with self._subs_lock:
            self._subs.pop(conn, None)

    def _handle_subscribe(self, conn, msg: MMonSubscribe) -> None:
        what = msg.what or {}
        if "osdmap" in what:
            with self._subs_lock:
                self._subs[conn] = int(what["osdmap"]) or 1
            self.publish_osdmap()

    # -- commands ----------------------------------------------------------
    def _handle_command(self, conn, msg: MMonCommand) -> None:
        cmd = msg.cmd or {}
        prefix = cmd.get("prefix", "")
        # dedup key includes the per-client random session id: two client
        # processes sharing the default entity name ('client.admin') and
        # tid counters starting at 0 must not collide (advisor r1 finding)
        key = (msg.src, msg.session, msg.tid)
        with self._cmd_lock:
            done = self._cmd_results.get(key)
            if done is None and key in self._cmd_inflight:
                return  # retry of a command still executing; first ack wins
            if done is None:
                self._cmd_inflight.add(key)
        if done is not None:
            try:
                conn.send_message(
                    MMonCommandAck(tid=msg.tid, retval=done[0], result=done[1])
                )
            except (OSError, ConnectionError):
                pass
            return
        # answerable by any mon, quorum or not
        if prefix == "mon stat":
            retval, result = 0, {
                "name": self.name, "rank": self.rank, "state": self.state,
                "leader": self.leader_rank, "quorum": self.quorum,
                "monmap": {
                    n: list(a) for n, a in self.monmap.addrs.items()
                },
            }
        elif not self.is_leader():
            retval, result = -307, {
                "error": "not leader",
                "leader": self.leader_rank,
                "leader_addr": (
                    list(self.monmap.addr_of(self.leader_rank))
                    if self.leader_rank is not None else None
                ),
            }
        elif prefix in ("status", "health", "health detail"):
            # `health detail` is the same payload — checks carry their
            # `detail` lines always; the CLI decides how much to render
            retval, result = 0, self._status()
        elif self.osdmon.osdmap is None:
            # elected but the initial map hasn't committed yet
            retval, result = -11, "cluster still forming, retry"
        elif prefix.startswith("config-key ") \
                or prefix.startswith("config "):
            try:
                retval, result = self._handle_config_command(cmd)
            except Exception as e:
                self.cct.dout("mon", 0, f"command {prefix!r} failed: {e!r}")
                retval, result = -22, f"command failed: {e}"
        else:
            try:
                retval, result = self.osdmon.handle_command(cmd)
            except Exception as e:
                self.cct.dout("mon", 0, f"command {prefix!r} failed: {e!r}")
                retval, result = -22, f"command failed: {e}"
        with self._cmd_lock:
            self._cmd_inflight.discard(key)
            # transient NACKs aren't final results; let retries re-run
            if retval not in (-307, -11):
                self._cmd_results[key] = (retval, result)
                while len(self._cmd_results) > 256:
                    self._cmd_results.pop(next(iter(self._cmd_results)))
        try:
            conn.send_message(
                MMonCommandAck(tid=msg.tid, retval=retval, result=result)
            )
        except (OSError, ConnectionError):
            pass

    # -- central config + config-key store (reference: MonMonmap-era
    # ConfigMonitor src/mon/ConfigMonitor.cc and the config-key KV of
    # src/mon/ConfigKeyService.cc; both paxos-replicated) --------------
    _CFG_SECTIONS = ("global", "mon", "osd", "mds", "mgr", "client")

    def _handle_config_command(self, cmd: dict) -> tuple[int, object]:
        prefix = cmd.get("prefix", "")
        if prefix == "config-key set":
            key = cmd.get("key", "")
            if not key:
                return -22, "key required"
            val = cmd.get("val", "")
            ok = self.paxos.propose([(1, f"ck/{key}",
                                      str(val).encode())])
            return (0, f"set {key}") if ok else (-110, "timed out")
        if prefix == "config-key get":
            v = self.store.get(f"ck/{cmd.get('key', '')}")
            return (0, v.decode()) if v is not None else (-2, "no key")
        if prefix == "config-key rm":
            ok = self.paxos.propose([(2, f"ck/{cmd.get('key', '')}",
                                      b"")])
            return (0, "removed") if ok else (-110, "timed out")
        if prefix == "config-key ls":
            return 0, sorted(
                k[len("ck/"):] for k, _v in self.store.iterate("ck/"))
        if prefix == "config-key exists":
            v = self.store.get(f"ck/{cmd.get('key', '')}")
            return (0, "exists") if v is not None else (-2, "no key")
        if prefix == "config set":
            who = cmd.get("who", "")
            name = cmd.get("name", "")
            base = who.split(".", 1)[0]
            if base not in self._CFG_SECTIONS:
                return -22, f"bad section {who!r}"
            try:
                self.cct.conf.table.get(name)
            except KeyError:
                return -2, f"unknown option {name!r}"
            ok = self.paxos.propose([
                (1, f"config/{who}/{name}",
                 str(cmd.get("value", "")).encode()),
            ])
            return (0, f"{who}/{name} set") if ok \
                else (-110, "timed out")
        if prefix == "config rm":
            ok = self.paxos.propose([
                (2, f"config/{cmd.get('who', '')}/"
                    f"{cmd.get('name', '')}", b""),
            ])
            return (0, "removed") if ok else (-110, "timed out")
        if prefix == "config dump":
            out = []
            for k, v in self.store.iterate("config/"):
                who, _, name = k[len("config/"):].rpartition("/")
                out.append({"section": who, "name": name,
                            "value": v.decode()})
            return 0, sorted(out, key=lambda e: (e["section"],
                                                 e["name"]))
        if prefix == "config get":
            # entity view: global < type section < exact daemon id —
            # the same precedence the daemon applies at boot
            who = cmd.get("who", "")
            base = who.split(".", 1)[0]
            out: dict[str, str] = {}
            for section in ("global", base, who):
                if not section:
                    continue
                for k, v in self.store.iterate(f"config/{section}/"):
                    out[k.rsplit("/", 1)[1]] = v.decode()
            return 0, out
        return -95, f"unknown config command {prefix!r}"

    def _status(self) -> dict:
        """reference: `ceph -s` (src/mon/Monitor.cc get_cluster_status +
        health checks from src/mon/health_check.h)."""
        osd = self.osdmon._stat()
        checks = {}
        m = self.osdmon.osdmap
        if m is not None:
            down = [
                o for o in range(m.max_osd)
                if m.exists(o) and not m.is_up(o)
            ]
            if down:
                checks["OSD_DOWN"] = {
                    "severity": "HEALTH_WARN",
                    "message": f"{len(down)} osds down",
                    "osds": down,
                }
            if m.flags & {"noout", "nodown", "noup"}:
                checks["OSDMAP_FLAGS"] = {
                    "severity": "HEALTH_WARN",
                    "message": f"flags {sorted(m.flags)} set",
                }
            full = sorted(
                p.name for p in m.pools.values()
                if "full_quota" in getattr(p, "flags", ())
            )
            if full:
                # reference: POOL_FULL health check from pool quota flags
                checks["POOL_FULL"] = {
                    "severity": "HEALTH_WARN",
                    "message": f"{len(full)} pool(s) reached quota: "
                               f"{', '.join(full)}",
                    "pools": full,
                }
            untagged = sorted(
                p.name for p in m.pools.values()
                if not p.application and p.tier_of < 0
            )
            if untagged:
                # reference: POOL_APP_NOT_ENABLED (mgr health checks) —
                # cache tiers inherit their base pool's application
                checks["POOL_APP_NOT_ENABLED"] = {
                    "severity": "HEALTH_WARN",
                    "message": f"{len(untagged)} pool(s) have no "
                               f"application enabled: "
                               f"{', '.join(untagged)}",
                    "pools": untagged,
                }
            no_rep = sorted(
                p.name for p in m.pools.values()
                if sum(1 for o in range(m.max_osd)
                       if m.exists(o) and m.is_up(o) and m.is_in(o))
                < p.min_size
            )
            if no_rep:
                # reference: PG_AVAILABILITY — too few live OSDs to meet
                # a pool's write quorum anywhere
                checks["PG_AVAILABILITY"] = {
                    "severity": "HEALTH_WARN",
                    "message": f"{len(no_rep)} pool(s) below min_size "
                               f"capacity: {', '.join(no_rep)}",
                    "pools": no_rep,
                }
        # usage + pg-state summary from the mgr digest, when one has
        # arrived (reference: `ceph -s` data/pgs sections via PGMap)
        usage = {}
        pgs_by_state: dict[str, int] = {}
        progress_out: dict | None = None
        ts_digest = getattr(self.osdmon, "mgr_digest", None)
        # a dead mgr's last digest must not masquerade as current
        # forever: past the stale-report age, drop the sections (the
        # missing lines in `ceph -s` ARE the signal the mgr is gone)
        max_age = self.cct.conf.get("mgr_stale_report_age")
        if ts_digest is not None \
                and time.monotonic() - ts_digest[0] <= max_age:
            digest = ts_digest[1]
            slow = digest.get("slow_ops") or {}
            if slow:
                # reference: the SLOW_OPS health warning from optracker
                # complaint counts streamed through the mgr.  The count
                # is the OSDs' STICKY count (in-flight + recently
                # completed slow), and the detail lines name each op's
                # dominant stage (cephmeter forensics)
                n = sum(slow.values())
                slow_detail = digest.get("slow_ops_detail") or {}
                checks["SLOW_OPS"] = {
                    "severity": "HEALTH_WARN",
                    "message": f"{n} slow ops on "
                               f"{', '.join(sorted(slow))}",
                    "daemons": sorted(slow),
                    "detail": [
                        f"{d}: {line}"
                        for d in sorted(slow)
                        for line in (slow_detail.get(d) or [])
                    ][:12],
                }
            backend = digest.get("backend_health") or {}
            deg = sorted(
                d for d, bh in backend.items()
                if (bh.get("sentinel") or {}).get("state") == "degraded"
            )
            if deg:
                # the accelerator analog of DEVICE_HEALTH: the backend
                # sentinel latched `degraded` on these daemons — kernels
                # are being served by the fallback path, perf numbers
                # reflect the fallback silicon (docs/observability.md)
                checks["TPU_BACKEND_DEGRADED"] = {
                    "severity": "HEALTH_WARN",
                    "message": f"TPU backend degraded on "
                               f"{len(deg)} daemon(s): "
                               f"{', '.join(deg)}",
                    "daemons": deg,
                    "detail": [
                        f"{d}: "
                        f"{(backend[d].get('sentinel') or {}).get('reason')}"
                        f" (since "
                        f"{(backend[d].get('sentinel') or {}).get('since')})"
                        for d in deg
                    ],
                }
            latched = sorted(d for d, bh in backend.items()
                             if bh.get("fallback"))
            if latched:
                # a codec latched its XLA fallback (one-shot Pallas
                # failure): traffic is served, numbers lie about the
                # silicon — alert until cleared (clear_kernel_fallback)
                details = []
                for d in latched:
                    for kern, rec in sorted(
                            (backend[d].get("fallback") or {}).items()):
                        details.append(
                            f"{d}: {kern} {rec.get('from')} -> "
                            f"{rec.get('to')} ({rec.get('reason')})")
                checks["KERNEL_FALLBACK_LATCHED"] = {
                    "severity": "HEALTH_WARN",
                    "message": f"kernel fallback latched on "
                               f"{len(latched)} daemon(s): "
                               f"{', '.join(latched)}",
                    "daemons": latched,
                    "detail": details,
                }
            # cephheal: degraded-redundancy + stalled-recovery checks
            # from the pg_info counts and the progress-module snapshot
            # the digest now carries (docs/observability.md)
            pg_info = digest.get("pg_info") or {}
            deg_pgs = {
                pgid: int(info.get("degraded") or 0)
                for pgid, info in pg_info.items()
                if int(info.get("degraded") or 0) > 0
            }
            if deg_pgs:
                # reference: PG_DEGRADED ("Degraded data redundancy")
                total_deg = sum(deg_pgs.values())
                worst = sorted(deg_pgs.items(), key=lambda kv: -kv[1])
                checks["PG_DEGRADED"] = {
                    "severity": "HEALTH_WARN",
                    "message": f"Degraded data redundancy: "
                               f"{total_deg} object copies degraded, "
                               f"{len(deg_pgs)} pg(s) degraded",
                    "pgs": sorted(deg_pgs),
                    "detail": [
                        f"pg {pgid} is degraded ({n} object copies)"
                        for pgid, n in worst[:6]
                    ],
                }
            prog = digest.get("progress") or {}
            stalled = prog.get("stalled") or []
            failing = prog.get("failing") or {}
            if stalled or failing:
                # recovery is owed (degraded > 0) but the drain rate is
                # ~zero past the grace, or a PG's recovery pass raises
                # every tick — either way the self-heal plane is stuck,
                # which a degraded count alone cannot distinguish from
                # slow-but-progressing recovery
                names = sorted({e["pgid"] for e in stalled}
                               | set(failing))
                detail = [
                    f"pg {e['pgid']}: {e['degraded']} object copies "
                    f"degraded, no progress for {e['stalled_for']}s"
                    for e in stalled[:6]
                ] + [
                    f"pg {pgid}: recovery failing on {rec.get('daemon')}"
                    f" ({rec.get('count')} consecutive ticks): "
                    f"{rec.get('error')}"
                    for pgid, rec in sorted(failing.items())[:6]
                ]
                checks["RECOVERY_STALLED"] = {
                    "severity": "HEALTH_WARN",
                    "message": f"recovery stalled on {len(names)} "
                               f"pg(s): {', '.join(names[:8])}",
                    "pgs": names,
                    "detail": detail,
                }
            if prog.get("events") is not None:
                progress_out = {
                    "events": prog.get("events") or [],
                    "stalled": stalled,
                }
            # cephplace: data-distribution imbalance from the placement
            # module's skew snapshot — raised only while the balancer is
            # idle or off (an active balancer mid-convergence would just
            # flap the check), cleared when deviations converge under
            # mgr_placement_max_deviation
            pl = digest.get("placement") or {}
            imbalanced = pl.get("imbalanced") or []
            if imbalanced and not pl.get("balancer_busy"):
                names = [e.get("pool") for e in imbalanced]
                thr = pl.get("max_deviation_threshold")
                checks["PG_IMBALANCE"] = {
                    "severity": "HEALTH_WARN",
                    "message": f"{len(imbalanced)} pool(s) exceed the "
                               f"placement deviation bound ({thr} PG "
                               f"shards) with an idle balancer: "
                               f"{', '.join(map(str, names))}",
                    "pools": names,
                    "detail": [
                        f"pool {e.get('pool')!r}: max deviation "
                        f"{e.get('max_deviation')} PG shards (stddev "
                        f"{e.get('stddev')}, score {e.get('score')})"
                        for e in imbalanced[:6]
                    ],
                }
            st = (digest.get("df") or {}).get("stats") or {}
            usage = {
                "total_bytes": st.get("total_bytes", 0),
                "total_used_raw_bytes": st.get("total_used_raw_bytes", 0),
                "total_avail_bytes": st.get("total_avail_bytes", 0),
            }
            for info in (digest.get("pg_info") or {}).values():
                s = info.get("state", "unknown")
                pgs_by_state[s] = pgs_by_state.get(s, 0) + 1
        return {
            "health": {
                "status": "HEALTH_WARN" if checks else "HEALTH_OK",
                "checks": checks,
            },
            "quorum": self.quorum,
            "leader": self.leader_rank,
            "osdmap": osd,
            "usage": usage,
            "pgs_by_state": pgs_by_state,
            # cephheal: in-flight recovery events for the `ceph status`
            # one-line progress bar (None = no progress data yet)
            "progress": progress_out,
            "paxos": {
                "version": self.paxos.last_committed,
                "pn": self.paxos.accepted_pn,
            },
        }
