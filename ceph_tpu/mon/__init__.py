"""ceph_tpu.mon — control plane (reference: src/mon; SURVEY.md §2.5).

Monitors hold the authoritative cluster maps, replicated across the quorum
by single-decree Paxos over the MonitorDBStore (here: LogKV/MemKV).  The
OSDMonitor is the OSDMap authority: EC profile validation (instantiating
through the erasure-code registry, exactly how `plugin=jax` is vetted at
`osd erasure-code-profile set`), pool creation with CRUSH rule synthesis,
failure-report corroboration → down, and the down→out timer.  MonClient is
the daemon/client session: commands, map subscriptions, boot.
"""
from .mon_client import MonClient
from .monitor import MonMap, Monitor

__all__ = ["MonClient", "MonMap", "Monitor"]
