"""Control-plane message types (reference: src/messages/MMonElection.h,
MMonPaxos.h, MMonCommand.h, MMonSubscribe.h, MOSDBoot.h, MOSDFailure.h,
MOSDMap.h).  JSON-bodied where the reference uses rich structs — the
framing/crc/session machinery below them is identical either way.
"""
from __future__ import annotations

import json

from ..common.buffer import BufferList, BufferListIterator
from ..msg.message import Message, register_message


class _JsonMessage(Message):
    """Base for messages whose body is one JSON object."""

    FIELDS: tuple[str, ...] = ()

    def __init__(self, **kw):
        super().__init__()
        for f in self.FIELDS:
            setattr(self, f, kw.get(f))

    def encode_payload(self, bl: BufferList) -> None:
        bl.append_str(json.dumps({f: getattr(self, f) for f in self.FIELDS}))

    def decode_payload(self, it: BufferListIterator) -> None:
        d = json.loads(it.get_str())
        for f in self.FIELDS:
            setattr(self, f, d.get(f))

    def __repr__(self):
        body = " ".join(f"{f}={getattr(self, f)!r}" for f in self.FIELDS)
        return f"<{type(self).__name__} {body}>"


@register_message
class MMonElection(_JsonMessage):
    """reference: MMonElection — op in {propose, ack, victory}."""

    MSG_TYPE = 65
    FIELDS = ("op", "epoch", "rank", "quorum", "fsid")


@register_message
class MMonPaxos(_JsonMessage):
    """reference: MMonPaxos — op in {collect, last, begin, accept, commit}.
    `version` is the paxos commit version, `pn` the proposal number,
    `value` a base64/hex-free JSON-encoded KV batch."""

    MSG_TYPE = 66
    FIELDS = ("op", "pn", "version", "last_committed", "value", "uncommitted",
              "nonce", "fsid")


@register_message
class MMonCommand(_JsonMessage):
    """reference: MMonCommand — a `ceph` CLI command as a JSON dict with
    `prefix` plus arguments; tid correlates the ack, and `session` is a
    per-client random id so two processes sharing the default entity name
    cannot collide in the monitor's command dedup cache."""

    MSG_TYPE = 50
    FIELDS = ("tid", "cmd", "session")


@register_message
class MMonCommandAck(_JsonMessage):
    MSG_TYPE = 51
    FIELDS = ("tid", "retval", "result")


@register_message
class MMonSubscribe(_JsonMessage):
    """reference: MMonSubscribe — {'osdmap': start_epoch}; the mon replies
    with every map >= start and keeps pushing new epochs."""

    MSG_TYPE = 15
    FIELDS = ("what",)


@register_message
class MOSDMapMsg(_JsonMessage):
    """reference: MOSDMap — full maps keyed by epoch (the reference sends
    incrementals when it can; full maps are the semantic fallback both
    sides must support, and what we always send)."""

    MSG_TYPE = 41
    FIELDS = ("maps",)  # {epoch(str): osdmap json}


@register_message
class MOSDBoot(_JsonMessage):
    """reference: MOSDBoot — an OSD announcing itself (id + public addr)."""

    MSG_TYPE = 71
    FIELDS = ("osd", "host", "port")


@register_message
class MOSDFailure(_JsonMessage):
    """reference: MOSDFailure — 'I can't reach osd.N' report."""

    MSG_TYPE = 72
    FIELDS = ("target", "failed_for", "reporter")


@register_message
class MOSDAlive(_JsonMessage):
    """reference: MOSDAlive / cancellation of a failure report.  An OSD
    that reported a peer down and then hears its ping reply retracts the
    report so the leader's corroboration count drains instead of riding
    until the target reboots.  `reporter` is pinned from `src` before
    any peon→leader forward, exactly like MOSDFailure."""

    MSG_TYPE = 73
    FIELDS = ("target", "reporter")
