"""Single-decree Paxos replicating the mon KV store (reference:
src/mon/Paxos.{h,cc}; SURVEY.md §2.5 "Paxos: single-decree Paxos
replicating MonitorDBStore").

One value is chosen at a time (version = last_committed + 1); a value is a
KV batch (JSON: {"ops": [[op, key, value_b64], ...]}) applied to the mon
store on commit.  Phases map to the reference's:

    leader_init → collect(pn) → peons reply last(pn, lc, uncommitted)
    propose     → begin(pn, v, value) → peons accept → commit broadcast

Recovery matches the reference's semantics: a collect learns any value
accepted under an older pn and re-proposes it; peons that fall behind ask
for a sync of missed commits (op=sync_req) instead of accepting a gap.
Leases are simplified away: reads are served by the leader only, and a
quorum change always runs a fresh collect.
"""
from __future__ import annotations

import base64
import json
import threading

from ..common.failpoint import FailpointCrash, FailpointError, failpoint
from ..common.lockdep import make_lock
from ..store.kv import Batch
from .messages import MMonPaxos


def _reply(conn, msg, fsid=None) -> None:
    if fsid is not None:
        msg.fsid = fsid
    try:
        conn.send_message(msg)
    except (OSError, ConnectionError):
        pass  # peer reset; election/timeout machinery recovers


_K_LAST = "paxos:last_committed"
_K_PN = "paxos:accepted_pn"
_K_UNCOMMITTED = "paxos:uncommitted"


def _txn_key(version: int) -> str:
    return f"paxos:txn:{version:012d}"


def encode_value(ops: list[tuple[int, str, bytes]]) -> str:
    return json.dumps(
        {"ops": [[op, key, base64.b64encode(val).decode()] for op, key, val in ops]}
    )


def decode_value(value: str) -> list[tuple[int, str, bytes]]:
    return [
        (op, key, base64.b64decode(val))
        for op, key, val in json.loads(value)["ops"]
    ]


class Paxos:
    """Runs inside a Monitor; the monitor routes MMonPaxos to handle()."""

    def __init__(self, mon, store):
        self.mon = mon  # provides rank, majority, other_ranks, send_mon, on_paxos_commit
        self.store = store
        self.last_committed = int(store.get(_K_LAST) or b"0")
        self.accepted_pn = int(store.get(_K_PN) or b"0")
        self._lock = make_lock("mon::paxos")
        self._cond = threading.Condition(self._lock)
        # leader state
        self.pn = 0
        self._collect_acks: set[int] = set()
        self._accept_acks: set[int] = set()
        self._proposing = False
        self._learned: dict[int, tuple[int, str]] = {}  # rank -> (v, value)
        # per-proposal instance id echoed in accepts: a late accept for an
        # aborted proposal under the same (pn, version) must not count
        # toward a different value (advisor r1 finding)
        self._propose_nonce = 0
        # an aborted (timed-out) proposal may have been accepted by a
        # minority; the next proposal must run a fresh collect under a new
        # pn (reference: Paxos re-bootstraps) instead of reusing the pn
        self._need_collect = False

    # -- helpers ----------------------------------------------------------
    def _apply(self, version: int, value: str) -> None:
        # "mon.paxos.commit": an error here is a crash BEFORE the commit
        # lands in the store — the accepted-but-uncommitted value stays
        # on disk and the next collect round must recover it (the paxos
        # crash-recovery replay path)
        failpoint("mon.paxos.commit",
                  cct=getattr(self.mon, "cct", None),
                  entity=f"mon.{getattr(self.mon, 'name', self.mon.rank)}",
                  version=version)
        batch = Batch()
        for op, key, val in decode_value(value):
            if op == 1:
                batch.set(key, val)
            else:
                batch.rm(key)
        batch.set(_txn_key(version), value.encode())
        batch.set(_K_LAST, str(version).encode())
        batch.rm(_K_UNCOMMITTED)
        self.store.submit_batch(batch)
        self.last_committed = version
        self.mon.on_paxos_commit(version)

    def _uncommitted(self) -> tuple[int, int, str] | None:
        """(accepted_pn, version, value) of the locally-accepted-but-
        uncommitted proposal, if any."""
        raw = self.store.get(_K_UNCOMMITTED)
        if not raw:
            return None
        d = json.loads(raw.decode())
        return d.get("pn", 0), d["version"], d["value"]

    def _store_uncommitted(self, version: int, value: str, pn: int) -> None:
        self.store.set(
            _K_UNCOMMITTED,
            json.dumps({"version": version, "value": value, "pn": pn}).encode(),
        )

    # -- leader: recovery round -------------------------------------------
    def leader_init(self, timeout: float = 5.0) -> bool:
        """Collect phase after winning an election (reference:
        Paxos::leader_init + collect)."""
        ok, best = self._collect(timeout)
        if not ok:
            return False
        if best is not None and best[1] == self.last_committed + 1:
            self._propose_locked_value(best[2])
        return True

    def _collect(self, timeout: float) -> tuple[bool, tuple | None]:
        """One collect round under a fresh pn.  Returns (ok, best) where
        best is the (pn, version, value) accepted under the highest pn at
        the next slot, or None."""
        with self._lock:
            self.pn = (self.accepted_pn // 100 + 1) * 100 + self.mon.rank
            self.accepted_pn = self.pn
            self.store.set(_K_PN, str(self.pn).encode())
            self._collect_acks = {self.mon.rank}
            self._learned = {}
            self._need_collect = False
            # send to every monmap member, not just the election quorum: a
            # mon whose election ack arrived late is outside `quorum` but
            # must still receive paxos traffic or it stays stale forever
            # (advisor r1 high finding)
            for r in self.mon.other_ranks():
                self.mon.send_mon(
                    r,
                    MMonPaxos(
                        op="collect", pn=self.pn,
                        last_committed=self.last_committed,
                    ),
                )
            ok = self._cond.wait_for(
                lambda: len(self._collect_acks) >= self.mon.majority(),
                timeout=timeout,
            )
            if not ok:
                self._need_collect = True
                return False, None
            # adopt the value accepted under the HIGHEST pn at the next
            # slot (Paxos: same-version values from different aborted
            # rounds are tie-broken by pn, not arrival order), then
            # re-propose it under our pn
            best = self._uncommitted()
            for got in self._learned.values():
                if got[1] == self.last_committed + 1 and (
                    best is None or got[0] >= best[0]
                ):
                    best = got
            if best is not None and best[1] != self.last_committed + 1:
                best = None
            return True, best

    # -- leader: proposal --------------------------------------------------
    def propose(self, ops: list[tuple[int, str, bytes]], timeout: float = 5.0) -> bool:
        """Replicate one KV batch; blocks until commit or timeout.
        (reference: Paxos::propose_pending / begin)"""
        return self._propose_locked_value(encode_value(ops), timeout)

    def _propose_locked_value(self, value: str, timeout: float = 5.0) -> bool:
        try:
            # "mon.paxos.propose": error refuses the proposal (callers
            # see the same -110 a timed-out quorum produces); delay
            # stretches the commit latency
            failpoint("mon.paxos.propose",
                      cct=getattr(self.mon, "cct", None),
                      entity=f"mon.{getattr(self.mon, 'name', self.mon.rank)}")
        except FailpointCrash:
            raise
        except FailpointError:
            return False
        with self._lock:
            # serialize proposals (reference: one in-flight proposal)
            ok = self._cond.wait_for(lambda: not self._proposing, timeout=timeout)
            if not ok:
                return False
            self._proposing = True
            try:
                # an aborted predecessor may have been accepted by a
                # minority under the current pn; Paxos safety forbids
                # reusing that pn for a different value at the same slot.
                # Re-collect under a fresh pn and re-propose its value
                # first.  Checked INSIDE the _proposing slot so a
                # concurrent proposer can't slip past the flag (reviewer
                # r2 finding).
                while self._need_collect:
                    ok, best = self._collect(timeout)
                    if not ok:
                        return False
                    if best is not None and best[1] == self.last_committed + 1:
                        if not self._begin_round_locked(best[2], timeout):
                            return False
                return self._begin_round_locked(value, timeout)
            finally:
                self._proposing = False
                self._cond.notify_all()

    def _begin_round_locked(self, value: str, timeout: float) -> bool:
        """One begin→accept-majority→commit round.  Caller holds _lock and
        the _proposing slot."""
        version = self.last_committed + 1
        self._store_uncommitted(version, value, self.pn)
        self._accept_acks = {self.mon.rank}
        self._propose_version = version
        self._propose_nonce += 1
        nonce = self._propose_nonce
        for r in self.mon.other_ranks():
            self.mon.send_mon(
                r,
                MMonPaxos(
                    op="begin", pn=self.pn, version=version,
                    value=value, nonce=nonce,
                ),
            )
        ok = self._cond.wait_for(
            lambda: len(self._accept_acks) >= self.mon.majority(),
            timeout=timeout,
        )
        if not ok:
            self._need_collect = True
            return False
        try:
            self._apply(version, value)
        except Exception:
            # failure (injected or real) between majority-accept and the
            # local commit: the value IS chosen but not applied here.
            # Reusing this pn for a different value at the same slot
            # would break Paxos safety, so the next proposal must
            # re-collect under a fresh pn and re-drive the chosen value.
            self._need_collect = True
            raise
        for r in self.mon.other_ranks():
            self.mon.send_mon(
                r, MMonPaxos(op="commit", version=version, value=value)
            )
        return True

    # -- message handling (both roles) ------------------------------------
    def handle(self, conn, msg: MMonPaxos) -> None:
        op = msg.op
        if op == "collect":
            self._handle_collect(conn, msg)
        elif op == "last":
            self._handle_last(msg)
        elif op == "begin":
            self._handle_begin(conn, msg)
        elif op == "accept":
            self._handle_accept(msg)
        elif op == "commit":
            self._handle_commit(conn, msg)
        elif op == "sync_req":
            self._handle_sync_req(conn, msg)

    def _handle_collect(self, conn, msg: MMonPaxos) -> None:
        with self._lock:
            if msg.pn <= self.accepted_pn:
                return  # stale proposer; ignore (it will time out)
            self.accepted_pn = msg.pn
            self.store.set(_K_PN, str(msg.pn).encode())
            unc = self._uncommitted()
            reply = MMonPaxos(
                op="last", pn=msg.pn, last_committed=self.last_committed,
                uncommitted=(
                    {"pn": unc[0], "version": unc[1], "value": unc[2]}
                    if unc else None
                ),
            )
            # share commits the new leader is missing (reference: the
            # collect handler sending committed versions)
            missing = {}
            for v in range(msg.last_committed + 1, self.last_committed + 1):
                raw = self.store.get(_txn_key(v))
                if raw is not None:
                    missing[str(v)] = raw.decode()
            reply.value = missing or None
        _reply(conn, reply, self.mon.monmap.fsid)

    def _handle_last(self, msg: MMonPaxos) -> None:
        with self._lock:
            if msg.pn != self.pn:
                return
            # absorb commits we missed while not leader
            if msg.value:
                for v_str in sorted(msg.value, key=int):
                    v = int(v_str)
                    if v == self.last_committed + 1:
                        self._apply(v, msg.value[v_str])
            rank = self.mon.rank_of(msg.src)
            if msg.uncommitted and rank is not None:
                self._learned[rank] = (
                    msg.uncommitted.get("pn", 0),
                    msg.uncommitted["version"],
                    msg.uncommitted["value"],
                )
            if rank is not None:
                self._collect_acks.add(rank)
            self._cond.notify_all()

    def _handle_begin(self, conn, msg: MMonPaxos) -> None:
        with self._lock:
            if msg.pn < self.accepted_pn:
                return
            if msg.version != self.last_committed + 1:
                # we're behind: ask for the missed commits instead of
                # accepting a gap
                _reply(
                    conn,
                    MMonPaxos(op="sync_req", last_committed=self.last_committed),
                    self.mon.monmap.fsid,
                )
                return
            self.accepted_pn = msg.pn
            self._store_uncommitted(msg.version, msg.value, msg.pn)
        _reply(
            conn,
            MMonPaxos(op="accept", pn=msg.pn, version=msg.version, nonce=msg.nonce),
            self.mon.monmap.fsid,
        )

    def _handle_accept(self, msg: MMonPaxos) -> None:
        with self._lock:
            # (pn, version, nonce) must all match: a late accept for an
            # aborted proposal (same pn+version, different value) must not
            # count toward the current one
            if (
                msg.pn != self.pn
                or msg.version != getattr(self, "_propose_version", None)
                or msg.nonce != self._propose_nonce
            ):
                return
            rank = self.mon.rank_of(msg.src)
            if rank is not None:
                self._accept_acks.add(rank)
            self._cond.notify_all()

    def _handle_commit(self, conn, msg: MMonPaxos) -> None:
        with self._lock:
            if msg.version == self.last_committed + 1:
                self._apply(msg.version, msg.value)
            elif msg.version > self.last_committed:
                _reply(
                    conn,
                    MMonPaxos(op="sync_req", last_committed=self.last_committed),
                    self.mon.monmap.fsid,
                )

    def _handle_sync_req(self, conn, msg: MMonPaxos) -> None:
        with self._lock:
            versions = range(msg.last_committed + 1, self.last_committed + 1)
            txns = [
                (v, self.store.get(_txn_key(v))) for v in versions
            ]
        for v, raw in txns:
            if raw is not None:
                _reply(
                    conn,
                    MMonPaxos(op="commit", version=v, value=raw.decode()),
                    self.mon.monmap.fsid,
                )
