"""Single-decree Paxos replicating the mon KV store (reference:
src/mon/Paxos.{h,cc}; SURVEY.md §2.5 "Paxos: single-decree Paxos
replicating MonitorDBStore").

One value is chosen at a time (version = last_committed + 1); a value is a
KV batch (JSON: {"ops": [[op, key, value_b64], ...]}) applied to the mon
store on commit.  Phases map to the reference's:

    leader_init → collect(pn) → peons reply last(pn, lc, uncommitted)
    propose     → begin(pn, v, value) → peons accept → commit broadcast

Recovery matches the reference's semantics: a collect learns any value
accepted under an older pn and re-proposes it; peons that fall behind ask
for a sync of missed commits (op=sync_req) instead of accepting a gap.
Leases are simplified away: reads are served by the leader only, and a
quorum change always runs a fresh collect.
"""
from __future__ import annotations

import base64
import json
import threading

from ..store.kv import Batch
from .messages import MMonPaxos


def _reply(conn, msg, fsid=None) -> None:
    if fsid is not None:
        msg.fsid = fsid
    try:
        conn.send_message(msg)
    except (OSError, ConnectionError):
        pass  # peer reset; election/timeout machinery recovers


_K_LAST = "paxos:last_committed"
_K_PN = "paxos:accepted_pn"
_K_UNCOMMITTED = "paxos:uncommitted"


def _txn_key(version: int) -> str:
    return f"paxos:txn:{version:012d}"


def encode_value(ops: list[tuple[int, str, bytes]]) -> str:
    return json.dumps(
        {"ops": [[op, key, base64.b64encode(val).decode()] for op, key, val in ops]}
    )


def decode_value(value: str) -> list[tuple[int, str, bytes]]:
    return [
        (op, key, base64.b64decode(val))
        for op, key, val in json.loads(value)["ops"]
    ]


class Paxos:
    """Runs inside a Monitor; the monitor routes MMonPaxos to handle()."""

    def __init__(self, mon, store):
        self.mon = mon  # provides rank, quorum, peon_ranks, send_mon, on_paxos_commit
        self.store = store
        self.last_committed = int(store.get(_K_LAST) or b"0")
        self.accepted_pn = int(store.get(_K_PN) or b"0")
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        # leader state
        self.pn = 0
        self._collect_acks: set[int] = set()
        self._accept_acks: set[int] = set()
        self._proposing = False
        self._learned: dict[int, tuple[int, str]] = {}  # rank -> (v, value)

    # -- helpers ----------------------------------------------------------
    def _apply(self, version: int, value: str) -> None:
        batch = Batch()
        for op, key, val in decode_value(value):
            if op == 1:
                batch.set(key, val)
            else:
                batch.rm(key)
        batch.set(_txn_key(version), value.encode())
        batch.set(_K_LAST, str(version).encode())
        batch.rm(_K_UNCOMMITTED)
        self.store.submit_batch(batch)
        self.last_committed = version
        self.mon.on_paxos_commit(version)

    def _uncommitted(self) -> tuple[int, str] | None:
        raw = self.store.get(_K_UNCOMMITTED)
        if not raw:
            return None
        d = json.loads(raw.decode())
        return d["version"], d["value"]

    def _store_uncommitted(self, version: int, value: str) -> None:
        self.store.set(
            _K_UNCOMMITTED,
            json.dumps({"version": version, "value": value}).encode(),
        )

    # -- leader: recovery round -------------------------------------------
    def leader_init(self, timeout: float = 5.0) -> bool:
        """Collect phase after winning an election (reference:
        Paxos::leader_init + collect)."""
        with self._lock:
            self.pn = (self.accepted_pn // 100 + 1) * 100 + self.mon.rank
            self.accepted_pn = self.pn
            self.store.set(_K_PN, str(self.pn).encode())
            self._collect_acks = {self.mon.rank}
            self._learned = {}
            peons = self.mon.peon_ranks()
            for r in peons:
                self.mon.send_mon(
                    r,
                    MMonPaxos(
                        op="collect", pn=self.pn,
                        last_committed=self.last_committed,
                    ),
                )
            ok = self._cond.wait_for(
                lambda: len(self._collect_acks) >= self.mon.majority(),
                timeout=timeout,
            )
            if not ok:
                return False
            # adopt any value accepted under an older pn (highest wins),
            # then re-propose it under our pn (reference: the collect's
            # uncommitted handling)
            best = self._uncommitted()
            for v, value in self._learned.values():
                if v == self.last_committed + 1 and (
                    best is None or v >= best[0]
                ):
                    best = (v, value)
        if best is not None and best[0] == self.last_committed + 1:
            self._propose_locked_value(best[1])
        return True

    # -- leader: proposal --------------------------------------------------
    def propose(self, ops: list[tuple[int, str, bytes]], timeout: float = 5.0) -> bool:
        """Replicate one KV batch; blocks until commit or timeout.
        (reference: Paxos::propose_pending / begin)"""
        return self._propose_locked_value(encode_value(ops), timeout)

    def _propose_locked_value(self, value: str, timeout: float = 5.0) -> bool:
        with self._lock:
            # serialize proposals (reference: one in-flight proposal)
            ok = self._cond.wait_for(lambda: not self._proposing, timeout=timeout)
            if not ok:
                return False
            self._proposing = True
            try:
                version = self.last_committed + 1
                self._store_uncommitted(version, value)
                self._accept_acks = {self.mon.rank}
                self._propose_version = version
                for r in self.mon.peon_ranks():
                    self.mon.send_mon(
                        r,
                        MMonPaxos(
                            op="begin", pn=self.pn, version=version, value=value,
                        ),
                    )
                ok = self._cond.wait_for(
                    lambda: len(self._accept_acks) >= self.mon.majority(),
                    timeout=timeout,
                )
                if not ok:
                    return False
                self._apply(version, value)
                for r in self.mon.peon_ranks():
                    self.mon.send_mon(
                        r, MMonPaxos(op="commit", version=version, value=value)
                    )
                return True
            finally:
                self._proposing = False
                self._cond.notify_all()

    # -- message handling (both roles) ------------------------------------
    def handle(self, conn, msg: MMonPaxos) -> None:
        op = msg.op
        if op == "collect":
            self._handle_collect(conn, msg)
        elif op == "last":
            self._handle_last(msg)
        elif op == "begin":
            self._handle_begin(conn, msg)
        elif op == "accept":
            self._handle_accept(msg)
        elif op == "commit":
            self._handle_commit(conn, msg)
        elif op == "sync_req":
            self._handle_sync_req(conn, msg)

    def _handle_collect(self, conn, msg: MMonPaxos) -> None:
        with self._lock:
            if msg.pn <= self.accepted_pn:
                return  # stale proposer; ignore (it will time out)
            self.accepted_pn = msg.pn
            self.store.set(_K_PN, str(msg.pn).encode())
            unc = self._uncommitted()
            reply = MMonPaxos(
                op="last", pn=msg.pn, last_committed=self.last_committed,
                uncommitted=(
                    {"version": unc[0], "value": unc[1]} if unc else None
                ),
            )
            # share commits the new leader is missing (reference: the
            # collect handler sending committed versions)
            missing = {}
            for v in range(msg.last_committed + 1, self.last_committed + 1):
                raw = self.store.get(_txn_key(v))
                if raw is not None:
                    missing[str(v)] = raw.decode()
            reply.value = missing or None
        _reply(conn, reply, self.mon.monmap.fsid)

    def _handle_last(self, msg: MMonPaxos) -> None:
        with self._lock:
            if msg.pn != self.pn:
                return
            # absorb commits we missed while not leader
            if msg.value:
                for v_str in sorted(msg.value, key=int):
                    v = int(v_str)
                    if v == self.last_committed + 1:
                        self._apply(v, msg.value[v_str])
            rank = self.mon.rank_of(msg.src)
            if msg.uncommitted and rank is not None:
                self._learned[rank] = (
                    msg.uncommitted["version"], msg.uncommitted["value"],
                )
            if rank is not None:
                self._collect_acks.add(rank)
            self._cond.notify_all()

    def _handle_begin(self, conn, msg: MMonPaxos) -> None:
        with self._lock:
            if msg.pn < self.accepted_pn:
                return
            if msg.version != self.last_committed + 1:
                # we're behind: ask for the missed commits instead of
                # accepting a gap
                _reply(
                    conn,
                    MMonPaxos(op="sync_req", last_committed=self.last_committed),
                    self.mon.monmap.fsid,
                )
                return
            self.accepted_pn = msg.pn
            self._store_uncommitted(msg.version, msg.value)
        _reply(
            conn,
            MMonPaxos(op="accept", pn=msg.pn, version=msg.version),
            self.mon.monmap.fsid,
        )

    def _handle_accept(self, msg: MMonPaxos) -> None:
        with self._lock:
            # version must match too: a late ack for an earlier proposal
            # under the same pn must not count toward the current one
            if msg.pn != self.pn or msg.version != getattr(self, "_propose_version", None):
                return
            rank = self.mon.rank_of(msg.src)
            if rank is not None:
                self._accept_acks.add(rank)
            self._cond.notify_all()

    def _handle_commit(self, conn, msg: MMonPaxos) -> None:
        with self._lock:
            if msg.version == self.last_committed + 1:
                self._apply(msg.version, msg.value)
            elif msg.version > self.last_committed:
                _reply(
                    conn,
                    MMonPaxos(op="sync_req", last_committed=self.last_committed),
                    self.mon.monmap.fsid,
                )

    def _handle_sync_req(self, conn, msg: MMonPaxos) -> None:
        with self._lock:
            versions = range(msg.last_committed + 1, self.last_committed + 1)
            txns = [
                (v, self.store.get(_txn_key(v))) for v in versions
            ]
        for v, raw in txns:
            if raw is not None:
                _reply(
                    conn,
                    MMonPaxos(op="commit", version=v, value=raw.decode()),
                    self.mon.monmap.fsid,
                )
