"""MonClient — daemon/client session to the monitor quorum (reference:
src/mon/MonClient.{h,cc}; SURVEY.md §2.5).

Hunts for a live mon, redials to the leader when a command is NACKed with
`not leader`, keeps the OSDMap subscription alive across reconnects, and
exposes `wait_for_osdmap` the way daemons block on map epochs.
"""
from __future__ import annotations

import threading
import time

from ..common.lockdep import make_lock
from ..msg import Dispatcher, Messenger
from ..osd.osdmap import OSDMap
from .messages import (
    MMonCommand,
    MMonCommandAck,
    MMonSubscribe,
    MOSDAlive,
    MOSDBoot,
    MOSDFailure,
    MOSDMapMsg,
)


class MonClient(Dispatcher):
    def __init__(self, cct, mon_addrs: list[tuple[str, int]], name: str | None = None):
        self.cct = cct
        self.mon_addrs = [tuple(a) for a in mon_addrs]
        self.messenger = Messenger.create(cct, name or cct.name)
        self.messenger.add_dispatcher(self)
        self._conn = None
        self._conn_addr: tuple[str, int] | None = None
        self._lock = make_lock("monc::lock")
        self._cond = threading.Condition(self._lock)
        self._tid = 0
        # random per-process session id: part of the monitor's command
        # dedup key so two clients with the same entity name don't collide
        import uuid

        self._session = uuid.uuid4().hex
        self._acks: dict[int, tuple[int, object]] = {}
        self._last_failed_hunt = float("-inf")
        self._hunting = False
        self.osdmap: OSDMap | None = None
        self._subscribed_from = 0
        self._map_callbacks: list = []

    # -- connection hunt ---------------------------------------------------
    def _connect(self, addr=None):
        with self._lock:
            if addr is None and self._conn is not None and self._conn.is_connected:
                return self._conn
            addrs = [addr] if addr else list(self.mon_addrs)
        # the dial + subscription renewal run OUTSIDE monc::lock: the
        # messenger dispatches incoming frames while holding
        # msgr::session and ms_dispatch then takes monc::lock, so
        # calling into the messenger with monc::lock held is the ABBA
        # inversion lockdep (rightly) aborts.  Concurrent dials are
        # harmless — last one wins the cache and the rest stay usable.
        last_err = None
        for a in addrs:
            try:
                conn = self.messenger.connect(tuple(a))
                # a mon that dies between accept and this send must
                # fail over to the next address like a refused dial
                self._renew_sub(conn)
            except (OSError, ConnectionError) as e:
                last_err = e
                continue
            with self._lock:
                self._conn, self._conn_addr = conn, tuple(a)
            return conn
        raise ConnectionError(f"no monitor reachable: {last_err}")

    def _renew_sub(self, conn) -> None:
        """(Re-)arm the osdmap subscription on a connection; idempotent on
        the mon side, shared by dial/subscribe/wait paths."""
        if self._subscribed_from:
            conn.send_message(
                MMonSubscribe(what={"osdmap": self._subscribed_from})
            )

    def ms_handle_reset(self, conn) -> None:
        with self._lock:
            if conn is self._conn:
                self._conn = None

    def ensure_connection(self) -> None:
        """Re-dial the quorum if the subscription connection died.  The
        osdmap subscription is PUSH-based: a mon that crashes between
        pushes leaves an idle subscriber on a stale map forever unless
        something re-hunts — daemons call this from their tick loop.
        Never blocks: the hunt runs on a helper thread (a full-quorum
        dial can eat whole connect timeouts, and the caller's tick loop
        drives heartbeats that must keep their cadence), rate-limited
        after failures.  The state check itself is a TRY-acquire so a
        busy client op can never stall the tick loop here."""
        if not self._lock.acquire(blocking=False):
            return  # a hunt (or another client op) is busy; next tick
        try:
            if self._conn is not None and self._conn.is_connected:
                return
            now = time.monotonic()
            if self._hunting or now - self._last_failed_hunt < 2.0:
                return
            self._hunting = True
        finally:
            self._lock.release()

        def _hunt() -> None:
            try:
                self._connect()
            except (OSError, ConnectionError):
                with self._lock:
                    self._last_failed_hunt = time.monotonic()
            finally:
                with self._lock:
                    self._hunting = False

        threading.Thread(  # noqa: CL13 — fire-and-forget by design: the _hunting flag dedups to one live hunt and it self-terminates on connect or deadline
            target=_hunt, name=f"{self.messenger.name}-mon-hunt", daemon=True
        ).start()

    # -- dispatch ----------------------------------------------------------
    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MMonCommandAck):
            with self._lock:
                self._acks[msg.tid] = (msg.retval, msg.result)
                self._cond.notify_all()
            return True
        if isinstance(msg, MOSDMapMsg):
            newest = None
            for _e, j in sorted(msg.maps.items(), key=lambda kv: int(kv[0])):
                newest = j
            if newest is not None:
                m = OSDMap.from_json(newest)
                callbacks = []
                with self._lock:
                    if self.osdmap is None or m.epoch > self.osdmap.epoch:
                        self.osdmap = m
                        self._subscribed_from = m.epoch + 1
                        callbacks = list(self._map_callbacks)
                        self._cond.notify_all()
                for cb in callbacks:
                    cb(m)
            return True
        return False

    # -- commands ----------------------------------------------------------
    def fetch_config(self, cct, who: str | None = None) -> int:
        """Boot-time central config pull (reference: the mon config db
        pushed at MAuth/MConfig time): fetch this entity's merged view
        and apply it at LEVEL_MON, so file/override settings still
        win.  Returns the number of options applied; mon unreachable
        or empty db is not an error — local config stands."""
        from ..common.config import LEVEL_MON

        who = who or cct.conf.get("name")
        try:
            rv, res = self.command({"prefix": "config get", "who": who},
                                   timeout=5.0)
        except Exception:
            return 0
        if rv != 0 or not isinstance(res, dict):
            return 0
        n = 0
        for name, value in res.items():
            try:
                cct.conf.set(name, value, level=LEVEL_MON)
                n += 1
            except (KeyError, ValueError) as e:
                cct.dout("monc", 2,
                         f"central config {name}={value!r} rejected: "
                         f"{e}")
        return n

    def command(self, cmd: dict, timeout: float = 10.0) -> tuple[int, object]:
        """Send a CLI-style command; transparently follows the leader
        (reference: MonClient command routing + Objecter retries)."""
        deadline = threading.TIMEOUT_MAX if timeout is None else timeout
        attempts = 0
        addr = None
        # one tid for every attempt of this logical command: the monitor
        # dedups on (src, session, tid), so a retry after a lost ack
        # re-fetches the recorded result instead of re-executing a
        # non-idempotent command
        with self._lock:
            self._tid += 1
            tid = self._tid
        while attempts < 5:
            attempts += 1
            try:
                conn = self._connect(addr)
                conn.send_message(
                    MMonCommand(tid=tid, cmd=cmd, session=self._session)
                )
            except (OSError, ConnectionError):
                addr = None
                continue
            with self._lock:
                ok = self._cond.wait_for(
                    lambda: tid in self._acks, timeout=min(deadline, 10.0)
                )
                if not ok:
                    addr = None
                    continue
                retval, result = self._acks.pop(tid)
            if retval == -307 and isinstance(result, dict):
                # peon: redial the leader it names
                la = result.get("leader_addr")
                addr = tuple(la) if la else None
                if addr is None:
                    time.sleep(0.2)  # election in progress
                continue
            if retval == -11:  # EAGAIN: leader elected, state still syncing
                time.sleep(0.2)
                continue
            return retval, result
        return -110, "command timed out (no leader?)"

    # -- subscriptions -----------------------------------------------------
    def subscribe_osdmap(self, from_epoch: int = 1, callback=None) -> None:
        with self._lock:
            self._subscribed_from = max(self._subscribed_from, from_epoch) or 1
            if callback is not None:
                self._map_callbacks.append(callback)
        # _connect renews only on a fresh dial; renew explicitly in case a
        # cached connection predates the subscription
        self._renew_sub(self._connect())

    def wait_for_osdmap(self, min_epoch: int = 1, timeout: float = 10.0) -> OSDMap:
        """Block until a map >= min_epoch arrives, actively hunting: if the
        mon connection resets (mon restart, lossy drop, mid-election
        hiccup) the subscription is re-armed on a fresh dial instead of
        waiting out the timeout on a dead session (reference: MonClient's
        hunt + renew on reset)."""
        deadline = time.monotonic() + timeout

        def have_map() -> bool:
            return self.osdmap is not None and self.osdmap.epoch >= min_epoch

        while True:
            with self._lock:
                if self._cond.wait_for(
                    have_map, timeout=min(1.0, max(0.0, deadline - time.monotonic()))
                ):
                    return self.osdmap
                expired = time.monotonic() >= deadline
            if expired:
                have = self.osdmap.epoch if self.osdmap else None
                raise TimeoutError(
                    f"no osdmap epoch >= {min_epoch} (have {have})"
                )
            # not served yet: re-dial if the connection died — the fresh
            # dial re-arms the subscription.  A live connection needs no
            # nudge (re-sending the sub every slice would make the mon
            # push the full map once per second per waiting daemon).
            try:
                self._connect()
            except (OSError, ConnectionError):
                pass

    # -- daemon helpers ----------------------------------------------------
    def send_boot(self, osd: int, addr: tuple[str, int]) -> None:
        self._connect().send_message(
            MOSDBoot(osd=osd, host=addr[0], port=addr[1])
        )

    def report_failure(self, target: int, failed_for: float = 0.0) -> None:
        try:
            self._connect().send_message(
                MOSDFailure(target=target, failed_for=failed_for, reporter=None)
            )
        except (OSError, ConnectionError):
            pass

    def report_alive(self, target: int) -> None:
        """Retract an earlier report_failure for `target` (reference:
        OSD::send_still_alive -> MOSDAlive): the mon discards this
        daemon's entry from the target's corroboration set.  reporter
        is left None — the receiving mon pins it from msg.src, which
        survives the peon→leader forward."""
        try:
            self._connect().send_message(
                MOSDAlive(target=target, reporter=None)
            )
        except (OSError, ConnectionError):
            pass

    def shutdown(self) -> None:
        self.messenger.shutdown()
