"""Monitor elections (reference: src/mon/Elector.{h,cc} — lowest rank in
the quorum wins; epoch odd while electing, even once stable).

Propose/ack/victory over the messenger: a mon proposes with a bumped
epoch; peers of higher rank ack (deferring), peers of lower rank counter-
propose.  The proposer declares victory once every monmap member acked or
a majority acked and the election timer expired.
"""
from __future__ import annotations

import threading

from ..common.failpoint import FailpointCrash, FailpointError, failpoint
from ..common.lockdep import make_lock
from .messages import MMonElection


class Elector:
    def __init__(self, mon, timeout: float = 0.3):
        self.mon = mon
        self.timeout = timeout
        self.epoch = 1
        self._acks: set[int] = set()
        self._electing = False
        # True while we are deferring to a lower-ranked proposer: our own
        # proposal is dead, so acks must not accumulate and a timeout must
        # RE-PROPOSE, never declare victory
        self._deferred = False
        self._timer: threading.Timer | None = None
        self._lock = make_lock("mon::elector")

    def stop(self) -> None:
        with self._lock:
            self._electing = False
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    def start_election(self) -> None:
        """reference: Elector::start — propose ourselves."""
        try:
            # "mon.election.start": delay holds this mon's proposal back
            # (higher ranks win the round); error suppresses it entirely
            # (getattr: unit tests drive the elector with bare stub mons)
            failpoint("mon.election.start",
                      cct=getattr(self.mon, "cct", None),
                      entity=f"mon.{getattr(self.mon, 'name', self.mon.rank)}")
        except FailpointCrash:
            raise
        except FailpointError:
            return
        with self._lock:
            if getattr(self, "_stopped", False):
                return
            if self.epoch % 2 == 0:
                self.epoch += 1  # odd = electing
            else:
                self.epoch += 2
            self._electing = True
            self._deferred = False
            self._acks = {self.mon.rank}
            self.mon.set_electing()
            for r in self.mon.other_ranks():
                self.mon.send_mon(
                    r, MMonElection(op="propose", epoch=self.epoch, rank=self.mon.rank)
                )
            self._arm_timer()
            self._maybe_win_locked()

    def _arm_timer(self, factor: float = 1.0) -> None:
        if self._timer is not None:
            self._timer.cancel()
        self._timer = threading.Timer(self.timeout * factor, self._election_timeout)
        self._timer.daemon = True
        self._timer.start()

    def _election_timeout(self) -> None:
        with self._lock:
            if not self._electing:
                return
            if not self._deferred and len(self._acks) >= self.mon.majority():
                self._declare_victory_locked()
            else:
                # couldn't form a quorum, or we were deferring to a
                # proposer that went silent: a deferred mon's proposal is
                # dead, so it RE-PROPOSES — it never declares victory
                self._electing = False
                self.start_election()

    def _maybe_win_locked(self) -> None:
        if self._electing and len(self._acks) >= len(self.mon.monmap.ranks()):
            self._declare_victory_locked()

    def _declare_victory_locked(self) -> None:
        self._electing = False
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self.epoch += 1  # even = stable
        quorum = sorted(self._acks)
        for r in self.mon.other_ranks():
            self.mon.send_mon(
                r,
                MMonElection(
                    op="victory", epoch=self.epoch, rank=self.mon.rank,
                    quorum=quorum,
                ),
            )
        self.mon.win_election(self.epoch, quorum)

    def handle(self, conn, msg: MMonElection) -> None:
        if msg.op == "propose":
            self._handle_propose(msg)
        elif msg.op == "ack":
            self._handle_ack(msg)
        elif msg.op == "victory":
            self._handle_victory(msg)

    def _handle_propose(self, msg: MMonElection) -> None:
        with self._lock:
            was_electing = self._electing
            if msg.epoch > self.epoch:
                self.epoch = msg.epoch
            if msg.rank < self.mon.rank:
                # defer to the lower rank (reference: Elector::defer); keep
                # a timer armed so a proposer that dies mid-election leaves
                # us retrying, not stranded — but MUCH longer than the
                # proposer's victory timer, else our re-propose races its
                # victory and elections livelock (epoch churn forever).
                # Forget any acks from our own abandoned proposal: a defer
                # timeout must RE-PROPOSE, never declare victory on a dead
                # election's ack set (a deferring mon that still held a
                # majority of stale acks would steal leadership from the
                # lower rank whenever the victory message was slow)
                self._electing = True
                self._deferred = True
                self._acks = {self.mon.rank}
                self.mon.set_electing()
                self._arm_timer(factor=5.0)
                self.mon.send_mon(
                    msg.rank,
                    MMonElection(op="ack", epoch=msg.epoch, rank=self.mon.rank),
                )
            elif not was_electing:
                # we outrank the proposer and have no election running:
                # counter-propose.  If one IS running, our earlier propose
                # stands — re-proposing on every higher-rank propose makes
                # boot-time elections storm (epoch churn, overlapping
                # leader_inits) instead of converging.
                self.start_election()

    def _handle_ack(self, msg: MMonElection) -> None:
        with self._lock:
            # acks addressed to a proposal we abandoned by deferring must
            # not accumulate — _maybe_win_locked would declare victory on
            # a dead election once every rank's late ack trickled in
            if not self._electing or self._deferred or msg.epoch != self.epoch:
                return
            self._acks.add(msg.rank)
            self._maybe_win_locked()

    def _handle_victory(self, msg: MMonElection) -> None:
        with self._lock:
            if msg.epoch < self.epoch:
                return
            self.epoch = msg.epoch
            self._electing = False
            self._deferred = False
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        self.mon.lose_election(msg.epoch, msg.rank, msg.quorum or [])
