"""OSDMonitor — the OSDMap authority (reference: src/mon/OSDMonitor.{h,cc};
SURVEY.md §2.5).

All OSDMap mutations funnel through here on the leader: a pending copy of
the map is mutated, bumped one epoch, and proposed through Paxos as the
store write `osdmap:<epoch>`; on commit every mon reloads and the leader
pushes the new epoch to subscribers.  Key reference behaviors mirrored:

- `osd erasure-code-profile set` validates by INSTANTIATING the codec via
  the ErasureCodePluginRegistry — exactly the seam where `plugin=jax` gets
  vetted (reference: OSDMonitor::crush_rule_create_erasure path).
- `osd pool create ... erasure <profile>` synthesizes the EC CRUSH rule
  (indep, k+m replicas) from the profile's failure domain.
- MOSDFailure reports are corroborated (`mon_osd_min_down_reporters`
  distinct reporters) before marking down; down OSDs go out after
  `mon_osd_down_out_interval` unless `noout` is set (reference: §5.3).
"""
from __future__ import annotations

import json
import time

from ..crush import add_simple_rule
from ..ec.interface import InvalidProfile
from ..ec.registry import ErasureCodePluginRegistry
from ..osd.osdmap import OSDMap, PG_POOL_ERASURE, PG_POOL_REPLICATED

_K_LAST_OSDMAP = "osdmap:last"


def _map_key(epoch: int) -> str:
    return f"osdmap:{epoch:010d}"


class OSDMonitor:
    def __init__(self, mon, initial_map: OSDMap | None = None):
        self.mon = mon
        self.osdmap: OSDMap | None = None
        # failure corroboration state (leader-local, reference:
        # OSDMonitor::failure_info)
        self._failure_reporters: dict[int, set[str]] = {}
        self._down_stamp: dict[int, float] = {}
        self.refresh()
        if self.osdmap is None and initial_map is not None and mon.rank == 0:
            self._initial = initial_map
        else:
            self._initial = None

    # -- store sync --------------------------------------------------------
    def refresh(self) -> None:
        """Reload the latest committed map (reference:
        OSDMonitor::update_from_paxos)."""
        raw = self.mon.store.get(_K_LAST_OSDMAP)
        if raw is None:
            return
        epoch = int(raw)
        map_raw = self.mon.store.get(_map_key(epoch))
        if map_raw is not None:
            self.osdmap = OSDMap.from_json(json.loads(map_raw.decode()))

    def on_elected_leader(self) -> None:
        """First leader seeds the initial map (vstart hands it in)."""
        if self.osdmap is None and self._initial is not None:
            self._propose_map(self._initial)

    def get_map_json(self, epoch: int) -> dict | None:
        raw = self.mon.store.get(_map_key(epoch))
        return json.loads(raw.decode()) if raw is not None else None

    @property
    def epoch(self) -> int:
        return self.osdmap.epoch if self.osdmap is not None else 0

    # -- mutation plumbing -------------------------------------------------
    def _pending(self) -> OSDMap:
        if self.osdmap is None:
            raise RuntimeError("no osdmap committed yet")
        return OSDMap.from_json(self.osdmap.to_json())

    def _propose_map(self, new_map: OSDMap) -> bool:
        new_map.epoch = max(new_map.epoch, self.epoch + 1)
        blob = json.dumps(new_map.to_json()).encode()
        ops = [
            (1, _map_key(new_map.epoch), blob),
            (1, _K_LAST_OSDMAP, str(new_map.epoch).encode()),
        ]
        ok = self.mon.paxos.propose(ops)
        if ok:
            self.mon.publish_osdmap()
        return ok

    # -- boot / failure (reference: §3.4, §5.3) ---------------------------
    def handle_boot(self, osd: int, addr: tuple[str, int]) -> bool:
        m = self._pending()
        if not (0 <= osd < m.max_osd):
            return False
        m.mark_up(osd)
        m.osd_addrs[osd] = addr
        self._failure_reporters.pop(osd, None)
        self._down_stamp.pop(osd, None)
        return self._propose_map(m)

    def handle_failure(self, target: int, reporter: str) -> bool:
        """Corroborated failure reports → down (reference:
        OSDMonitor::prepare_failure)."""
        if self.osdmap is None or not self.osdmap.is_up(target):
            return False
        if "nodown" in self.osdmap.flags:
            return False
        reporters = self._failure_reporters.setdefault(target, set())
        reporters.add(reporter)
        needed = self.mon.cct.conf.get("mon_osd_min_down_reporters")
        if len(reporters) < needed:
            return False
        m = self._pending()
        m.mark_down(target)
        del self._failure_reporters[target]
        self._down_stamp[target] = time.monotonic()
        return self._propose_map(m)

    def handle_alive(self, target: int, reporter: str) -> None:
        reporters = self._failure_reporters.get(target)
        if reporters:
            reporters.discard(reporter)

    def tick(self) -> None:
        """down → out after the grace (reference: mon_osd_down_out_interval
        in OSDMonitor::tick)."""
        if self.osdmap is None or not self.mon.is_leader():
            return
        if "noout" in self.osdmap.flags:
            return
        grace = self.mon.cct.conf.get("mon_osd_down_out_interval")
        now = time.monotonic()
        to_out = [
            o for o, t in self._down_stamp.items()
            if now - t >= grace and self.osdmap.osd_weight[o] != 0
            and not self.osdmap.is_up(o)
        ]
        if not to_out:
            return
        m = self._pending()
        for o in to_out:
            m.mark_out(o)
            del self._down_stamp[o]
        self._propose_map(m)

    # -- commands ----------------------------------------------------------
    def handle_command(self, cmd: dict) -> tuple[int, object]:
        """Returns (retval, result) — retval 0 on success (reference:
        OSDMonitor::prepare_command)."""
        prefix = cmd.get("prefix", "")
        if prefix == "osd dump":
            return 0, self.osdmap.to_json() if self.osdmap else {}
        if prefix == "osd getmap":
            # historical epoch fetch (reference: mon serving old maps for
            # OSD pg-history reconstruction / PastIntervals rebuild)
            try:
                e = int(cmd.get("epoch", 0))
            except (TypeError, ValueError):
                return -22, "bad epoch"
            if e <= 0:  # no/zero epoch = the current map, like `osd dump`
                e = self.osdmap.epoch if self.osdmap else 0
            j = self.get_map_json(e)
            return (0, j) if j is not None else (-2, f"no map epoch {e}")
        if prefix == "osd getmaps":
            # batched range fetch for interval-history rebuilds: 64
            # epochs per call keeps one recovery pass at ~8 round trips
            # instead of 512 (review r4); trimmed epochs are omitted
            try:
                first = int(cmd.get("first", 0))
                last = int(cmd.get("last", 0))
            except (TypeError, ValueError):
                return -22, "bad epoch range"
            if first < 1 or last < first:
                return -22, f"bad epoch range [{first},{last}]"
            last = min(last, first + 63)
            out = {}
            for e in range(first, last + 1):
                j = self.get_map_json(e)
                if j is not None:
                    out[str(e)] = j
            return 0, {"maps": out, "last": last}
        if prefix == "osd stat":
            return 0, self._stat()
        if prefix == "mgr digest":
            # reference: MMonMgrReport -> MgrStatMonitor; the mgr streams
            # its PGMap digest here so df/pg-dump answer from the mon
            d = cmd.get("digest")
            if not isinstance(d, dict):
                return -22, "digest must be a dict"
            self.mgr_digest = (time.monotonic(), d)
            return 0, "ok"
        if prefix in ("df", "osd df", "pg dump"):
            return self._cmd_from_digest(prefix)
        if prefix == "perf history":
            return self._cmd_perf_history(cmd)
        if prefix == "progress":
            return self._cmd_progress()
        if prefix == "balancer status":
            return self._cmd_balancer_status()
        if prefix == "placement diff":
            return self._cmd_placement_diff()
        if prefix == "osd erasure-code-profile set":
            return self._cmd_profile_set(cmd)
        if prefix == "osd erasure-code-profile get":
            name = cmd.get("name", "")
            prof = (self.osdmap.ec_profiles if self.osdmap else {}).get(name)
            return (0, prof) if prof is not None else (-2, f"no profile {name!r}")
        if prefix == "osd erasure-code-profile ls":
            return 0, sorted(self.osdmap.ec_profiles) if self.osdmap else []
        if prefix == "osd pool create":
            return self._cmd_pool_create(cmd)
        if prefix == "osd pool ls":
            if not self.osdmap:
                return 0, []
            if cmd.get("detail"):
                return 0, [vars(p) for p in self.osdmap.pools.values()]
            return 0, [p.name for p in self.osdmap.pools.values()]
        if prefix in ("osd down", "osd out", "osd in"):
            return self._cmd_osd_state(prefix.split()[1], cmd)
        if prefix == "osd crush add-bucket":
            m = self._pending()
            try:
                m.crush.add_bucket(cmd.get("name", ""),
                                   cmd.get("type", ""))
            except (ValueError, KeyError) as e:
                return -22, str(e)
            return (0, f"added bucket {cmd.get('name')!r}") \
                if self._propose_map(m) else (-110, "proposal timed out")
        if prefix == "osd crush move":
            m = self._pending()
            try:
                m.crush.move_item(cmd.get("name", ""),
                                  cmd.get("dest", ""))
            except (ValueError, KeyError) as e:
                return -22, str(e)
            return (0, f"moved {cmd.get('name')!r} under "
                       f"{cmd.get('dest')!r}") \
                if self._propose_map(m) else (-110, "proposal timed out")
        if prefix == "osd crush rm":
            m = self._pending()
            try:
                m.crush.remove_item(cmd.get("name", ""))
            except (ValueError, KeyError) as e:
                return -22, str(e)
            return (0, f"removed {cmd.get('name')!r}") \
                if self._propose_map(m) else (-110, "proposal timed out")
        if prefix == "osd crush reweight":
            # reference: OSDMonitor prepare_command OSD_CRUSH_REWEIGHT —
            # distinct from `osd reweight` (the probabilistic in/out
            # thinning): this changes the CRUSH weight, i.e. placement
            try:
                w = float(cmd.get("weight"))
            except (TypeError, ValueError):
                return -22, "numeric weight required"
            m = self._pending()
            try:
                m.crush.reweight_item(cmd.get("name", ""), w)
            except (KeyError, ValueError) as e:
                return -22, str(e)
            return (0, f"reweighted {cmd.get('name')} to {w}") \
                if self._propose_map(m) else (-110, "proposal timed out")
        if prefix in ("osd reweight", "osd primary-affinity"):
            # reference: OSDMonitor prepare_command OSD_REWEIGHT /
            # OSD_PRIMARY_AFFINITY — 0.0..1.0 stored as 16.16 fixed
            try:
                osd = int(cmd.get("id"))
                w = float(cmd.get("weight"))
            except (TypeError, ValueError):
                return -22, "need id and weight"
            if not (0.0 <= w <= 1.0):
                return -22, f"weight {w} out of [0, 1]"
            if self.osdmap is None or not (0 <= osd < self.osdmap.max_osd):
                return -22, f"no osd.{osd}"
            m = self._pending()
            fixed = int(round(w * 0x10000))
            if prefix == "osd reweight":
                m.osd_weight[osd] = fixed
            else:
                m.osd_primary_affinity[osd] = fixed
            what = prefix.split()[1]
            return (0, f"{what} osd.{osd} to {w}") \
                if self._propose_map(m) else (-110, "proposal timed out")
        if prefix in ("osd set", "osd unset"):
            flag = cmd.get("key", "")
            if flag not in ("noout", "nodown", "noup"):
                return -22, f"unknown flag {flag!r}"
            m = self._pending()
            (m.flags.add if prefix == "osd set" else m.flags.discard)(flag)
            return (0, f"{flag} {'set' if prefix == 'osd set' else 'unset'}") \
                if self._propose_map(m) else (-110, "proposal timed out")
        if prefix == "osd pool set":
            return self._cmd_pool_set(cmd)
        if prefix == "osd pool set-quota":
            return self._cmd_pool_quota(cmd)
        if prefix == "osd pool get-quota":
            name = cmd.get("name", "")
            pool = next((p for p in self.osdmap.pools.values()
                         if p.name == name), None) if self.osdmap else None
            if pool is None:
                return -2, f"no pool {name!r}"
            return 0, {"quota_max_bytes": pool.quota_max_bytes,
                       "quota_max_objects": pool.quota_max_objects,
                       "full": "full_quota" in pool.flags}
        if prefix == "osd pool quota-flag":
            # internal: the mgr's quota loop flips FULL_QUOTA when stats
            # cross/clear the quota (reference: the mon's own stats-driven
            # pool FULL flag; our stats live in the mgr)
            name = cmd.get("name", "")
            m = self._pending()
            pool = next((p for p in m.pools.values() if p.name == name),
                        None)
            if pool is None:
                return -2, f"no pool {name!r}"
            want = bool(int(cmd.get("full", 0)))
            have = "full_quota" in pool.flags
            if want == have:
                return 0, "unchanged"
            if want:
                pool.flags.append("full_quota")
            else:
                pool.flags.remove("full_quota")
            return (0, f"full_quota={'set' if want else 'cleared'}") \
                if self._propose_map(m) else (-110, "proposal timed out")
        if prefix == "osd pool rm":
            return self._cmd_pool_rm(cmd)
        if prefix == "osd ok-to-stop":
            return self._cmd_ok_to_stop(cmd)
        if prefix == "osd safe-to-destroy":
            return self._cmd_safe_to_destroy(cmd)
        if prefix == "osd pool application enable":
            return self._cmd_pool_application(cmd, enable=True)
        if prefix == "osd pool application disable":
            return self._cmd_pool_application(cmd, enable=False)
        if prefix == "osd pool application get":
            m = self.osdmap
            pool = next((p for p in m.pools.values()
                         if p.name == cmd.get("pool")), None)
            if pool is None:
                return -2, f"no pool {cmd.get('pool')!r}"
            return 0, pool.application
        if prefix == "osd pool rename":
            src_n, dst_n = cmd.get("srcpool", ""), cmd.get("destpool", "")
            if not src_n or not dst_n:
                return -22, "srcpool and destpool required"
            m = self._pending()
            if any(p.name == dst_n for p in m.pools.values()):
                return -17, f"pool {dst_n!r} already exists"
            pool = next((p for p in m.pools.values() if p.name == src_n),
                        None)
            if pool is None:
                return -2, f"no pool {src_n!r}"
            pool.name = dst_n
            return (0, f"pool {src_n!r} renamed to {dst_n!r}") \
                if self._propose_map(m) else (-110, "proposal timed out")
        if prefix in ("osd pool mksnap", "osd pool rmsnap"):
            return self._cmd_pool_snap(prefix.endswith("mksnap"), cmd)
        if prefix == "osd pg-upmap-items":
            return self._cmd_upmap_items(cmd)
        if prefix.startswith("osd tier "):
            return self._cmd_tier(prefix[len("osd tier "):], cmd)
        if prefix == "osd tree":
            return 0, self._cmd_tree()
        if prefix == "auth get-ticket":
            return self._cmd_auth_ticket(cmd)
        if prefix == "auth rotate":
            return self._cmd_auth_rotate(cmd)
        if prefix == "auth gens":
            return 0, dict(self.osdmap.auth_gens) if self.osdmap else {}
        if prefix == "auth get-s3-key":
            return self._cmd_auth_s3_key(cmd)
        return -22, f"unknown command {prefix!r}"

    # -- cephx KeyServer role (reference: src/auth/cephx CephxKeyServer;
    # the mon mints service tickets, and rotation is an OSDMap change so
    # it reaches every daemon through paxos + subscriptions) -------------
    def _cluster_secret(self) -> bytes | None:
        """Same parsing + length rules as the messengers
        (CephxAuthenticator) — the mon must never mint tickets under a
        secret the acceptors refuse to load."""
        from ..auth import AuthError, CephxAuthenticator

        s = self.mon.cct.conf.get("auth_shared_secret")
        if not s:
            return None
        try:
            return CephxAuthenticator(s).secret
        except AuthError:
            return None

    def _cmd_auth_ticket(self, cmd: dict) -> tuple[int, object]:
        """`auth get-ticket service=<svc> [entity=<name>] [ttl=<secs>]` —
        mints a sealed service ticket + session key.  Reaches the client
        over its (authenticated, frame-signed) mon session; a cluster
        with auth off can still mint, which tests use to pre-provision."""
        from ..auth import mint_ticket

        secret = self._cluster_secret()
        if secret is None:
            return -1, "no cluster secret configured (auth_shared_secret)"
        service = cmd.get("service", "")
        if not service or not service.isidentifier():
            return -22, f"bad service {service!r}"
        entity = cmd.get("entity", "client.admin")
        ttl = float(cmd.get("ttl")
                    or self.mon.cct.conf.get("auth_service_ticket_ttl"))
        gen = (self.osdmap.auth_gens.get(service, 1)
               if self.osdmap is not None else 1)
        blob, session_key = mint_ticket(secret, entity, service, gen, ttl)
        return 0, {"service": service, "entity": entity, "gen": gen,
                   "ticket": blob, "session_key": session_key}

    def _cmd_auth_s3_key(self, cmd: dict) -> tuple[int, object]:
        """`auth get-s3-key entity=<name>` — S3 credentials DERIVED from
        the cephx cluster secret at the current "rgw" generation, so
        `auth rotate service=rgw` invalidates outstanding keys (the
        RGWUserInfo-credential role without a user database)."""
        from ..auth import derive_s3_secret

        secret = self._cluster_secret()
        if secret is None:
            return -1, "no cluster secret configured (auth_shared_secret)"
        entity = cmd.get("entity", "client.admin")
        if not entity or any(c in entity for c in " /,"):
            return -22, f"bad entity {entity!r}"
        gen = (self.osdmap.auth_gens.get("rgw", 1)
               if self.osdmap is not None else 1)
        return 0, {"access_key": entity, "gen": gen,
                   "secret_key": derive_s3_secret(secret, entity, gen)}

    def _cmd_auth_rotate(self, cmd: dict) -> tuple[int, object]:
        """`auth rotate service=<svc>` — bump the service's key
        generation in the OSDMap.  Daemons accept {gen, gen-1}
        (validate_ticket's grace window), so one rotation starts the
        cutover and a second one cuts stale tickets off entirely."""
        service = cmd.get("service", "")
        if not service or not service.isidentifier():
            return -22, f"bad service {service!r}"
        m = self._pending()
        new_gen = m.auth_gens.get(service, 1) + 1
        m.auth_gens[service] = new_gen
        if not self._propose_map(m):
            return -110, "proposal timed out"
        return 0, {"service": service, "gen": new_gen}

    def _cmd_pool_quota(self, cmd: dict) -> tuple[int, object]:
        """`osd pool set-quota <pool> max_bytes|max_objects <val>`
        (reference: OSDMonitor prepare_command OSD_POOL_SET_QUOTA);
        0 clears."""
        name = cmd.get("name", "")
        fieldn = cmd.get("field", "")
        if fieldn not in ("max_bytes", "max_objects"):
            return -22, f"field {fieldn!r}: want max_bytes|max_objects"
        try:
            value = int(cmd.get("value"))
        except (TypeError, ValueError):
            return -22, "integer value required"
        if value < 0:
            return -22, f"quota {value} must be >= 0"
        m = self._pending()
        pool = next((p for p in m.pools.values() if p.name == name), None)
        if pool is None:
            return -2, f"no pool {name!r}"
        setattr(pool, f"quota_{fieldn}", value)
        if value == 0 and not (pool.quota_max_bytes
                               or pool.quota_max_objects):
            # clearing the last quota lifts a standing full flag
            if "full_quota" in pool.flags:
                pool.flags.remove("full_quota")
        return (0, f"set quota_{fieldn} = {value} on {name!r}") \
            if self._propose_map(m) else (-110, "proposal timed out")

    def _cmd_pool_set(self, cmd: dict) -> tuple[int, object]:
        """`osd pool set <pool> <key> <value>` — pg_num/pgp_num/size
        (reference: OSDMonitor::prepare_command_pool_set).  pg_num may
        only grow (splits; merges are out of scope), and pgp_num follows
        pg_num so placement tracks the split immediately."""
        name = cmd.get("name", "")
        key = cmd.get("key", "")
        try:
            value = int(cmd.get("value"))
        except (TypeError, ValueError):
            return -22, f"pool set {key}: integer value required"
        m = self._pending()
        pool = next((p for p in m.pools.values() if p.name == name), None)
        if pool is None:
            return -2, f"no pool {name!r}"
        if key == "pg_num":
            if value < pool.pg_num:
                return -22, (
                    f"pg_num {value} < current {pool.pg_num}: "
                    "merges not supported"
                )
            if value == pool.pg_num:
                return 0, f"pg_num already {value}"
            per_osd = self.mon.cct.conf.get("mon_max_pg_per_osd")
            n_osds = max(1, sum(1 for o in range(m.max_osd) if m.is_up(o)))
            if value * pool.size > per_osd * n_osds:
                return -34, (  # ERANGE, as the reference returns
                    f"pg_num {value} would exceed "
                    f"mon_max_pg_per_osd {per_osd}"
                )
            pool.pg_num = value
            pool.pgp_num = value
        elif key == "pgp_num":
            if not (1 <= value <= pool.pg_num):
                return -22, (
                    f"pgp_num {value} must be in [1, pg_num={pool.pg_num}]"
                )
            pool.pgp_num = value
        elif key == "size":
            if pool.type == PG_POOL_ERASURE:
                # EC width is k+m from the profile, not a free knob
                return -95, "cannot change size of an erasure-coded pool"
            if not (1 <= value <= 10):
                return -22, f"size {value} out of range"
            pool.size = value
            # keep the derived write quorum consistent (the same rule
            # PGPool.__post_init__ applies at creation)
            pool.min_size = value - value // 2
        elif key == "target_max_objects":
            # cache-tier agent threshold (reference: pg_pool_t::
            # target_max_objects driving agent_choose_mode)
            if value < 0:
                return -22, "target_max_objects must be >= 0"
            pool.target_max_objects = value
        else:
            return -22, f"unknown pool key {key!r}"
        return (0, f"set pool {name} {key} to {value}") \
            if self._propose_map(m) else (-110, "proposal timed out")

    def _cmd_pool_snap(self, create: bool, cmd: dict) -> tuple[int, object]:
        """`osd pool mksnap/rmsnap <pool> <snapname>` (reference:
        OSDMonitor's pool-snap commands updating pg_pool_t::snaps)."""
        name = cmd.get("name", "")
        snapname = cmd.get("snapname", "")
        if not snapname:
            return -22, "snap name required"
        m = self._pending()
        pool = next((p for p in m.pools.values() if p.name == name), None)
        if pool is None:
            return -2, f"no pool {name!r}"
        if create:
            if snapname in pool.snaps.values():
                return -17, f"snap {snapname!r} exists"
            pool.snap_seq += 1
            pool.snaps[pool.snap_seq] = snapname
            result = {"snapid": pool.snap_seq}
        else:
            sid = next(
                (i for i, n in pool.snaps.items() if n == snapname), None
            )
            if sid is None:
                return -2, f"no snap {snapname!r}"
            del pool.snaps[sid]
            result = {"removed": sid}
        return (0, result) if self._propose_map(m) else \
            (-110, "proposal timed out")

    def _cmd_tier(self, sub: str, cmd: dict) -> tuple[int, object]:
        """`osd tier add/remove/cache-mode/set-overlay/remove-overlay`
        (reference: OSDMonitor::prepare_command's "osd tier *" family
        mutating pg_pool_t tier fields).  `pool` names the BASE pool and
        `tierpool` the cache for add/remove/set-overlay; cache-mode takes
        the cache pool in `pool`."""
        m = self._pending()

        def by_name(n):
            return next((p for p in m.pools.values() if p.name == n), None)

        pool = by_name(cmd.get("pool", ""))
        if pool is None:
            return -2, f"no pool {cmd.get('pool')!r}"
        if sub in ("add", "remove", "set-overlay"):
            tierpool = by_name(cmd.get("tierpool", ""))
            if tierpool is None:
                return -2, f"no tier pool {cmd.get('tierpool')!r}"
        if sub == "add":
            if tierpool.pool_id == pool.pool_id:
                return -22, "pool cannot tier itself"
            if tierpool.tier_of >= 0 and tierpool.tier_of != pool.pool_id:
                return -16, f"pool {tierpool.name!r} is already a tier"
            if tierpool.tiers or pool.tier_of >= 0:
                return -22, "multi-level tiering not supported"
            if tierpool.type == PG_POOL_ERASURE:
                # the cache must serve arbitrary overwrites cheaply
                return -95, "an erasure-coded pool cannot be a cache tier"
            tierpool.tier_of = pool.pool_id
            if tierpool.pool_id not in pool.tiers:
                pool.tiers.append(tierpool.pool_id)
            result = f"pool {tierpool.name!r} is now a tier of {pool.name!r}"
        elif sub == "remove":
            if tierpool.tier_of != pool.pool_id:
                return -2, f"pool {tierpool.name!r} is not a tier of {pool.name!r}"
            if pool.read_tier == tierpool.pool_id or \
                    pool.write_tier == tierpool.pool_id:
                return -16, "remove the overlay first"
            tierpool.tier_of = -1
            tierpool.cache_mode = "none"
            pool.tiers = [t for t in pool.tiers if t != tierpool.pool_id]
            result = f"pool {tierpool.name!r} removed as tier of {pool.name!r}"
        elif sub == "cache-mode":
            mode = cmd.get("mode", "")
            if mode not in ("none", "writeback", "readproxy"):
                return -22, f"unknown cache mode {mode!r}"
            if pool.tier_of < 0:
                return -22, f"pool {pool.name!r} is not a tier"
            if mode == "none":
                # with the overlay still routing base I/O here, mode none
                # would bypass promotion and make every non-cached base
                # object unreadable (the reference refuses this too)
                basep = m.pools.get(pool.tier_of)
                if basep is not None and pool.pool_id in (
                    basep.read_tier, basep.write_tier
                ):
                    return -16, (
                        f"pool {pool.name!r} is the overlay for "
                        f"{basep.name!r}; remove-overlay first"
                    )
            pool.cache_mode = mode
            result = f"set cache-mode of {pool.name!r} to {mode}"
        elif sub == "set-overlay":
            if tierpool.tier_of != pool.pool_id:
                return -22, f"pool {tierpool.name!r} is not a tier of {pool.name!r}"
            if tierpool.cache_mode == "none":
                # mirror of the cache-mode-none guard above (advisor r4):
                # an overlay onto a mode-none tier redirects all base I/O
                # to a cache whose OSD front-end is disabled — reads of
                # non-cached objects 404 and writes land tier-less
                return -16, (
                    f"pool {tierpool.name!r} has cache-mode none; set "
                    f"cache-mode first"
                )
            pool.read_tier = pool.write_tier = tierpool.pool_id
            result = f"overlay for {pool.name!r} is now {tierpool.name!r}"
        elif sub == "remove-overlay":
            pool.read_tier = pool.write_tier = -1
            result = f"overlay for {pool.name!r} removed"
        else:
            return -22, f"unknown tier command {sub!r}"
        return (0, result) if self._propose_map(m) else \
            (-110, "proposal timed out")

    def _cmd_tree(self) -> list[dict]:
        """reference: `ceph osd tree` (OSDMonitor dumping the CRUSH
        hierarchy annotated with up/in state)."""
        m = self.osdmap
        if m is None:
            return []
        w = m.crush
        rows: list[dict] = []

        def walk(item: int, depth: int) -> None:
            if item >= 0:
                rows.append({
                    "id": item,
                    "name": f"osd.{item}",
                    "type": "osd",
                    "depth": depth,
                    "reweight": m.osd_weight[item] / 0x10000
                    if item < m.max_osd else 0.0,
                    "status": "up" if m.is_up(item) else "down",
                })
                return
            b = w.map.buckets[item]
            rows.append({
                "id": item,
                "name": w.name_of(item),
                "type": w.type_name(b.type),
                "depth": depth,
                "weight": b.weight / 0x10000,
            })
            for child in b.items:
                walk(child, depth + 1)

        roots = set(w.map.buckets) - {
            c for b in w.map.buckets.values() for c in b.items if c < 0
        }
        for root in sorted(roots, reverse=True):
            walk(root, 0)
        return rows

    def _cmd_perf_history(self, cmd: dict) -> tuple[int, object]:
        """`ceph perf history [name] [daemon]` — recent samples of the
        digest's perf series (cephmeter; reference: the reads a
        closed-loop controller does against its own series, served
        mon-side from the MMonMgrReport digest like df/pg dump)."""
        ts_digest = getattr(self, "mgr_digest", None)
        if ts_digest is None:
            return -2, "no mgr digest yet (is the mgr running?)"
        ts, digest = ts_digest
        hist = digest.get("perf_history")
        if not isinstance(hist, dict) or not hist.get("daemons"):
            return -2, "digest carries no perf history yet"
        name = cmd.get("name")
        daemon = cmd.get("daemon")
        daemons = {}
        for d, series in (hist.get("daemons") or {}).items():
            if daemon is not None and d != daemon:
                continue
            keep = {n: s for n, s in series.items()
                    if name is None or n == name}
            if keep:
                daemons[d] = keep
        if (name is not None or daemon is not None) and not daemons:
            return -2, (f"no history for name={name!r} daemon={daemon!r}; "
                        f"names: {hist.get('names')}")
        return 0, {
            "digest_age_seconds": round(time.monotonic() - ts, 1),
            "names": hist.get("names"),
            "samples_per_series": hist.get("samples_per_series"),
            "daemons": daemons,
        }

    def _cmd_progress(self) -> tuple[int, object]:
        """`ceph progress` (cephheal; reference: the mgr progress
        module's `ceph progress` output) — per-PG recovery/backfill
        events with completion fraction, drain rate, and ETA, served
        mon-side from the digest like perf history."""
        ts_digest = getattr(self, "mgr_digest", None)
        if ts_digest is None:
            return -2, "no mgr digest yet (is the mgr running?)"
        ts, digest = ts_digest
        prog = digest.get("progress")
        if not isinstance(prog, dict):
            return -2, ("digest carries no progress data yet (is the "
                        "progress module hosted?)")
        return 0, {
            "digest_age_seconds": round(time.monotonic() - ts, 1),
            "events": prog.get("events") or [],
            "completed": prog.get("completed") or [],
            "stalled": prog.get("stalled") or [],
            "failing": prog.get("failing") or {},
        }

    def _cmd_balancer_status(self) -> tuple[int, object]:
        """`ceph balancer status` (cephplace; reference: the balancer
        module's `balancer status` output) — passes, move outcomes,
        pre/post skew scores, last error — served mon-side from the
        digest like perf history."""
        ts_digest = getattr(self, "mgr_digest", None)
        if ts_digest is None:
            return -2, "no mgr digest yet (is the mgr running?)"
        ts, digest = ts_digest
        bal = digest.get("balancer")
        if not isinstance(bal, dict):
            return -2, ("digest carries no balancer data yet (is the "
                        "balancer module hosted?)")
        return 0, {
            "digest_age_seconds": round(time.monotonic() - ts, 1),
            **bal,
        }

    def _cmd_placement_diff(self) -> tuple[int, object]:
        """`ceph placement diff` (cephplace) — the latest osdmap-epoch
        remap forecast (PGs/shards remapped, predicted bytes-to-move,
        misplaced fraction) plus the current skew snapshot, served
        mon-side from the digest."""
        ts_digest = getattr(self, "mgr_digest", None)
        if ts_digest is None:
            return -2, "no mgr digest yet (is the mgr running?)"
        ts, digest = ts_digest
        pl = digest.get("placement")
        if not isinstance(pl, dict):
            return -2, ("digest carries no placement data yet (is the "
                        "placement module hosted?)")
        return 0, {
            "digest_age_seconds": round(time.monotonic() - ts, 1),
            "cluster": pl.get("cluster"),
            "pools": pl.get("pools") or [],
            "imbalanced": pl.get("imbalanced") or [],
            "diff": pl.get("diff"),
        }

    def _cmd_from_digest(self, prefix: str) -> tuple[int, object]:
        """Serve `df`/`osd df`/`pg dump` from the mgr's streamed digest
        (reference: MgrStatMonitor::preprocess_statfs / PGMap dumps).
        pg-dump placement columns come live from the mon's own map —
        only state/version need the digest."""
        ts_digest = getattr(self, "mgr_digest", None)
        if ts_digest is None:
            # NOT -11: MonClient treats EAGAIN as "leader still syncing"
            # and retry-loops into a misleading timeout
            return -2, "no mgr digest yet (is the mgr running?)"
        ts, digest = ts_digest
        age = time.monotonic() - ts
        if prefix == "df":
            out = dict(digest.get("df") or {})
            out["digest_age_seconds"] = round(age, 1)
            return 0, out
        if prefix == "osd df":
            out = dict(digest.get("osd_df") or {})
            out["digest_age_seconds"] = round(age, 1)
            return 0, out
        m = self.osdmap
        pg_info = digest.get("pg_info") or {}
        rows = []
        for pid, pool in sorted(m.pools.items()):
            for ps in range(pool.pg_num):
                up, upp, acting, prim = m.pg_to_up_acting_osds(pid, ps)
                pgid = f"{pid}.{ps}"
                info = pg_info.get(pgid) or {}
                rows.append({
                    "pgid": pgid,
                    "state": info.get("state", "unknown"),
                    "version": info.get("version", 0),
                    "up": up, "up_primary": upp,
                    "acting": acting, "acting_primary": prim,
                })
        return 0, {"pg_stats": rows, "digest_age_seconds": round(age, 1)}

    def _stat(self) -> dict:
        m = self.osdmap
        if m is None:
            return {"num_osds": 0}
        up = sum(1 for o in range(m.max_osd) if m.is_up(o))
        inn = sum(1 for o in range(m.max_osd) if m.osd_weight[o] != 0)
        return {
            "epoch": m.epoch, "num_osds": m.max_osd, "num_up_osds": up,
            "num_in_osds": inn, "flags": sorted(m.flags),
        }

    def _cmd_profile_set(self, cmd: dict) -> tuple[int, object]:
        name = cmd.get("name")
        if not name:
            return -22, "profile name required"
        profile = dict(cmd.get("profile", {}))
        profile.setdefault("plugin", "jax")
        # validation = instantiation through the registry, the reference's
        # exact mechanism (OSDMonitor validating plugin=jax end to end)
        try:
            codec = ErasureCodePluginRegistry.instance().factory(profile)
        except InvalidProfile as e:
            return -22, str(e)
        m = self._pending()
        if name in m.ec_profiles and m.ec_profiles[name] != profile:
            in_use = any(p.ec_profile == name for p in m.pools.values())
            if in_use and not cmd.get("force"):
                return -1, f"profile {name!r} is in use; --force to override"
        m.ec_profiles[name] = profile
        if not self._propose_map(m):
            return -110, "proposal timed out"
        return 0, {
            "name": name, "profile": profile,
            "k": codec.get_data_chunk_count(),
            "m": codec.get_chunk_count() - codec.get_data_chunk_count(),
        }

    def _cmd_ok_to_stop(self, cmd: dict) -> tuple[int, object]:
        """Would stopping these OSDs leave every PG at or above
        min_size?  Pure map arithmetic (reference: OSDMonitor
        check_pg_num / ok-to-stop returning EBUSY when data
        availability would be lost)."""
        try:
            ids = {int(i) for i in cmd.get("ids", [])}
        except (TypeError, ValueError):
            return -22, "ids must be osd numbers"
        if not ids:
            return -22, "no osd ids given"
        m = self.osdmap
        unsafe = []
        for pid, pool in m.pools.items():
            for ps in range(pool.pg_num):
                _up, _upp, acting, _p = m.pg_to_up_acting_osds(pid, ps)
                left = [o for o in acting if o not in ids and o >= 0]
                if acting and len(left) < pool.min_size:
                    unsafe.append(f"{pid}.{ps}")
        if unsafe:
            return -16, {
                "ok_to_stop": False,
                "unsafe_pgs": unsafe[:32],
                "num_unsafe": len(unsafe),
            }
        return 0, {"ok_to_stop": True, "osds": sorted(ids)}

    def _cmd_safe_to_destroy(self, cmd: dict) -> tuple[int, object]:
        """Destroying is safe once the OSD hosts no PGs: it must be out
        of every acting set AND its last mgr-reported pg count must be
        zero (reference: OSDMonitor osd safe-to-destroy)."""
        try:
            osd = int(cmd.get("id", -1))
        except (TypeError, ValueError):
            return -22, "bad osd id"
        m = self.osdmap
        if not (0 <= osd < m.max_osd) or not m.exists(osd):
            return -2, f"osd.{osd} does not exist"
        mapped = []
        for pid, pool in m.pools.items():
            for ps in range(pool.pg_num):
                _up, _upp, acting, _p = m.pg_to_up_acting_osds(pid, ps)
                if osd in acting:
                    mapped.append(f"{pid}.{ps}")
        ts_digest = getattr(self, "mgr_digest", None)
        reported = None
        if ts_digest is not None:
            for row in (ts_digest[1].get("osd_df") or {}).get("nodes", []):
                if row.get("id") == osd:
                    reported = row.get("pgs")
        if mapped:
            return -16, {"safe": False, "mapped_pgs": len(mapped)}
        if reported is None:
            # no mgr stats: refuse rather than approve blind — the OSD
            # may still hold data being drained (reference returns
            # EAGAIN "no osd_stat"; -11 would make MonClient retry-loop)
            return -16, {"safe": False,
                         "reason": "no mgr pg report for this osd "
                                   "(is the mgr running?)"}
        if reported != 0:
            return -16, {"safe": False, "reported_pgs": reported}
        return 0, {"safe": True, "osd": osd}

    def _cmd_pool_application(self, cmd: dict,
                              enable: bool) -> tuple[int, object]:
        """reference: OSDMonitor prepare_command_pool_application —
        tag a pool with the client application using it (rbd/rgw/
        cephfs/rados); untagged pools raise POOL_APP_NOT_ENABLED."""
        app = cmd.get("app", "")
        if not app:
            return -22, "application name required"
        m = self._pending()
        pool = next((p for p in m.pools.values()
                     if p.name == cmd.get("pool")), None)
        if pool is None:
            return -2, f"no pool {cmd.get('pool')!r}"
        if enable and app in pool.application:
            return 0, f"application {app!r} already enabled"
        if not enable and app not in pool.application:
            return 0, f"application {app!r} not enabled"
        if enable:
            # only reached when `app` is NOT yet enabled (early return
            # above): the guard fires on "a different app already set"
            if pool.application \
                    and cmd.get("sure") != "--yes-i-really-mean-it":
                other = next(iter(pool.application))
                return -1, (f"pool {pool.name!r} already has application "
                            f"{other!r}; pass --yes-i-really-mean-it to "
                            f"enable a second one")
            pool.application[app] = {}
        else:
            pool.application.pop(app, None)
        verb = "enabled on" if enable else "disabled on"
        return (0, f"application {app!r} {verb} pool {pool.name!r}") \
            if self._propose_map(m) else (-110, "proposal timed out")

    def _cmd_pool_rm(self, cmd: dict) -> tuple[int, object]:
        """`osd pool rm <name> <name> --yes-i-really-really-mean-it`
        (reference: OSDMonitor prepare_command OSD_POOL_DELETE with its
        double-name + sure-flag safety).  OSDs garbage-collect the
        pool's PG collections when the map lands."""
        name = cmd.get("name", "")
        if cmd.get("name2") != name:
            return -1, "pool name must be given twice"
        if cmd.get("sure") != "--yes-i-really-really-mean-it":
            return -1, ("this will PERMANENTLY DESTROY all data; pass "
                        "sure=--yes-i-really-really-mean-it")
        m = self._pending()
        pool = next((p for p in m.pools.values() if p.name == name), None)
        if pool is None:
            return -2, f"no pool {name!r}"
        if pool.tiers:
            return -16, f"pool {name!r} has cache tiers; remove them first"
        if pool.tier_of >= 0:
            return -16, (f"pool {name!r} is a cache tier; "
                         f"`osd tier remove` first")
        pid = pool.pool_id
        del m.pools[pid]
        # scrub per-PG overrides keyed by (pool, ps) — a later pool must
        # not inherit them (reference: OSDMonitor clean_pg_upmaps)
        for ovr in (m.pg_upmap, m.pg_upmap_items, m.pg_temp,
                    m.primary_temp):
            for key in [k for k in ovr if k[0] == pid]:
                del ovr[key]
        return (0, f"pool {name!r} removed") \
            if self._propose_map(m) else (-110, "proposal timed out")

    def _cmd_pool_create(self, cmd: dict) -> tuple[int, object]:
        name = cmd.get("name")
        if not name:
            return -22, "pool name required"
        m = self._pending()
        if any(p.name == name for p in m.pools.values()):
            return -17, f"pool {name!r} already exists"
        pg_num = int(cmd.get("pg_num") or self.mon.cct.conf.get("osd_pool_default_pg_num"))
        pool_id = max(m.max_pool_id, max(m.pools, default=0)) + 1
        kind = cmd.get("pool_type", "replicated")
        # pg-per-osd sanity (reference: mon_max_pg_per_osd check)
        up = sum(1 for o in range(m.max_osd) if m.is_up(o)) or 1
        total_pgs = sum(p.pg_num * p.size for p in m.pools.values())
        limit = self.mon.cct.conf.get("mon_max_pg_per_osd")
        if kind == "erasure":
            prof_name = cmd.get("erasure_code_profile", "default")
            profile = m.ec_profiles.get(prof_name)
            if profile is None:
                return -2, f"no erasure-code profile {prof_name!r}"
            try:
                codec = ErasureCodePluginRegistry.instance().factory(profile)
            except InvalidProfile as e:
                return -22, str(e)
            size = codec.get_chunk_count()
            if (total_pgs + pg_num * size) / up > limit:
                return -34, f"would exceed mon_max_pg_per_osd {limit}"
            # EC crush rule: indep over the profile's failure domain
            # (reference: OSDMonitor::crush_rule_create_erasure)
            rule_id = self._create_rule(
                m, f"{name}_rule",
                profile.get("crush-failure-domain", "host"),
                firstn=False,
            )
            pool = m.create_pool(
                pool_id, pg_num=pg_num, size=size, crush_rule=rule_id,
                type=PG_POOL_ERASURE, name=name, ec_profile=prof_name,
            )
        else:
            size = int(cmd.get("size") or self.mon.cct.conf.get("osd_pool_default_size"))
            if (total_pgs + pg_num * size) / up > limit:
                return -34, f"would exceed mon_max_pg_per_osd {limit}"
            rule_id = self._create_rule(
                m, f"{name}_rule", cmd.get("crush_failure_domain", "host"),
                firstn=True,
            )
            extra = {}
            try:
                if cmd.get("min_size") is not None:
                    ms = int(cmd["min_size"])
                    if not (1 <= ms <= size):
                        return -22, f"min_size {ms} out of [1, size={size}]"
                    extra["min_size"] = ms
                else:
                    # osd_pool_default_min_size: 0 keeps the derived
                    # size - size//2 quorum (PGPool.__post_init__)
                    dms = int(self.mon.cct.conf.get(
                        "osd_pool_default_min_size"))
                    if dms:
                        extra["min_size"] = max(1, min(dms, size))
            except (TypeError, ValueError):
                return -22, "integer min_size required"
            pool = m.create_pool(
                pool_id, pg_num=pg_num, size=size, crush_rule=rule_id,
                type=PG_POOL_REPLICATED, name=name, **extra,
            )
        if not self._propose_map(m):
            return -110, "proposal timed out"
        return 0, {"pool_id": pool.pool_id, "name": name, "size": size,
                   "pg_num": pg_num, "crush_rule": rule_id}

    def _create_rule(self, m: OSDMap, name: str, failure_domain: str,
                     firstn: bool) -> int:
        # reuse an existing rule with identical shape if one exists
        rule_id = max(m.crush.map.rules, default=-1) + 1
        try:
            ftype = m.crush.type_id(failure_domain)
        except KeyError:
            ftype = 1  # host
        add_simple_rule(m.crush.map, -1, ftype, rule_id=rule_id, firstn=firstn)
        m.crush.invalidate()
        return rule_id

    def _cmd_osd_state(self, action: str, cmd: dict) -> tuple[int, object]:
        # `ids` marks a whole cohort in ONE map epoch / one proposal —
        # a cascading failure is one event, and a thousand-OSD storm
        # cannot afford a paxos round trip per member (reference: a
        # real mon batches many down marks into a single epoch too)
        raw = cmd.get("ids") if cmd.get("ids") is not None \
            else [cmd.get("id")]
        try:
            ids = [int(o) for o in raw]
        except (TypeError, ValueError):
            return -22, f"bad osd ids {raw!r}"
        max_osd = self.osdmap.max_osd if self.osdmap else 0
        if not ids or any(not (0 <= o < max_osd) for o in ids):
            return -22, f"bad osd ids {raw!r}"
        m = self._pending()
        for osd in ids:
            if action == "down":
                m.mark_down(osd)
                self._down_stamp[osd] = time.monotonic()
            elif action == "out":
                m.mark_out(osd)
            else:
                m.mark_in(osd)
        if not self._propose_map(m):
            return -110, "proposal timed out"
        if len(ids) == 1:
            return 0, f"marked {action} osd.{ids[0]}"
        return 0, f"marked {action} {len(ids)} osds"

    def _cmd_upmap_items(self, cmd: dict) -> tuple[int, object]:
        try:
            pool_id, ps = int(cmd["pool"]), int(cmd["ps"])
            pairs = [(int(a), int(b)) for a, b in cmd["mappings"]]
        except (KeyError, TypeError, ValueError) as e:
            return -22, f"bad pg-upmap-items args: {e}"
        m = self._pending()
        if pool_id not in m.pools:
            return -2, f"no pool {pool_id}"
        if pairs:
            m.pg_upmap_items[(pool_id, ps)] = pairs
        else:
            m.pg_upmap_items.pop((pool_id, ps), None)
        if not self._propose_map(m):
            return -110, "proposal timed out"
        return 0, f"set {len(pairs)} upmap items on {pool_id}.{ps:x}"
