"""Deployment tooling (reference: src/cephadm; SURVEY.md §2.8)."""
