"""Deployment host process — runs a spec's daemons until killed
(reference role: the systemd units cephadm writes per daemon; here one
supervisor process hosts the cluster, matching the framework's
threaded-daemon model).

Invoked by cephadm bootstrap as a detached subprocess:

    python -m ceph_tpu.deploy.host --data-dir DIR

Reads DIR/spec.json, builds the cluster, writes DIR/cluster.json
(mon addresses, service endpoints, pid), then idles until SIGTERM.
"""
from __future__ import annotations

import argparse
import faulthandler
import json
import os
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", required=True)
    args = ap.parse_args(argv)

    # SIGUSR1 -> all-thread stack dump on stderr (the host.log): a host
    # that won't die under SIGTERM can be diagnosed without a debugger
    faulthandler.register(signal.SIGUSR1, all_threads=True)

    with open(os.path.join(args.data_dir, "spec.json")) as f:
        spec = json.load(f)

    # daemons default to the CPU backend: placement uses the scalar
    # mapper, and a supervisor must not block on TPU-tunnel availability.
    # A spec can opt the balancer/EC offload onto the device with
    # {"jax_platform": "axon"}.
    import jax

    jax.config.update("jax_platforms", spec.get("jax_platform", "cpu"))

    from ..qa.vstart import LocalCluster

    osd_spec = spec.get("osd") or {}
    conf = dict(spec.get("conf") or {})
    if osd_spec.get("objectstore"):
        conf["objectstore"] = osd_spec["objectstore"]
        conf.setdefault("osd_data", os.path.join(args.data_dir, "osd"))
    cluster = LocalCluster(
        n_mons=(spec.get("mon") or {}).get("count", 1),
        n_osds=osd_spec.get("count", 3),
        conf_overrides=conf,
        with_mgr=(spec.get("mgr") or {}).get("count", 0) > 0,
        with_mds=(spec.get("mds") or {}).get("count", 0) > 0,
    )
    cluster.start()
    state = {
        "pid": os.getpid(),
        "mon_addrs": cluster.mon_addrs,
        "daemons": (
            [f"mon.{n}" for n in cluster.mons]
            + [f"osd.{i}" for i in cluster.osds]
        ),
    }
    if cluster.mgr is not None:
        state["daemons"].append("mgr.x")
    if cluster.mds is not None:
        state["daemons"].append("mds.0")
    if (spec.get("rgw") or {}).get("count", 0) > 0:
        rgw = cluster.start_rgw()
        state["rgw_addr"] = list(rgw.addr)
        state["daemons"].append("rgw.0")

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    # state file last: its presence tells bootstrap the cluster is up
    tmp = os.path.join(args.data_dir, ".cluster.json.tmp")
    with open(tmp, "w") as f:
        json.dump(state, f)
    os.replace(tmp, os.path.join(args.data_dir, "cluster.json"))

    stop.wait()
    cluster.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
