"""cephadm-analog deploy CLI (reference: src/cephadm/cephadm.py —
bootstrap / ls / rm-cluster / shell; SURVEY.md §2.8).

The reference deploys containerized daemons under systemd; this analog
deploys the framework's threaded daemons under one detached supervisor
process per cluster (deploy/host.py), tracked by a state file in the
cluster's data dir.

    python -m ceph_tpu.deploy.cephadm bootstrap --data-dir DIR \
        [--spec spec.json]
    python -m ceph_tpu.deploy.cephadm ls --data-dir DIR
    python -m ceph_tpu.deploy.cephadm ps --data-dir DIR
    python -m ceph_tpu.deploy.cephadm shell --data-dir DIR -- \
        osd pool create mypool
    python -m ceph_tpu.deploy.cephadm rm-cluster --data-dir DIR

Spec (JSON; every section optional):

    {"mon": {"count": 3}, "mgr": {"count": 1},
     "osd": {"count": 6, "objectstore": "bluestore"},
     "mds": {"count": 1}, "rgw": {"count": 1},
     "conf": {"osd_pool_default_size": 2}}
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time

DEFAULT_SPEC = {"mon": {"count": 1}, "osd": {"count": 3}}


def _state_path(data_dir: str) -> str:
    return os.path.join(data_dir, "cluster.json")


def _load_state(data_dir: str) -> dict | None:
    try:
        with open(_state_path(data_dir)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _alive(pid: int) -> bool:
    # When bootstrap ran IN-PROCESS (the test harness calls main() as a
    # function), the detached host is a child of THIS process: once it
    # exits it lingers as a zombie that still answers kill(0), and
    # rm-cluster would burn its whole 15 s deadline "waiting" for a
    # corpse.  Reap it if it is ours, then check /proc for the Z state
    # in case someone else holds the wait.
    try:
        done, _status = os.waitpid(pid, os.WNOHANG)
        if done == pid:
            return False
    except (ChildProcessError, OSError):
        pass   # not our child (the normal CLI case)
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        # EPERM: the pid exists but belongs to another user — very much
        # alive; treating it as dead would let rm-cluster rmtree the data
        # dir out from under a running process
        return True
    try:
        with open(f"/proc/{pid}/stat") as f:
            if f.read().rsplit(")", 1)[-1].split()[0] == "Z":
                return False   # zombie: exited, just unreaped
    except OSError:
        pass   # no /proc (non-linux): fall through to "alive"
    return True


def cmd_bootstrap(args, out) -> int:
    os.makedirs(args.data_dir, exist_ok=True)
    if _load_state(args.data_dir):
        print(f"cluster already deployed in {args.data_dir}", file=out)
        return 1
    if args.spec:
        with open(args.spec) as f:
            spec = json.load(f)
    else:
        spec = DEFAULT_SPEC
    with open(os.path.join(args.data_dir, "spec.json"), "w") as f:
        json.dump(spec, f, indent=2)
    log = open(os.path.join(args.data_dir, "host.log"), "ab")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.deploy.host",
             "--data-dir", args.data_dir],
            stdout=log, stderr=log,
            start_new_session=True,  # survives the CLI exiting
            cwd=os.path.dirname(
                os.path.dirname(os.path.dirname(__file__))),
        )
    finally:
        # the child holds its own dup of the descriptor once spawned;
        # ours only pins the fd (and leaks if Popen raises)
        log.close()
    deadline = time.time() + args.timeout
    while time.time() < deadline:
        state = _load_state(args.data_dir)
        if state:
            mons = ",".join(f"{h}:{p}" for h, p in state["mon_addrs"])
            print(f"cluster up: mon {mons}", file=out)
            print(f"daemons: {' '.join(state['daemons'])}", file=out)
            if "rgw_addr" in state:
                h, p = state["rgw_addr"]
                print(f"rgw: http://{h}:{p}", file=out)
            return 0
        if proc.poll() is not None:
            print("host process died during bootstrap (see host.log)",
                  file=out)
            return 1
        time.sleep(0.2)
    proc.terminate()
    print("bootstrap timed out", file=out)
    return 1


def cmd_ls(args, out) -> int:
    state = _load_state(args.data_dir)
    if not state:
        print("no cluster deployed", file=out)
        return 1
    for d in state["daemons"]:
        print(d, file=out)
    return 0


def cmd_ps(args, out) -> int:
    state = _load_state(args.data_dir)
    if not state:
        print("no cluster deployed", file=out)
        return 1
    up = _alive(state["pid"])
    print(f"pid {state['pid']}: {'running' if up else 'DEAD'} "
          f"({len(state['daemons'])} daemons)", file=out)
    return 0 if up else 2


def cmd_shell(args, out) -> int:
    """Run a `ceph` CLI command against the deployed cluster (reference:
    cephadm shell -- ceph ...)."""
    state = _load_state(args.data_dir)
    if not state:
        print("no cluster deployed", file=out)
        return 1
    from ..tools.ceph_cli import main as ceph_main

    mons = ",".join(f"{h}:{p}" for h, p in state["mon_addrs"])
    return ceph_main(["-m", mons] + args.words, out=out)


def cmd_rm_cluster(args, out) -> int:
    state = _load_state(args.data_dir)
    if state and _alive(state["pid"]):
        os.kill(state["pid"], signal.SIGTERM)
        deadline = time.time() + 15
        while _alive(state["pid"]) and time.time() < deadline:
            time.sleep(0.1)
        if _alive(state["pid"]):
            os.kill(state["pid"], signal.SIGKILL)
    if os.path.isdir(args.data_dir):
        shutil.rmtree(args.data_dir, ignore_errors=True)
    print("cluster removed", file=out)
    return 0


def main(argv=None, out=sys.stdout) -> int:
    ap = argparse.ArgumentParser(prog="cephadm")
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("bootstrap", "ls", "ps", "rm-cluster", "shell"):
        p = sub.add_parser(name)
        p.add_argument("--data-dir", required=True)
        if name == "bootstrap":
            p.add_argument("--spec")
            p.add_argument("--timeout", type=float, default=60.0)
        if name == "shell":
            p.add_argument("words", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if args.cmd == "shell":
        # strip a leading "--" separator
        if args.words and args.words[0] == "--":
            args.words = args.words[1:]
    return {
        "bootstrap": cmd_bootstrap,
        "ls": cmd_ls,
        "ps": cmd_ps,
        "rm-cluster": cmd_rm_cluster,
        "shell": cmd_shell,
    }[args.cmd](args, out)


if __name__ == "__main__":
    sys.exit(main())
