"""Bitplane GF(2^8) codec — the TPU-native formulation of RS encode/decode.

Replaces the role of gf-complete's SIMD GF byte kernels (reference:
src/erasure-code/jerasure/gf-complete :: gf_w8 SSE/AVX paths, and
src/isa-l :: ec_encode_data): instead of per-byte GF multiplies (TPU has no
byte multiplier and gathers are slow), every GF(2^8) multiply-by-constant is
expanded once, on the host, into its 8x8 GF(2) bitmatrix
(ceph_tpu.gf.matrix.matrix_to_bitmatrix — the trick jerasure's Cauchy path
uses for XOR scheduling, reference: jerasure.c :: jerasure_matrix_to_bitmatrix).
The whole m x k coding matrix becomes one (m*8) x (k*8) 0/1 matrix B, and

    parity_bitplanes = (B @ data_bitplanes) mod 2

is a single int8 matmul on the MXU with contraction depth k*8 — exactly the
"large, batched" shape XLA tiles well.  Data layout is whole shards
[k, shard_len] (chunk j of every stripe is contiguous on shard j, mirroring
ECBackend's shard layout, reference: src/osd/ECUtil.h :: stripe_info_t), so
one matmul covers every stripe of an object, and shard_len is the batch axis
sharded across chips by ceph_tpu.parallel.

Bit-exactness: all ops are exact integer ops; tests assert parity bytes are
identical to the C++ oracle (native/gf_oracle.cc).
"""
from __future__ import annotations

import hashlib
import os
import sys
import time
from collections import OrderedDict
from functools import lru_cache, partial
from threading import Lock

import jax
import jax.numpy as jnp
import numpy as np

from ..common.kernel_telemetry import SENTINEL, TELEMETRY
from ..common.tracer import tracepoint
from ..gf.matrix import decode_matrix_for, matrix_to_bitmatrix, systematic_generator

_BIT_IDX = np.arange(8, dtype=np.uint8)


def unpack_bitplanes(chunks: jnp.ndarray) -> jnp.ndarray:
    """[n, L] uint8 bytes -> [n*8, L] int8 bitplanes (plane n*8+l = bit l)."""
    n, L = chunks.shape
    bits = (chunks[:, None, :] >> jnp.asarray(_BIT_IDX)[None, :, None]) & 1
    return bits.reshape(n * 8, L).astype(jnp.int8)


def pack_bitplanes(bits: jnp.ndarray) -> jnp.ndarray:
    """[n*8, L] 0/1 -> [n, L] uint8."""
    n8, L = bits.shape
    b = bits.reshape(n8 // 8, 8, L).astype(jnp.uint8)
    return (b << jnp.asarray(_BIT_IDX)[None, :, None]).sum(axis=1, dtype=jnp.uint8)


def _bitmatrix_body(B: jnp.ndarray, chunks: jnp.ndarray) -> jnp.ndarray:
    """(rows*8 x n*8) GF(2) matrix times [n, L] byte chunks -> [rows, L]
    — THE encode math, written once and wrapped below (plain, donated,
    and fused variants must never diverge byte-wise)."""
    bits = unpack_bitplanes(chunks)
    acc = jax.lax.dot_general(
        B,
        bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return pack_bitplanes((acc & 1).astype(jnp.uint8))


_apply_bitmatrix = jax.jit(_bitmatrix_body)

#: _apply_bitmatrix with the packed stripe buffer DONATED (SNIPPETS.md
#: [1]/[3] `donation_vector` machinery behind `donate_argnums`): a
#: flush's input buffer is recycled for the kernel's bitplane workspace/
#: output instead of allocating fresh — real on donating backends
#: (TPU/GPU), a no-op annotation on CPU.  The caller must own `chunks`
#: exclusively (the write batcher's pooled pack does; never donate a
#: caller-visible array).
_apply_bitmatrix_donated = jax.jit(_bitmatrix_body, donate_argnums=(1,))


def matrix_digest(mat: np.ndarray) -> str:
    """Stable identity of a coding matrix (shape + bytes) — computed
    ONCE per codec/cached-decode-matrix and used as the device-cache key
    so the hot path stops paying a fresh `mat.tobytes()` host copy per
    stripe (the cephdma satellite fix)."""
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    h = hashlib.sha1(repr(mat.shape).encode())
    h.update(mat.tobytes())
    return h.hexdigest()


#: digest-keyed device bitmatrix cache (LRU, same bound as the legacy
#: tobytes-keyed lru_cache); one lock — lookups are dict reads
_BITMATRIX_BY_KEY: OrderedDict[tuple, jnp.ndarray] = OrderedDict()
_BITMATRIX_LOCK = Lock()
_BITMATRIX_MAX = 256


def _bitmatrix_for(mat: np.ndarray, mat_key: str | None,
                   xor: bool = False) -> jnp.ndarray:
    """Device bitmatrix for `mat`: by precomputed stable digest when the
    caller holds one (codec hot path — no per-call host copy), else the
    legacy tobytes-keyed cache."""
    if mat_key is None:
        m = np.ascontiguousarray(mat, dtype=np.uint8)
        if xor:
            return xor_bitmatrix_device(m.tobytes(), m.shape)
        return bitmatrix_device(m.tobytes(), m.shape)
    key = (mat_key, bool(xor))
    with _BITMATRIX_LOCK:
        B = _BITMATRIX_BY_KEY.get(key)
        if B is not None:
            _BITMATRIX_BY_KEY.move_to_end(key)
            return B
    m = np.ascontiguousarray(mat, dtype=np.uint8)
    B = (jnp.asarray(np.kron(m, np.eye(8, dtype=np.int8))) if xor
         else jnp.asarray(matrix_to_bitmatrix(m), dtype=jnp.int8))
    with _BITMATRIX_LOCK:
        _BITMATRIX_BY_KEY[key] = B
        _BITMATRIX_BY_KEY.move_to_end(key)
        while len(_BITMATRIX_BY_KEY) > _BITMATRIX_MAX:
            _BITMATRIX_BY_KEY.popitem(last=False)
    return B


def apply_matrix_xla(mat: np.ndarray, chunks,
                     mat_key: str | None = None) -> jnp.ndarray:
    """GF(2^8) matrix (rows x n, uint8 elements) applied to byte chunks via
    the XLA bitplane matmul (bitplanes round-trip through HBM).

    Byte-wise GF semantics identical to the oracle's gfo_apply (ISA-L
    convention) for every technique.  `mat_key`: the codec's precomputed
    stable digest of `mat` — skips the per-call tobytes host copy.
    """
    B = _bitmatrix_for(mat, mat_key)
    chunks = jnp.asarray(chunks, dtype=jnp.uint8)
    return _apply_bitmatrix(B, chunks)


# One-shot latch: a Mosaic/silicon failure in auto mode must not be
# retried (and re-fail) on every subsequent op in the process.
_pallas_broken: Exception | None = None

# Config-surface override (the `ec_kernel` option): process-wide like the
# env knob it mirrors — kernel dispatch is per-process, not per-daemon,
# so the last daemon to boot with an explicit setting wins.
_kernel_override: str | None = None


def set_kernel_override(mode: str | None) -> None:
    """Force the GF kernel path from config ('xla'/'pallas'; None/'auto'
    clears).  Takes precedence over CEPH_TPU_EC_KERNEL."""
    global _kernel_override
    _kernel_override = None if mode in (None, "auto") else mode


def _forced_pallas() -> bool:
    return (_kernel_override or os.environ.get("CEPH_TPU_EC_KERNEL")) \
        == "pallas"


def _want_pallas() -> bool:
    """Kernel dispatch policy (round-4 verdict item #3: the production
    registry -> codec path must reach the fused Pallas kernel on TPU).

    CEPH_TPU_EC_KERNEL: "pallas" / "xla" force a path; default "auto"
    picks the fused kernel on TPU backends ('axon' is this box's
    tunneled-TPU alias) and the XLA gather-free bitplane path elsewhere.
    The `ec_kernel` config option sets the same switch programmatically
    (set_kernel_override) and wins over the env var.
    """
    mode = _kernel_override or os.environ.get("CEPH_TPU_EC_KERNEL", "auto")
    if mode == "pallas":
        return True
    if mode == "xla":
        return False
    if mode != "auto":
        raise ValueError(
            f"CEPH_TPU_EC_KERNEL={mode!r}: want auto|pallas|xla"
        )
    # the sentinel's latched `degraded` state downgrades auto dispatch:
    # a wedged backend must not be fed fresh Pallas launches (forced
    # modes above still win — the operator said so).  The backend name
    # comes from the policy seam (cephtopo): a cpu-fallback topology
    # keeps auto on the XLA path even on an accelerator box
    from ..common.device_policy import get_device_policy

    return (_pallas_broken is None and not SENTINEL.is_degraded
            and get_device_policy().backend() in ("tpu", "axon"))


def current_backend() -> str:
    """The GF kernel auto dispatch would pick right now ('pallas'/'xla')
    — telemetry provenance for call sites above this seam."""
    return "pallas" if _want_pallas() else "xla"


def _latch_xla_fallback(e: Exception) -> None:
    """Latch the process-wide XLA fallback LOUDLY: stderr (the historic
    channel), a cephtrace tracepoint, and a telemetry fallback-latch
    event that the mon surfaces as KERNEL_FALLBACK_LATCHED."""
    global _pallas_broken
    _pallas_broken = e
    reason = f"{type(e).__name__}: {e}"
    print(
        f"# ceph_tpu: Pallas GF kernel failed ({reason}); "
        f"latching XLA fallback",
        file=sys.stderr,
    )
    TELEMETRY.record_fallback("gf_apply", reason, frm="pallas", to="xla")
    tracepoint("ops", "kernel_fallback_latched", kernel="gf_apply",
               reason=reason)


def clear_fallback_latch() -> bool:
    """Un-latch the XLA fallback without a daemon restart (the
    `clear_kernel_fallback` admin command): the next auto-mode dispatch
    retries Pallas.  Returns True if a latch was actually cleared."""
    global _pallas_broken
    was = _pallas_broken is not None
    _pallas_broken = None
    TELEMETRY.clear_fallback("gf_apply")
    if was:
        tracepoint("ops", "kernel_fallback_cleared", kernel="gf_apply")
    return was


def _apply_matrix_dispatch(mat: np.ndarray, chunks,
                           mat_key: str | None = None,
                           donate: bool = False) -> tuple:
    """(result, backend) — the dispatch body of apply_matrix_jax, split
    out so the telemetry wrapper can attribute the call to the backend
    that actually served it (a latching fallback serves on 'xla').
    `donate=True` routes the XLA path through the donation-enabled jit
    (caller owns `chunks` exclusively — the pooled pack contract); the
    Pallas route ignores it (its VMEM kernel manages its own buffers)."""
    if _want_pallas():
        from .pallas_gf import apply_matrix_pallas

        from ..common.device_policy import get_device_policy

        forced = _forced_pallas()
        try:
            return apply_matrix_pallas(
                mat, chunks,
                interpret=get_device_policy().backend() == "cpu",
            ), "pallas"
        except Exception as e:
            if forced:
                raise
            _latch_xla_fallback(e)
    if donate:
        # only take the donated jit where the backend honors donation
        # (CPU accepts-and-ignores it, with a warning per shape): the
        # non-donating path is byte-identical, so nothing is lost
        from .device_pool import donation_supported

        if donation_supported():
            B = _bitmatrix_for(mat, mat_key)
            return _apply_bitmatrix_donated(
                B, jnp.asarray(chunks, dtype=jnp.uint8)), "xla"
    return apply_matrix_xla(mat, chunks, mat_key=mat_key), "xla"


def apply_matrix_dev(mat: np.ndarray, chunks, mat_key: str | None = None,
                     donate: bool = False) -> jnp.ndarray:
    """Device-resident GF(2^8) matrix apply: same kernel dispatch as
    apply_matrix_jax, but the result STAYS a device array and the call
    never blocks — the cephdma async encode seam.  The caller owns the
    single deliberate sync (its commit-point `np.asarray`) and accounts
    it there; this records an async (synced=False) telemetry sample with
    zero host-copy bytes.  `donate=True` recycles `chunks`' device
    buffer into the kernel (the packed-stripe-buffer donation — `chunks`
    must be an exclusively-owned device array; a donated buffer is dead
    to the caller afterward)."""
    tm = TELEMETRY
    if not tm.enabled:
        return _apply_matrix_dispatch(mat, chunks, mat_key, donate)[0]
    t0 = time.perf_counter()
    out, backend = _apply_matrix_dispatch(mat, chunks, mat_key, donate)
    dt = time.perf_counter() - t0
    shape = getattr(chunks, "shape", None)
    tm.record(
        "gf_apply", backend, dt,
        bytes_in=int(getattr(chunks, "nbytes", 0)),
        bytes_out=mat.shape[0] * shape[-1] if shape else 0,
        compiled=tm.first_call(("gf_apply", mat.shape, shape, backend,
                                donate)),
    )
    return out


@lru_cache(maxsize=256)
def _fused_encode_jit(nargs: int, donate: bool):
    """One jitted program per stripe count: commit of the host stripe
    args, the column concat, AND the bitplane encode fuse into a single
    dispatch — the pack never exists as a host staging copy and XLA
    sees the whole flush (donate=True additionally donates every stripe
    arg's committed buffer into the kernel)."""

    def body(B, *chunks):
        x = chunks[0] if len(chunks) == 1 else \
            jnp.concatenate(chunks, axis=1)
        return _bitmatrix_body(B, x)

    return jax.jit(body, donate_argnums=tuple(range(1, nargs + 1))
                   if donate else ())


def fused_bucket(n: int) -> int:
    """The arity fused_encode_async actually dispatches for `n` stripes
    (next power of two; pads are zero stripes) — exposed so the flush
    seam's host-copy accounting can charge the REAL transfer volume,
    pads included."""
    return 1 << max(0, n - 1).bit_length() if n > 1 else 1


def fused_encode_async(mat: np.ndarray, chunks_list,
                       mat_key: str | None = None,
                       donate: bool = False) -> jnp.ndarray:
    """Fused multi-stripe encode, fully async: [k, L] stripes (host or
    device) -> ONE device-resident [m, sum(L)] parity array in ONE
    dispatch, bit-identical to apply_matrix_jax on the host-concatenated
    pack.  The cephdma flush seam: no host staging pack, no fetch — the
    caller owns the single commit-point materialization and its
    accounting.  `donate=True` donates the stripes' committed buffers
    into the kernel on backends that honor donation."""
    n = len(chunks_list)
    if _want_pallas():
        # the Pallas VMEM kernel keeps its own packing; hand it the
        # host pack and stay async through apply_matrix_dev
        packed = chunks_list[0] if n == 1 else \
            np.concatenate([np.asarray(c) for c in chunks_list], axis=1)
        return apply_matrix_dev(mat, packed, mat_key=mat_key,
                                donate=donate)
    if donate:
        from .device_pool import donation_supported

        donate = donation_supported()
    # bucket the arity to the next power of two with zero stripes (the
    # extra parity columns are zeros past every caller's demux window):
    # a traffic run's stripe counts drift over 1..max_stripes, and an
    # unbucketed jit compiles per DISTINCT count — measured as 200 ms+
    # p99 stalls whenever a novel count appeared mid-run.  7 variants
    # warm quickly; the pad waste is bounded at <2x and the pads are
    # fresh zeros (donation-safe: every donated arg a distinct buffer).
    bucket = fused_bucket(n)
    if bucket > n:
        shape = chunks_list[0].shape
        chunks_list = list(chunks_list) + [
            np.zeros(shape, dtype=np.uint8) for _ in range(bucket - n)]
    B = _bitmatrix_for(mat, mat_key)
    fn = _fused_encode_jit(bucket, donate)
    tm = TELEMETRY
    if not tm.enabled:
        return fn(B, *chunks_list)
    t0 = time.perf_counter()
    out = fn(B, *chunks_list)
    dt = time.perf_counter() - t0
    # bytes_in counts what was actually committed, pads included
    tm.record(
        "gf_apply", "xla", dt,
        bytes_in=sum(int(getattr(c, "nbytes", 0)) for c in chunks_list),
        bytes_out=mat.shape[0] * n * chunks_list[0].shape[1],
        compiled=tm.first_call(
            ("gf_fused", mat.shape, bucket, chunks_list[0].shape,
             donate)),
    )
    return out


def apply_matrix_jax(mat: np.ndarray, chunks,
                     mat_key: str | None = None) -> jnp.ndarray:
    """GF(2^8) matrix apply with kernel dispatch: the fused Pallas VMEM
    kernel on TPU (ops/pallas_gf.py), the XLA bitplane path elsewhere.

    This is the single entry every production codec (rs/shec/clay plugin
    encode/decode/repair) goes through, so `plugin=jax` via the registry
    runs the same kernel the headline bench measures.  In auto mode a
    Pallas failure latches a process-wide XLA fallback (resilience for
    the OSD data path) with a counted telemetry event; a forced
    CEPH_TPU_EC_KERNEL=pallas fails loudly.

    Telemetry (docs/observability.md): one `gf_apply` record per call —
    backend, wall time (dispatch-side; JAX queues the launch, so only
    sync call sites above this seam report achieved GiB/s), bytes
    in/out, compile-vs-execute split by first-seen shape.  Disabled:
    one attribute check.  `mat_key`: precomputed stable digest of `mat`
    (matrix_digest) held on the codec — skips the per-call tobytes host
    copy when resolving the cached device bitmatrix.
    """
    tm = TELEMETRY
    if not tm.enabled:
        return _apply_matrix_dispatch(mat, chunks, mat_key)[0]
    t0 = time.perf_counter()
    out, backend = _apply_matrix_dispatch(mat, chunks, mat_key)
    dt = time.perf_counter() - t0
    shape = getattr(chunks, "shape", None)
    tm.record(
        "gf_apply", backend, dt,
        bytes_in=int(getattr(chunks, "nbytes", 0)),
        bytes_out=mat.shape[0] * shape[-1] if shape else 0,
        compiled=tm.first_call(("gf_apply", mat.shape, shape, backend)),
    )
    return out


@lru_cache(maxsize=256)
def xor_bitmatrix_device(b_bytes: bytes, shape: tuple[int, int]) -> jnp.ndarray:
    """0/1 XOR-combination matrix expanded to bitplane form: each byte
    row mixes independently per bit, so the bit-level operator is
    kron(B, I_8) and the GF(2^8) bitplane kernel serves XOR codes
    (liberation/blaum_roth/liber8tion packets) unchanged."""
    B = np.frombuffer(b_bytes, dtype=np.uint8).reshape(shape)
    return jnp.asarray(np.kron(B, np.eye(8, dtype=np.int8)))


def apply_xor_matrix_jax(B: np.ndarray, rows,
                         mat_key: str | None = None) -> jnp.ndarray:
    """[R, N] 0/1 matrix XOR-combining [N, L] byte rows -> [R, L], on
    device through the same MXU bitplane matmul as the GF(2^8) path.

    On TPU this dispatches through apply_matrix_jax: a 0/1 matrix IS a
    GF(2^8) matrix (multiply-by-1 expands to the identity bitmatrix), so
    the fused Pallas kernel serves the XOR codes unchanged."""
    if _want_pallas():
        return apply_matrix_jax(np.ascontiguousarray(B, dtype=np.uint8),
                                rows, mat_key=mat_key)
    Bd = _bitmatrix_for(B, mat_key, xor=True)
    tm = TELEMETRY
    if not tm.enabled:
        return _apply_bitmatrix(Bd, jnp.asarray(rows, dtype=jnp.uint8))
    t0 = time.perf_counter()
    out = _apply_bitmatrix(Bd, jnp.asarray(rows, dtype=jnp.uint8))
    shape = getattr(rows, "shape", None)
    tm.record(
        "gf_xor", "xla", time.perf_counter() - t0,
        bytes_in=int(getattr(rows, "nbytes", 0)),
        bytes_out=B.shape[0] * shape[-1] if shape else 0,
        compiled=tm.first_call(("gf_xor", B.shape, shape)),
    )
    return out


def apply_xor_matrix_dev(B: np.ndarray, rows, mat_key: str | None = None,
                         donate: bool = False) -> jnp.ndarray:
    """Device-resident variant of apply_xor_matrix_jax (the bitmatrix/
    packet-codec route of the cephdma async seam): result stays on
    device, no sync; `donate=True` recycles `rows`' exclusively-owned
    device buffer through the donation-enabled jit."""
    if _want_pallas():
        return apply_matrix_dev(np.ascontiguousarray(B, dtype=np.uint8),
                                rows, mat_key=mat_key, donate=donate)
    if donate:
        from .device_pool import donation_supported

        donate = donation_supported()
    Bd = _bitmatrix_for(B, mat_key, xor=True)
    fn = _apply_bitmatrix_donated if donate else _apply_bitmatrix
    tm = TELEMETRY
    if not tm.enabled:
        return fn(Bd, jnp.asarray(rows, dtype=jnp.uint8))
    t0 = time.perf_counter()
    out = fn(Bd, jnp.asarray(rows, dtype=jnp.uint8))
    shape = getattr(rows, "shape", None)
    tm.record(
        "gf_xor", "xla", time.perf_counter() - t0,
        bytes_in=int(getattr(rows, "nbytes", 0)),
        bytes_out=B.shape[0] * shape[-1] if shape else 0,
        compiled=tm.first_call(("gf_xor", B.shape, shape, donate)),
    )
    return out


@lru_cache(maxsize=256)
def bitmatrix_device(mat_bytes: bytes, shape: tuple[int, int]) -> jnp.ndarray:
    """Host-expanded bitmatrix, cached per coding matrix (the analog of
    ErasureCodeIsaTableCache's per-pattern table cache, reference:
    src/erasure-code/isa/ErasureCodeIsaTableCache.cc)."""
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(shape)
    return jnp.asarray(matrix_to_bitmatrix(mat), dtype=jnp.int8)


class BitplaneCodec:
    """Encode/decode a systematic RS code on TPU via the bitplane matmul.

    Mirrors the encode_chunks/decode_chunks split of the reference's
    ErasureCodeInterface (reference:
    src/erasure-code/ErasureCodeInterface.h :: encode_chunks, decode_chunks).
    """

    def __init__(self, coding: np.ndarray):
        self.coding = np.ascontiguousarray(coding, dtype=np.uint8)
        self.m, self.k = self.coding.shape
        # stable device-cache key, computed ONCE per codec (cephdma: the
        # hot path used to pay a fresh mat.tobytes() host copy per
        # stripe to key the bitmatrix cache)
        self.coding_digest = matrix_digest(self.coding)
        self.generator = systematic_generator(self.coding)
        #: erasure pattern -> (decode matrix, its stable digest)
        self._decode_cache: dict[tuple[int, ...],
                                 tuple[np.ndarray, str]] = {}

    def encode(self, data) -> jnp.ndarray:
        """[k, L] data shards -> [m, L] parity shards (device array)."""
        data = jnp.asarray(data, dtype=jnp.uint8)
        if data.shape[0] != self.k:
            raise ValueError(f"expected {self.k} data shards, got {data.shape[0]}")
        return apply_matrix_jax(self.coding, data,
                                mat_key=self.coding_digest)

    def decode_matrix(self, available_rows: tuple[int, ...]) -> np.ndarray:
        """Per-erasure-pattern inverted matrix, host-cached (ISA-L table-cache
        pattern; SURVEY.md §7 'decode-matrix churn')."""
        return self._decode_entry(available_rows)[0]

    def _decode_entry(self, available_rows) -> tuple[np.ndarray, str]:
        key = tuple(available_rows[: self.k])
        ent = self._decode_cache.get(key)
        if ent is None:
            dm = decode_matrix_for(self.generator, self.k, list(key)).astype(np.uint8)
            ent = (dm, matrix_digest(dm))
            self._decode_cache[key] = ent
        return ent

    def decode(self, available_rows, shards) -> jnp.ndarray:
        """Rebuild the k data shards from >= k surviving shards.

        available_rows: shard ids (sorted) matching shards' leading rows.
        """
        rows = tuple(int(r) for r in available_rows)
        if len(rows) < self.k:
            raise ValueError(f"need >= {self.k} shards, got {len(rows)}")
        dm, dm_key = self._decode_entry(rows)
        shards = jnp.asarray(shards, dtype=jnp.uint8)[: self.k]
        return apply_matrix_jax(dm, shards, mat_key=dm_key)

    def reconstruct(self, available_rows, shards, want_rows) -> jnp.ndarray:
        """Rebuild arbitrary shards (data or parity) — the recovery path
        (reference: src/osd/ECBackend.cc :: recover_object re-encodes missing
        shards from decoded data)."""
        data = self.decode(available_rows, shards)
        want_rows = list(int(w) for w in want_rows)
        out_mat = self.generator[want_rows, :].astype(np.uint8)
        return apply_matrix_jax(out_mat, data)
