"""Pallas fused GF(2^8) matrix-apply kernel — the TPU hot-loop (SURVEY.md §7
step 2, HOT LOOP #1 of §3.1).

The XLA bitplane path (ceph_tpu/ops/bitplane.py) materializes the unpacked
bitplanes (8x the data) through HBM; this kernel keeps them in VMEM:

    per L-tile:  load [kG, T] bytes ->
                 8 mask-compares to [8*kG, T] 0/1 int8 (VPU) ->
                 one int8 MXU matmul with the kron-expanded bitmatrix ->
                 mod-2 -> pack bits back to bytes with a tiny bf16 matmul ->
                 store [rows*G, T] bytes

HBM traffic becomes read 1x + write (rows/n)x of the data — the minimum —
instead of ~17x.  Plays the role gf-complete's SIMD kernels play for
jerasure (reference: src/erasure-code/jerasure/gf-complete :: gf_w8 SSE
paths) and ec_encode_data's AVX-512 loops play for ISA-L (reference:
src/isa-l).

Two tricks carry the throughput (measured on v5e, RS(8,4) 1 MiB shards:
22.7 -> ~65 GiB/s):

- **Pack-by-matmul**: bit->byte repacking as P @ (acc & 1) with P holding
  2^l weights in bfloat16 (exact: sums <= 255 < 2^8 and bf16 represents
  integers to 2^8), replacing 8 VPU shift+or passes.  int32 matmuls do not
  legalize in Mosaic and int8 cannot hold 128, hence bf16.
- **kron(B, I_G) row grouping**: the natural [k, T] block has only k
  sublanes while int8 tiles are (32, 128), so every VPU op padded 4-8x.
  Each chunk row is split into G segments stacked vertically ([k*G, T/G],
  a free row-major reshape) and the bitmatrix becomes its Kronecker
  expansion with I_G.  MXU cycles are unchanged (the array pads K/M to 128
  anyway) but every elementwise op runs on full tiles.

Layout: bit r of input row j lives at bits row l*kG + (j*G+g); output bit
rows are l'*rG + (i*G+g).  The host builds both expanded matrices once per
(mat, G) (lru_cache), the kernel is shape-generic.
"""
from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..gf.matrix import matrix_to_bitmatrix

DEFAULT_TILE = 8192


def _pick_group(rows: int, n: int) -> int:
    """Segments per row: enough that n*G reaches a full int8 sublane tile
    (32) and the contraction depth n*8*G reaches the 128-wide MXU, capped
    so the expanded bitmatrix stays small."""
    G = 1
    while n * G < 32 or n * 8 * G < 128:
        G *= 2
    return min(G * 2, 64)  # one extra doubling measured fastest on v5e


def _pick_tile(rows: int, n: int, G: int, tile: int = DEFAULT_TILE) -> int:
    """Shrink the column tile until the kernel's VMEM working set fits.

    Scoped VMEM scales linearly in the tile width: the unpacked bitplanes
    (8*kG int8), the int32 accumulator + its bf16 parity view (8*rG each),
    the packed f32 output (4*rG), and the in/out byte blocks.  Small
    coding matrices (RS 8+4: ~2.3 KiB/col) run the full DEFAULT_TILE; big
    decode/repair matrices (CLAY(8,4,d=11) repair is [64, 176]: ~10
    KiB/col) blew the v5e 16 MiB scoped-vmem limit at 8192 (observed:
    43 MiB requested, r4 silicon).  The 24 MiB budget is calibrated to
    the compiler's observed ~2x buffer reuse over this naive sum — the
    known-good RS(8,4)@8192 case sits just under it."""
    kG, rG = n * G, rows * G
    # bytes per tile column: bits int8 [8kG] + acc int32 [8rG] + parity
    # bf16 [8rG] + packed f32 [rG] + in/out byte blocks
    per_col = 8 * kG + 32 * rG + 16 * rG + 4 * rG + kG + rG
    budget = 24 << 20
    while tile > 512 and per_col * tile > budget:
        tile //= 2
    return tile


@lru_cache(maxsize=256)
def _kron_matrices(
    mat_bytes: bytes, shape: tuple[int, int], G: int
) -> tuple[np.ndarray, np.ndarray]:
    """(B', P'): kron-expanded GF(2) bitmatrix (int8) and bf16 pack matrix."""
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(shape)
    rows, n = shape
    Bbit = matrix_to_bitmatrix(mat)  # [rows*8, n*8], cols j*8+l
    kG, rG = n * G, rows * G
    Bk = np.zeros((rows * 8 * G, n * 8 * G), np.int8)
    g = np.arange(G)
    for i in range(rows):
        for l2 in range(8):
            for j in range(n):
                for l in range(8):
                    if Bbit[i * 8 + l2, j * 8 + l]:
                        Bk[l2 * rG + i * G + g, l * kG + j * G + g] = 1
    Pk = np.zeros((rG, rows * 8 * G), np.float32)
    for i in range(rows):
        for l2 in range(8):
            Pk[i * G + g, l2 * rG + i * G + g] = 1 << l2
    return Bk, Pk


def _apply_kernel(B_ref, P_ref, x_ref, o_ref, *, kG: int):
    x = x_ref[:]  # [kG, T] uint8
    bits = jnp.stack(
        [(x & jnp.uint8(1 << l) != 0).astype(jnp.int8) for l in range(8)]
    ).reshape(8 * kG, x.shape[1])
    acc = jax.lax.dot_general(
        B_ref[:],
        bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    par = (acc & 1).astype(jnp.bfloat16)
    packed = jax.lax.dot_general(
        P_ref[:],
        par,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = packed.astype(jnp.int32).astype(jnp.uint8)


@partial(jax.jit, static_argnames=("rows", "n", "G", "tile", "interpret"))
def _apply_grouped(
    B, P, xg, rows: int, n: int, G: int, tile: int, interpret: bool
):
    """xg: [n*G, Lg] uint8 (row j*G+g = segment g of chunk j); returns
    [rows*G, Lg] uint8 in the same grouped layout."""
    from jax.experimental import pallas as pl

    kG, rG = n * G, rows * G
    Lg = xg.shape[1]
    if Lg % tile:
        raise ValueError(f"grouped length {Lg} not a multiple of tile {tile}")
    return pl.pallas_call(
        partial(_apply_kernel, kG=kG),
        grid=(Lg // tile,),
        in_specs=[
            pl.BlockSpec(B.shape, lambda i: (0, 0)),
            pl.BlockSpec(P.shape, lambda i: (0, 0)),
            pl.BlockSpec((kG, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((rG, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((rG, Lg), jnp.uint8),
        interpret=interpret,
    )(B, P, xg)


def apply_matrix_pallas(
    mat: np.ndarray,
    chunks,
    tile: int = DEFAULT_TILE,
    interpret: bool = False,
) -> jnp.ndarray:
    """GF(2^8) matrix apply via the fused Pallas kernel.

    Same contract (and bit-exact output) as
    ceph_tpu.ops.bitplane.apply_matrix_jax: [rows, n] x [n, L] -> [rows, L].
    """
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    rows, n = mat.shape
    G = _pick_group(rows, n)
    tile = _pick_tile(rows, n, G, tile)
    Bk, Pk = _kron_matrices(mat.tobytes(), mat.shape, G)
    B = jnp.asarray(Bk)
    P = jnp.asarray(Pk, jnp.bfloat16)
    if isinstance(chunks, np.ndarray):
        chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
    else:
        chunks = jnp.asarray(chunks, dtype=jnp.uint8)
    L = chunks.shape[1]
    seg = G * tile
    pad = (-L) % seg
    if pad:
        if isinstance(chunks, np.ndarray):
            chunks = np.pad(chunks, ((0, 0), (0, pad)))
        else:
            chunks = jnp.pad(chunks, ((0, 0), (0, pad)))
    Lp = L + pad
    # row-major reshape [n, Lp] -> [n*G, Lp/G] is free on host arrays and a
    # relayout copy on device arrays (still far cheaper than the kernel win)
    xg = chunks.reshape(n * G, Lp // G)
    out = _apply_grouped(B, P, jnp.asarray(xg), rows, n, G, tile, interpret)
    out = out.reshape(rows, Lp)
    return out[:, :L] if pad else out
