"""Pallas fused GF(2^8) matrix-apply kernel — the TPU hot-loop (SURVEY.md §7
step 2, HOT LOOP #1 of §3.1).

The XLA bitplane path (ceph_tpu/ops/bitplane.py) materializes the unpacked
bitplanes (8x the data) through HBM; this kernel keeps them in VMEM:

    per L-tile:  load [n, T] bytes ->
                 unpack to [n*8, T] 0/1 int8 (VPU shifts) ->
                 one MXU matmul with the (rows*8, n*8) bitmatrix ->
                 mod-2 + repack to [rows, T] bytes -> store

HBM traffic becomes read 1x + write (rows/n)x of the data — the minimum —
instead of ~17x.  Plays the role gf-complete's SIMD kernels play for
jerasure (reference: src/erasure-code/jerasure/gf-complete :: gf_w8 SSE
paths) and ec_encode_data's AVX-512 loops play for ISA-L (reference:
src/isa-l).

Layout notes:
- bit-plane order inside the kernel is l*n + j (concatenate over bit l of
  chunk j), so the host pre-permutes the bitmatrix columns accordingly;
  output rows stay i*8 + l so repacking is a plain reshape.
- the bitmatrix is tiny ((rows*8) x (n*8) int8) and lives in VMEM whole.
"""
from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..gf.matrix import matrix_to_bitmatrix

DEFAULT_TILE = 32768


@lru_cache(maxsize=256)
def _permuted_bitmatrix(mat_bytes: bytes, shape: tuple[int, int]) -> np.ndarray:
    """(rows*8) x (n*8) bitmatrix with columns permuted to l*n+j order."""
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(shape)
    B = matrix_to_bitmatrix(mat)  # cols j*8+l
    rows8, n8 = B.shape
    n = n8 // 8
    perm = np.empty(n8, dtype=np.int64)
    for l in range(8):
        for j in range(n):
            perm[l * n + j] = j * 8 + l
    return np.ascontiguousarray(B[:, perm]).astype(np.int8)


def _apply_kernel(B_ref, x_ref, o_ref, *, n: int, rows: int):
    x = x_ref[:].astype(jnp.int32)  # [n, T]
    planes = [((x >> l) & 1).astype(jnp.int8) for l in range(8)]
    bits = jnp.concatenate(planes, axis=0)  # [8n, T], row order l*n+j
    acc = jax.lax.dot_general(
        B_ref[:],
        bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [rows*8, T]
    par = acc & 1  # int32: Mosaic cannot legalize vector shifts on int8
    T = par.shape[1]
    stacked = par.reshape(rows, 8, T)
    packed = stacked[:, 0, :]
    for l in range(1, 8):
        packed = packed | (stacked[:, l, :] << l)
    o_ref[:] = packed.astype(jnp.uint8)


@partial(jax.jit, static_argnames=("rows", "n", "tile", "interpret"))
def _apply_padded(B, chunks, rows: int, n: int, tile: int, interpret: bool):
    from jax.experimental import pallas as pl

    L = chunks.shape[1]
    if L % tile:
        raise ValueError(f"chunk length {L} not a multiple of tile {tile}")
    grid = (L // tile,)
    return pl.pallas_call(
        partial(_apply_kernel, n=n, rows=rows),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows * 8, n * 8), lambda i: (0, 0)),
            pl.BlockSpec((n, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((rows, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((rows, L), jnp.uint8),
        interpret=interpret,
    )(B, chunks)


def apply_matrix_pallas(
    mat: np.ndarray, chunks, tile: int = DEFAULT_TILE, interpret: bool = False
) -> jnp.ndarray:
    """GF(2^8) matrix apply via the fused Pallas kernel.

    Same contract (and bit-exact output) as
    ceph_tpu.ops.bitplane.apply_matrix_jax.
    """
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    rows, n = mat.shape
    Bp = jnp.asarray(_permuted_bitmatrix(mat.tobytes(), mat.shape))
    chunks = jnp.asarray(chunks, dtype=jnp.uint8)
    L = chunks.shape[1]
    pad = (-L) % tile
    if pad:
        chunks = jnp.pad(chunks, ((0, 0), (0, pad)))
    out = _apply_padded(Bp, chunks, rows, n, tile, interpret)
    return out[:, :L] if pad else out
