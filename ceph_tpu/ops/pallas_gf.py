"""Pallas fused GF(2^8) matrix-apply kernel — the TPU hot-loop (SURVEY.md §7
step 2, HOT LOOP #1 of §3.1).

The XLA bitplane path (ceph_tpu/ops/bitplane.py) materializes the unpacked
bitplanes (8x the data) through HBM; this kernel keeps them in VMEM:

    per L-tile:  load [kG, T] bytes ->
                 8 mask-compares to [8*kG, T] 0/1 int8 (VPU) ->
                 one int8 MXU matmul with the kron-expanded bitmatrix ->
                 mod-2 -> pack bits back to bytes with a tiny bf16 matmul ->
                 store [rows*G, T] bytes

HBM traffic becomes read 1x + write (rows/n)x of the data — the minimum —
instead of ~17x.  Plays the role gf-complete's SIMD kernels play for
jerasure (reference: src/erasure-code/jerasure/gf-complete :: gf_w8 SSE
paths) and ec_encode_data's AVX-512 loops play for ISA-L (reference:
src/isa-l).

Two tricks carry the throughput (measured on v5e, RS(8,4) 1 MiB shards:
22.7 -> ~65 GiB/s):

- **Pack-by-matmul**: bit->byte repacking as P @ (acc & 1) with P holding
  2^l weights in bfloat16 (exact: sums <= 255 < 2^8 and bf16 represents
  integers to 2^8), replacing 8 VPU shift+or passes.  int32 matmuls do not
  legalize in Mosaic and int8 cannot hold 128, hence bf16.
- **kron(B, I_G) row grouping**: the natural [k, T] block has only k
  sublanes while int8 tiles are (32, 128), so every VPU op padded 4-8x.
  Each chunk row is split into G segments stacked vertically ([k*G, T/G],
  a free row-major reshape) and the bitmatrix becomes its Kronecker
  expansion with I_G.  MXU cycles are unchanged (the array pads K/M to 128
  anyway) but every elementwise op runs on full tiles.

Layout: bit r of input row j lives at bits row l*kG + (j*G+g); output bit
rows are l'*rG + (i*G+g).  The host builds both expanded matrices once per
(mat, G) (lru_cache), the kernel is shape-generic.
"""
from __future__ import annotations

import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..gf.matrix import matrix_to_bitmatrix

DEFAULT_TILE = 8192
# VMEM budget for the analytic working-set model below: calibrated to the
# compiler's observed ~2x buffer reuse over the naive sum — the known-good
# RS(8,4)@8192 case sits just under it, the known-bad CLAY@8192 unblocked
# case (43 MiB requested on v5e, r4) sits far over.  tests/test_pallas.py
# pins both sides.
VMEM_BUDGET = 24 << 20
MAX_ROW_BLOCKS = 8  # static unroll bound (compile time ~ RB)


def _pick_group(rows: int, n: int) -> int:
    """Segments per row: enough that n*G reaches a full int8 sublane tile
    (32) and the contraction depth n*8*G reaches the 128-wide MXU, capped
    so the expanded bitmatrix stays small."""
    G = 1
    while n * G < 32 or n * 8 * G < 128:
        G *= 2
    return min(G * 2, 64)  # one extra doubling measured fastest on v5e


def vmem_estimate(rows: int, n: int, G: int, tile: int, rb: int) -> int:
    """Analytic per-launch VMEM working set (bytes) for the kernel below.

    Column-proportional terms: unpacked bitplanes (8*kG int8) + input
    block (kG) are shared across row blocks; the int32 accumulator
    (32*rGb) and bf16 parity view (16*rGb) live per block (the unrolled
    loop reuses one buffer); the packed f32 (4*rGb per block, but the
    full-out byte block (rG) persists).  This is the model _pick_layout
    enforces and tests assert against the recorded silicon shapes."""
    rows_b = -(-rows // rb)
    kG, rGb, rG = n * G, rows_b * G, rows * G
    per_col = (8 * kG + kG) + (32 + 16 + 4) * rGb + rG
    return per_col * tile


def _pick_layout(rows: int, n: int, G: int,
                 tile: int = DEFAULT_TILE) -> tuple[int, int]:
    """(tile, row_blocks) fitting VMEM_BUDGET.

    Fat decode/repair matrices (CLAY(8,4,d=11) repair is [64, 176]) used
    to shrink the column tile to fit — r4 measured the cost: 3.2 GiB/s vs
    the flagship's 85 (round-4 verdict item #4).  Row-blocking instead
    splits the matrix into RB row bands, statically unrolled inside the
    kernel: the bitplanes are fetched and unpacked ONCE per tile and each
    band runs a smaller matmul into its own output rows, so tile (and
    grid-step count) stay at the flagship shape.  Tile shrink remains the
    last resort once RB hits MAX_ROW_BLOCKS.

    CEPH_TPU_GF_ROWBLOCKS / CEPH_TPU_GF_TILE override for silicon sweeps.
    """
    def _knob(name: str, lo: int, multiple_of: int = 1) -> int | None:
        raw = os.environ.get(name)
        if not raw:
            return None
        try:
            v = int(raw)
        except ValueError:
            raise ValueError(f"{name}={raw!r}: integer required") from None
        if v < lo or v % multiple_of:
            raise ValueError(
                f"{name}={v}: must be >= {lo}"
                + (f" and a multiple of {multiple_of}"
                   if multiple_of > 1 else "")
            )
        return v

    env_tile = _knob("CEPH_TPU_GF_TILE", 128, 128)
    env_rb = _knob("CEPH_TPU_GF_ROWBLOCKS", 1)
    if env_tile:
        tile = env_tile
    if env_rb:
        return tile, min(env_rb, rows)
    while True:
        rb = 1
        while (vmem_estimate(rows, n, G, tile, rb) > VMEM_BUDGET
               and rb < min(MAX_ROW_BLOCKS, rows)):
            rb *= 2
        if vmem_estimate(rows, n, G, tile, rb) <= VMEM_BUDGET or tile <= 512:
            return tile, rb
        tile //= 2


@lru_cache(maxsize=256)
def _kron_matrices(
    mat_bytes: bytes, shape: tuple[int, int], G: int
) -> tuple[np.ndarray, np.ndarray]:
    """(B', P'): kron-expanded GF(2) bitmatrix (int8) and bf16 pack matrix."""
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(shape)
    rows, n = shape
    Bbit = matrix_to_bitmatrix(mat)  # [rows*8, n*8], cols j*8+l
    kG, rG = n * G, rows * G
    Bk = np.zeros((rows * 8 * G, n * 8 * G), np.int8)
    g = np.arange(G)
    for i in range(rows):
        for l2 in range(8):
            for j in range(n):
                for l in range(8):
                    if Bbit[i * 8 + l2, j * 8 + l]:
                        Bk[l2 * rG + i * G + g, l * kG + j * G + g] = 1
    Pk = np.zeros((rG, rows * 8 * G), np.float32)
    for i in range(rows):
        for l2 in range(8):
            Pk[i * G + g, l2 * rG + i * G + g] = 1 << l2
    return Bk, Pk


@lru_cache(maxsize=256)
def _kron_matrices_blocked(
    mat_bytes: bytes, shape: tuple[int, int], G: int, rb: int
) -> tuple[np.ndarray, np.ndarray, int]:
    """Row-banded kron matrices for the unrolled fat-matrix kernel:
    (B_stack [rb, rows_b*8*G, n*8*G] int8, P_stack [rb, rows_b*G,
    rows_b*8*G] f32, rows_b).  The matrix rows are padded with zero rows
    to rb*rows_b; band b covers byte rows [b*rows_b, (b+1)*rows_b), so
    the stacked outputs concatenate back in plain row order."""
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(shape)
    rows, n = shape
    rows_b = -(-rows // rb)
    padded = np.zeros((rb * rows_b, n), np.uint8)
    padded[:rows] = mat
    Bs, Ps = [], []
    for b in range(rb):
        sub = np.ascontiguousarray(padded[b * rows_b:(b + 1) * rows_b])
        Bk, Pk = _kron_matrices(sub.tobytes(), (rows_b, n), G)
        Bs.append(Bk)
        Ps.append(Pk)
    return np.stack(Bs), np.stack(Ps), rows_b


def _unpack_bits(x, kG: int):
    """[kG, T] uint8 -> [8*kG, T] 0/1 int8 bitplanes (VPU mask-compares)."""
    return jnp.stack(
        [(x & jnp.uint8(1 << l) != 0).astype(jnp.int8) for l in range(8)]
    ).reshape(8 * kG, x.shape[1])


def _apply_kernel(B_ref, P_ref, x_ref, o_ref, *, kG: int):
    bits = _unpack_bits(x_ref[:], kG)
    acc = jax.lax.dot_general(
        B_ref[:],
        bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    par = (acc & 1).astype(jnp.bfloat16)
    packed = jax.lax.dot_general(
        P_ref[:],
        par,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = packed.astype(jnp.int32).astype(jnp.uint8)


def _apply_kernel_blocked(B_ref, P_ref, x_ref, o_ref, *, kG: int, rb: int,
                          rGb: int):
    """Fat-matrix variant (round-4 verdict item #4): unpack the bitplanes
    ONCE, then statically unroll over the rb row bands — each band's
    smaller matmul reuses `bits` and writes its own output row range, so
    the accumulator footprint is rb-fold smaller and the column tile
    stays at the flagship width instead of shrinking."""
    bits = _unpack_bits(x_ref[:], kG)
    for b in range(rb):
        acc = jax.lax.dot_general(
            B_ref[b],
            bits,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        par = (acc & 1).astype(jnp.bfloat16)
        packed = jax.lax.dot_general(
            P_ref[b],
            par,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[b * rGb:(b + 1) * rGb, :] = packed.astype(jnp.int32).astype(
            jnp.uint8
        )


@partial(jax.jit,
         static_argnames=("rows", "n", "G", "tile", "rb", "interpret"))
def _apply_grouped(
    B, P, xg, rows: int, n: int, G: int, tile: int, rb: int, interpret: bool
):
    """xg: [n*G, Lg] uint8 (row j*G+g = segment g of chunk j); returns
    [rows_p*G, Lg] uint8 in the same grouped layout, where rows_p is rows
    padded up to a multiple of rb (callers slice)."""
    from jax.experimental import pallas as pl

    kG = n * G
    Lg = xg.shape[1]
    if Lg % tile:
        raise ValueError(f"grouped length {Lg} not a multiple of tile {tile}")
    if rb == 1:
        rG = rows * G
        return pl.pallas_call(
            partial(_apply_kernel, kG=kG),
            grid=(Lg // tile,),
            in_specs=[
                pl.BlockSpec(B.shape, lambda i: (0, 0)),
                pl.BlockSpec(P.shape, lambda i: (0, 0)),
                pl.BlockSpec((kG, tile), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((rG, tile), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((rG, Lg), jnp.uint8),
            interpret=interpret,
        )(B, P, xg)
    rows_b = B.shape[1] // (8 * G)
    rGb = rows_b * G
    rGp = rb * rGb
    return pl.pallas_call(
        partial(_apply_kernel_blocked, kG=kG, rb=rb, rGb=rGb),
        grid=(Lg // tile,),
        in_specs=[
            pl.BlockSpec(B.shape, lambda i: (0, 0, 0)),
            pl.BlockSpec(P.shape, lambda i: (0, 0, 0)),
            pl.BlockSpec((kG, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((rGp, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((rGp, Lg), jnp.uint8),
        interpret=interpret,
    )(B, P, xg)


def apply_matrix_pallas(
    mat: np.ndarray,
    chunks,
    tile: int = DEFAULT_TILE,
    interpret: bool = False,
) -> jnp.ndarray:
    """GF(2^8) matrix apply via the fused Pallas kernel.

    Same contract (and bit-exact output) as
    ceph_tpu.ops.bitplane.apply_matrix_jax: [rows, n] x [n, L] -> [rows, L].
    """
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    rows, n = mat.shape
    G = _pick_group(rows, n)
    tile, rb = _pick_layout(rows, n, G, tile)
    if rb == 1:
        Bk, Pk = _kron_matrices(mat.tobytes(), mat.shape, G)
        rows_p = rows
    else:
        Bk, Pk, rows_b = _kron_matrices_blocked(
            mat.tobytes(), mat.shape, G, rb
        )
        rows_p = rb * rows_b
    B = jnp.asarray(Bk)
    P = jnp.asarray(Pk, jnp.bfloat16)
    if isinstance(chunks, np.ndarray):
        chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
    else:
        chunks = jnp.asarray(chunks, dtype=jnp.uint8)
    L = chunks.shape[1]
    seg = G * tile
    pad = (-L) % seg
    if pad:
        if isinstance(chunks, np.ndarray):
            chunks = np.pad(chunks, ((0, 0), (0, pad)))
        else:
            chunks = jnp.pad(chunks, ((0, 0), (0, pad)))
    Lp = L + pad
    # row-major reshape [n, Lp] -> [n*G, Lp/G] is free on host arrays and a
    # relayout copy on device arrays (still far cheaper than the kernel win)
    xg = chunks.reshape(n * G, Lp // G)
    out = _apply_grouped(
        B, P, jnp.asarray(xg), rows, n, G, tile, rb, interpret
    )
    out = out.reshape(rows_p, Lp)[:rows]
    return out[:, :L] if pad else out
