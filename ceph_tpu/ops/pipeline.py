"""Host<->device pipelining for stripe-batch streams (SURVEY.md §2.9
"pipeline parallelism" analog: the reference overlaps its write pipeline
stages; the TPU equivalent is double-buffering host->device DMA against
kernel compute).

`stream_encode` drives a sequence of host batches through the encode
kernel with at most two batches resident: while the device computes
parity for batch i, batch i+1's transfer is already in flight (both
device_put and kernel launches are async under JAX's dispatch model;
the np.asarray fetch of result i-1 is the only sync point and it
overlaps the later batches' work).

The input is consumed as a true ITERATOR: a long traffic run (the
write-batcher's multi-batch bursts, bench soaks) holds at most two
input batches of host memory at any moment, never the whole stream.
"""
from __future__ import annotations

import time

import numpy as np

from ..common.kernel_telemetry import TELEMETRY


def _apply_fn(mat: np.ndarray, kernel: str, mat_key: str | None,
              donate: bool):
    """Resolve the kernel choice once per stream.  'xla' and 'pallas'
    force a path (the bench's explicit columns); 'auto' routes through
    the production dispatch — the same path the codec plugins take,
    honoring the `ec_kernel` option and the latched XLA fallback — so
    batched parity is bit-identical to the per-op path.  With the
    device pool on, the auto/xla route goes through apply_matrix_dev
    with the stream's batch buffer DONATED (the stream owns it
    exclusively) and the stable mat_key skips per-batch tobytes keys."""
    if kernel == "pallas":
        from .pallas_gf import apply_matrix_pallas

        return lambda x: apply_matrix_pallas(mat, x)
    from .bitplane import apply_matrix_dev

    return lambda x: apply_matrix_dev(mat, x, mat_key=mat_key,
                                      donate=donate)


def stream_encode(mat: np.ndarray, batches, kernel: str = "xla",
                  mat_key: str | None = None):
    """Encode an iterable of [k, L] host batches; returns the list of
    parity arrays.  kernel: 'xla' (ops.bitplane), 'pallas'
    (ops.pallas_gf), or 'auto' (production dispatch, ec_kernel-aware).

    `batches` may be any iterable, including a one-shot generator; it is
    pulled lazily, one batch ahead of the compute, so the stream's
    host-memory high-water mark is two batches regardless of length.

    cephdma: batch transfers commit through the device stripe pool
    (recycled buffers where the backend donates; the pool's bypass —
    `ec_device_pool=false` or sentinel-degraded — falls back to plain
    device_put) and the in-flight batch buffer is donated into the
    encode.  The result fetches stay: returning host parity arrays IS
    this function's contract, so the stream remains a deliberate sync
    seam and its record counts the transfer+fetch host-copy volume.

    Telemetry: one `stream_encode` record per stream — the np.asarray
    fetches make this a true sync point, so the record carries an honest
    achieved GiB/s for the whole double-buffered pipeline."""
    import jax

    from .device_pool import POOL

    tm = TELEMETRY
    t_start = time.perf_counter() if tm.enabled else 0.0
    bytes_in = bytes_out = 0
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    use_pool = POOL.enabled()
    apply_fn = _apply_fn(mat, kernel, mat_key,
                         donate=use_pool and kernel != "pallas")

    def commit(host):
        host = np.ascontiguousarray(host, dtype=np.uint8)
        if use_pool:
            return POOL.put(host)
        return jax.device_put(host)  # noqa: CL8 — pool-off transfer seam

    it = iter(batches)
    first = next(it, None)
    if first is None:
        return []
    outs = []
    pending = None  # device result of the previous batch, not yet fetched
    nxt = commit(first)
    while nxt is not None:
        cur = nxt
        bytes_in += int(cur.nbytes)
        # launch compute first (async), THEN start the next DMA so the
        # copy engine and the cores overlap
        res = apply_fn(cur)
        upcoming = next(it, None)
        nxt = commit(upcoming) if upcoming is not None else None
        if pending is not None:
            # fetch the previous result; keeps two batches live
            outs.append(np.asarray(pending))
            if use_pool:
                POOL.release(pending)  # dead device buffer: recycle
        pending = res
    outs.append(np.asarray(pending))
    if use_pool:
        POOL.release(pending)
    if tm.enabled:
        from .bitplane import current_backend

        bytes_out = sum(int(o.nbytes) for o in outs)
        backend = kernel if kernel == "pallas" else current_backend()
        tm.record("stream_encode", backend,
                  time.perf_counter() - t_start,
                  bytes_in=bytes_in, bytes_out=bytes_out, synced=True,
                  host_copy_bytes=bytes_in + bytes_out)
    return outs
