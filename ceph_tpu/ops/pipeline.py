"""Host<->device pipelining for stripe-batch streams (SURVEY.md §2.9
"pipeline parallelism" analog: the reference overlaps its write pipeline
stages; the TPU equivalent is double-buffering host->device DMA against
kernel compute).

`stream_encode` drives a sequence of host batches through the encode
kernel with at most two batches resident: while the device computes
parity for batch i, batch i+1's transfer is already in flight (both
device_put and kernel launches are async under JAX's dispatch model;
the np.asarray fetch of result i-1 is the only sync point and it
overlaps the later batches' work).
"""
from __future__ import annotations

import numpy as np


def stream_encode(mat: np.ndarray, batches, kernel: str = "xla"):
    """Encode an iterable of [k, L] host batches; returns the list of
    parity arrays.  kernel: 'xla' (ops.bitplane) or 'pallas'
    (ops.pallas_gf)."""
    import jax

    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    if kernel == "pallas":
        from .pallas_gf import apply_matrix_pallas

        def apply_fn(x):
            return apply_matrix_pallas(mat, x)

    else:
        from .bitplane import apply_matrix_jax

        def apply_fn(x):
            return apply_matrix_jax(mat, x)

    batches = list(batches)
    if not batches:
        return []
    outs = []
    results = []
    nxt = jax.device_put(np.ascontiguousarray(batches[0], dtype=np.uint8))
    for i in range(len(batches)):
        cur = nxt
        # launch compute first (async), THEN start the next DMA so the
        # copy engine and the cores overlap
        results.append(apply_fn(cur))
        if i + 1 < len(batches):
            nxt = jax.device_put(
                np.ascontiguousarray(batches[i + 1], dtype=np.uint8)
            )
        if i >= 1:  # fetch the previous result; keeps two batches live
            outs.append(np.asarray(results[i - 1]))
            results[i - 1] = None
    outs.append(np.asarray(results[-1]))
    return outs
