"""Host<->device pipelining for stripe-batch streams (SURVEY.md §2.9
"pipeline parallelism" analog: the reference overlaps its write pipeline
stages; the TPU equivalent is double-buffering host->device DMA against
kernel compute).

`stream_encode` drives a sequence of host batches through the encode
kernel with at most two batches resident: while the device computes
parity for batch i, batch i+1's transfer is already in flight (both
device_put and kernel launches are async under JAX's dispatch model;
the np.asarray fetch of result i-1 is the only sync point and it
overlaps the later batches' work).

The input is consumed as a true ITERATOR: a long traffic run (the
write-batcher's multi-batch bursts, bench soaks) holds at most two
input batches of host memory at any moment, never the whole stream.
"""
from __future__ import annotations

import time

import numpy as np

from ..common.kernel_telemetry import TELEMETRY


def _apply_fn(mat: np.ndarray, kernel: str):
    """Resolve the kernel choice once per stream.  'xla' and 'pallas'
    force a path (the bench's explicit columns); 'auto' routes through
    apply_matrix_jax's production dispatch — the same path the codec
    plugins take, honoring the `ec_kernel` option and the latched XLA
    fallback — so batched parity is bit-identical to the per-op path."""
    if kernel == "pallas":
        from .pallas_gf import apply_matrix_pallas

        return lambda x: apply_matrix_pallas(mat, x)
    # 'xla' (historical name for the default path) and 'auto' both route
    # through apply_matrix_jax's dispatch, as stream_encode always has
    from .bitplane import apply_matrix_jax

    return lambda x: apply_matrix_jax(mat, x)


def stream_encode(mat: np.ndarray, batches, kernel: str = "xla"):
    """Encode an iterable of [k, L] host batches; returns the list of
    parity arrays.  kernel: 'xla' (ops.bitplane), 'pallas'
    (ops.pallas_gf), or 'auto' (production dispatch, ec_kernel-aware).

    `batches` may be any iterable, including a one-shot generator; it is
    pulled lazily, one batch ahead of the compute, so the stream's
    host-memory high-water mark is two batches regardless of length.

    Telemetry: one `stream_encode` record per stream — the np.asarray
    fetches make this a true sync point, so the record carries an honest
    achieved GiB/s for the whole double-buffered pipeline."""
    import jax

    tm = TELEMETRY
    t_start = time.perf_counter() if tm.enabled else 0.0
    bytes_in = bytes_out = 0
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    apply_fn = _apply_fn(mat, kernel)
    it = iter(batches)
    first = next(it, None)
    if first is None:
        return []
    outs = []
    pending = None  # device result of the previous batch, not yet fetched
    nxt = jax.device_put(np.ascontiguousarray(first, dtype=np.uint8))
    while nxt is not None:
        cur = nxt
        if tm.enabled:
            bytes_in += int(cur.nbytes)
        # launch compute first (async), THEN start the next DMA so the
        # copy engine and the cores overlap
        res = apply_fn(cur)
        upcoming = next(it, None)
        nxt = (
            jax.device_put(np.ascontiguousarray(upcoming, dtype=np.uint8))
            if upcoming is not None else None
        )
        if pending is not None:
            # fetch the previous result; keeps two batches live
            outs.append(np.asarray(pending))
        pending = res
    outs.append(np.asarray(pending))
    if tm.enabled:
        from .bitplane import current_backend

        bytes_out = sum(int(o.nbytes) for o in outs)
        backend = kernel if kernel == "pallas" else current_backend()
        tm.record("stream_encode", backend,
                  time.perf_counter() - t_start,
                  bytes_in=bytes_in, bytes_out=bytes_out, synced=True)
    return outs
