"""Pallas fused straw2 score kernel — hash + crush_ln without gathers
(HOT LOOP #3 of SURVEY.md §3.3, the straw2 draw inner loop).

Why: TPUs have no hardware vector gather, so XLA lowers the batched
mapper's two per-(x, item) random lookups — the 2^16-entry CRUSH_LN_TABLE
gather — at ~9 ns/element; measured, that one op was ~0.55 s of every
0.62 s straw2 launch at 262k x 128 draws on v5e, and XLA's int32 rjenkins
hash another 0.06 s.  This kernel keeps everything in VMEM:

    per [T, S] tile:  rjenkins1_3(x, item, r) on the VPU (u32 add/xor/
                      shift only — no multiplies in the hash) ->
                      u = h & 0xffff ->
                      crush_ln(u) via the reference's OWN small-table
                      formulation (crush/ln_compute.py): two lookups into
                      129- and 256-entry tables, each a one-hot f32
                      matmul on the MXU (the TPU-native gather), plus
                      exact 32-bit limb arithmetic ->
                      ln as two int32 planes (bits 24..47 / 0..23)

The caller (crush/mapper.py score path) recombines the planes into int64
and runs the div64 draw + argmax under its x64 scope — those measured at
noise level.  Plays the role the compiled mapper.c straw2 loop plays for
the reference (reference: src/crush/mapper.c :: bucket_straw2_choose).

Bit-exactness: tests/test_crush.py compares this path (interpret=True on
CPU) against the table gather for random and exhaustive inputs.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..crush.hash import crush_hash32_3
from ..crush.ln_compute import (
    TBL1_BYTES,
    TBL2_BYTES,
    crush_ln_limbs,
    recombine_limbs,
)

# one-hot matmul tables in 8-bit limbs (bf16-exact), bf16 operands so the
# MXU runs its fast single-pass mode while staying bit-exact: the default
# f32 path silently truncates operands to bf16 (observed: table value
# 34663 -> 34560), and HIGHEST-precision f32 costs a 6-pass decomposition
_T1 = TBL1_BYTES  # [256, 16], rows 129.. zero-padded by the builder
_T2 = TBL2_BYTES  # [256, 8]

import os as _os

CHUNK = 32


def _loop_from_env() -> bool:
    return _os.environ.get("CEPH_TPU_STRAW2_LOOP", "1") != "0"


def _tile_from_env() -> int:
    """CEPH_TPU_STRAW2_TILE override for hardware sweeps (e.g. 32
    restores the single-slab shape); validated here so a bad value fails
    at the knob with its name, not deep inside a score call.  The
    default is wide (2048) in loop-slab mode — grid steps are the cost
    and compile time no longer grows with tile — and the r4-proven 256
    in static-unroll mode."""
    raw = _os.environ.get(
        "CEPH_TPU_STRAW2_TILE", "2048" if _loop_from_env() else "256"
    )
    try:
        tile = int(raw)
    except ValueError:
        raise ValueError(
            f"CEPH_TPU_STRAW2_TILE={raw!r}: integer required"
        ) from None
    if tile <= 0 or tile % CHUNK:
        raise ValueError(
            f"CEPH_TPU_STRAW2_TILE={tile}: must be a positive multiple "
            f"of {CHUNK}"
        )
    return tile


# rows per grid step ([T, S] tile; S padded to 128).  Callers read this
# module attribute at CALL time and pass it as the explicit static
# `tile` argument — the mapper's downshift fallback mutates it after a
# hardware compile failure, and jit's static-arg cache keys on the
# passed value, so the mutation takes effect on the next call.
# The kernel walks the tile in CHUNK-row slabs: the one-hot
# [CHUNK, S, 256] bf16 intermediates are what blow the 16 MiB
# scoped-vmem limit (CHUNK=64 hit ~28 MiB on v5e), so CHUNK stays small
# while the tile — and therefore the number of grid steps, each of which
# pays fixed Mosaic setup cost — shrinks by tile/CHUNK.
DEFAULT_TILE = _tile_from_env()

# Slab-walk strategy (round-4 verdict item #2: compile time grew with
# tile because the slabs were STATICALLY unrolled, which is why big
# tiles were attempted speculatively on silicon and wedged the tunnel).
# True: the slabs run under ONE traced lax.fori_loop body with REF-level
# pl.ds slicing — compile time is constant in tile, so large tiles (few
# grid steps) become cheap to build.  The r4 silicon failure was a
# VALUE-level dynamic_slice (no Mosaic TC lowering); ref-level dynamic
# slices at 32-row-aligned offsets are the standard supported pattern.
# False restores the r4 known-good statically-unrolled shape.  The
# mapper's fallback flips this to False (keeping the tile) before it
# downshifts the tile itself, so one bad Mosaic build costs one retry.
LOOP_SLABS = _loop_from_env()


class TileShapeError(ValueError):
    """Caller-side shape/validation error (distinct from hardware compile
    failures so the mapper's tile-downshift retry can tell them apart)."""


def _disable_x64():
    """x64-OFF trace scope: the mapper calls this kernel inside its
    enable_x64() context, and ambient x64 turns index_map/kernel literals
    into i64 constants Mosaic can't legalize (see common/jaxutil.py).
    Everything in this kernel is explicit int32/uint32 limb math, so
    tracing with x64 off is both safe and required."""
    from ..common.jaxutil import x64_ctx

    return x64_ctx(False)


def _onehot_lookup(idx, tbl_bf16):
    """[T, S] int32 indices -> [T, S, ncols] f32 byte-limb rows via a bf16
    one-hot matmul (exact: one-hot rows select a single 0..255 value, and
    bf16 represents those exactly).  The 3D one-hot + last-dim contraction
    is the shape Mosaic legalizes (2D flatten reshapes are not)."""
    K = tbl_bf16.shape[0]
    oh = (
        idx[:, :, None]
        == jax.lax.broadcasted_iota(jnp.int32, (1, 1, K), 2)
    ).astype(jnp.bfloat16)
    return jax.lax.dot_general(
        oh, tbl_bf16,
        dimension_numbers=(((2,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _make_lookups(t1, t2):
    def look1(i):
        rows = _onehot_lookup(i, t1)
        return (
            recombine_limbs(rows, 0, 3, jnp),    # r2
            recombine_limbs(rows, 3, 2, jnp),    # r1
            recombine_limbs(rows, 5, 2, jnp),    # r0
            recombine_limbs(rows, 7, 4, jnp),    # lh_hi
            recombine_limbs(rows, 11, 3, jnp),   # lh_lo
        )

    def look2(i):
        rows = _onehot_lookup(i, t2)
        return (
            recombine_limbs(rows, 0, 4, jnp),    # ll_hi
            recombine_limbs(rows, 4, 3, jnp),    # ll_lo
        )

    return look1, look2


def _slab_scores(x, r, items, look1, look2):
    """One CHUNK-row slab: rjenkins hash + crush_ln limbs."""
    h = crush_hash32_3(
        x.astype(jnp.uint32),  # broadcasts [CHUNK, 1] across S
        items.astype(jnp.uint32),
        r.astype(jnp.uint32),
    )
    u = (h & jnp.uint32(0xFFFF)).astype(jnp.int32)
    return crush_ln_limbs(u, jnp, look1, look2)


def _score_kernel(x_ref, r_ref, items_ref, t1_ref, t2_ref, hi_ref, lo_ref,
                  *, loop_slabs: bool):
    t1 = t1_ref[:]
    t2 = t2_ref[:]
    T = x_ref.shape[0]
    look1, look2 = _make_lookups(t1, t2)

    # CHUNK-row slabs bound the [CHUNK, S, 256] one-hot VMEM footprint
    # while the grid step stays large.  Two walk strategies (see
    # LOOP_SLABS): a fori_loop with REF-level pl.ds slices (constant
    # compile time — offsets are 32-row aligned, the supported Mosaic
    # pattern; the r4 failure was VALUE-level dynamic_slice) or the r4
    # known-good static unroll (compile time ~ tile/CHUNK).
    if loop_slabs:
        def slab(c, carry):
            row = pl.multiple_of(c * CHUNK, CHUNK)
            x = x_ref[pl.ds(row, CHUNK), :]
            r = r_ref[pl.ds(row, CHUNK), :]
            items = items_ref[pl.ds(row, CHUNK), :]
            hi, lo = _slab_scores(x, r, items, look1, look2)
            hi_ref[pl.ds(row, CHUNK), :] = hi
            lo_ref[pl.ds(row, CHUNK), :] = lo
            return carry

        jax.lax.fori_loop(0, T // CHUNK, slab, 0)
    else:
        for c in range(T // CHUNK):
            row = c * CHUNK
            hi, lo = _slab_scores(
                x_ref[row:row + CHUNK, :],
                r_ref[row:row + CHUNK, :],
                items_ref[row:row + CHUNK, :],
                look1, look2,
            )
            hi_ref[row:row + CHUNK, :] = hi
            lo_ref[row:row + CHUNK, :] = lo


@partial(jax.jit, static_argnames=("tile", "loop_slabs", "interpret"))
def straw2_scores_pallas(x, r, items, tile: int,  # noqa: CL9 — public on purpose: crush/batched.py pads+launches it and crush_do_rule_batch owns the telemetry record; renaming would break the engine registry
                         loop_slabs: bool = False,
                         interpret: bool = False):
    """(x [B], r [B], items [B, S]) -> (ln_hi [B, S], ln_lo [B, S]) int32.

    B must be a multiple of `tile` and S a multiple of 128 (the mapper
    pads); planes combine as crush_ln = hi * 2^24 + lo.
    """
    B, S = items.shape
    if B % tile:
        raise TileShapeError(f"B={B} not a multiple of tile={tile}")
    if tile % CHUNK:
        raise TileShapeError(f"tile={tile} not a multiple of CHUNK={CHUNK}")
    if S % 128:
        raise TileShapeError(f"S={S} not a multiple of 128")
    x2 = x.reshape(B, 1).astype(jnp.int32)
    r2 = r.reshape(B, 1).astype(jnp.int32)
    items2 = items.astype(jnp.int32)
    with _disable_x64():
        t1 = jnp.asarray(_T1, jnp.bfloat16)
        t2 = jnp.asarray(_T2, jnp.bfloat16)
        out = pl.pallas_call(
            partial(_score_kernel, loop_slabs=loop_slabs),
            grid=(B // tile,),
            in_specs=[
                pl.BlockSpec((tile, 1), lambda i: (i, 0)),
                pl.BlockSpec((tile, 1), lambda i: (i, 0)),
                pl.BlockSpec((tile, S), lambda i: (i, 0)),
                pl.BlockSpec(_T1.shape, lambda i: (0, 0)),
                pl.BlockSpec(_T2.shape, lambda i: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((tile, S), lambda i: (i, 0)),
                pl.BlockSpec((tile, S), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, S), jnp.int32),
                jax.ShapeDtypeStruct((B, S), jnp.int32),
            ],
            interpret=interpret,
        )(x2, r2, items2, t1, t2)
    return out
