"""TPU compute kernels: bitplane GF(2) matmul (XLA) and Pallas variants."""
from .bitplane import (
    BitplaneCodec,
    apply_matrix_jax,
    pack_bitplanes,
    unpack_bitplanes,
)

__all__ = [
    "BitplaneCodec",
    "apply_matrix_jax",
    "pack_bitplanes",
    "unpack_bitplanes",
]
