"""cephdma — geometry-keyed device-resident stripe-buffer pool
(ROADMAP "Device-resident stripe pools and donated buffers"; the memory-
access-elimination class of win arXiv:2108.02692 measures, applied to
the queueing structure arXiv:1709.05365 shows dominates online EC).

Every encode used to round-trip host memory per flush: pack on host ->
``device_put`` -> kernel -> ``np.asarray`` -> scatter to shards.  The
pool is the allocation half of killing those trips (the dispatch half is
``ops.bitplane.apply_matrix_dev`` + the write batcher's async demux):

- ``put(host_array)`` commits a host stripe to the device THROUGH the
  pool: a free same-geometry buffer is recycled as donation fuel for the
  transfer (``donate_argnums`` on the destination — XLA reuses its
  storage for the result where the backend supports donation; CPU
  ignores donation, so there the pool is accounting + bounding only and
  the recycling becomes real the day the tunnel un-wedges), else a fresh
  ``jax.device_put``.
- ``release(dev_array)`` returns a dead device buffer (a fetched parity
  block, a consumed helper-chunk stack) to the free list for the next
  same-geometry ``put``.
- Free lists are keyed by buffer geometry ``(rows, cols, dtype)`` — the
  flattened form of the EC ``(k|m, stripes*shard_len, dtype)`` stripe
  geometry — and bounded by ``ec_device_pool_max_bytes`` with
  least-recently-USED geometry eviction (a retired pool's odd shapes
  age out instead of pinning device memory).

Stats (hits/misses/evictions/donations/resident_bytes) are
authoritative here and mirrored into the kernel telemetry PerfCounters
(``device_pool_*`` series) so the pool shows up next to the kernels it
feeds.  ``enabled()`` is sentinel-aware: a latched TPU_BACKEND_DEGRADED
forces pool bypass so the data path falls back to the historical
synchronous route (the same downgrade rule ``_want_pallas`` follows).

Config: ``ec_device_pool`` (escape hatch, default on) and
``ec_device_pool_max_bytes`` are read at daemon start into this
process-wide singleton (first daemon wins, like the sentinel policy);
the write batcher additionally re-reads ``ec_device_pool`` per flush so
the hatch works at runtime.
"""
from __future__ import annotations

import warnings
from collections import OrderedDict
from functools import partial

import jax
import numpy as np

from ..common.kernel_telemetry import SENTINEL, TELEMETRY
from ..common.lockdep import make_lock

# donation on backends that can't use it (CPU) is harmless but warns per
# compiled shape; the pool routes donation deliberately, so silence just
# that advisory here rather than at every call site
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

#: backends whose runtime actually recycles donated buffers ('axon' is
#: this box's tunneled-TPU alias)
_DONATING_BACKENDS = ("tpu", "axon", "gpu", "cuda", "rocm")


#: test hook: pin donation_supported() (None = ask the backend)
_donation_override: bool | None = None


def set_donation_override(v: bool | None) -> None:
    """Force donation_supported()'s answer (tests exercise the donation
    accounting on CPU where the backend would say no); None clears."""
    global _donation_override
    _donation_override = v


def donation_supported() -> bool:
    """True when `donate_argnums` buys real buffer reuse on the current
    backend (CPU accepts the annotation but ignores it).  The backend
    name comes from the policy seam (cephtopo), so a cpu-fallback
    topology disables donation even on an accelerator box."""
    if _donation_override is not None:
        return _donation_override
    from ..common.device_policy import get_device_policy

    return get_device_policy().backend() in _DONATING_BACKENDS


@partial(jax.jit, donate_argnums=(0,))
def _refill(dst, src):
    """Transfer `src` into the device while donating `dst`'s storage:
    where donation works the result lands in the recycled buffer instead
    of a fresh allocation; elsewhere it is a plain committed copy."""
    return src


def _geom(shape, dtype) -> tuple:
    return (tuple(int(d) for d in shape), np.dtype(dtype).name)


class DevicePool:
    """Bounded geometry-keyed free-list of device buffers (see module
    docstring).  Process-wide singleton ``POOL`` below; thread-safe."""

    def __init__(self, max_bytes: int = 256 << 20, enabled: bool = True,
                 policy=None):
        self._lock = make_lock("ops::device_pool")
        self._max_bytes = int(max_bytes)
        self._enabled = bool(enabled)
        #: injected DevicePolicy (cephtopo); None = legacy fixed bound
        self._policy = policy
        #: geometry -> free buffers; OrderedDict order IS the LRU order
        #: (move_to_end on every touch, evict from the front)
        self._free: OrderedDict[tuple, list] = OrderedDict()
        self._resident = 0
        self._stats = {"hits": 0, "misses": 0, "evictions": 0,
                       "donations": 0, "puts": 0, "releases": 0}

    # -- config ------------------------------------------------------------
    def configure(self, enabled: bool | None = None,
                  max_bytes: int | None = None, policy=None) -> None:
        """Apply the ec_device_pool / ec_device_pool_max_bytes options
        (daemon start; first daemon in the process wins the size).
        `policy` injects the daemon's DevicePolicy: the residency bound
        becomes the policy's pool_budget (per-device share x healthy
        devices), so a sentinel-shrunk mesh shrinks the pool with it."""
        if policy is not None:
            self._policy = policy
        with self._lock:
            if enabled is not None:
                self._enabled = bool(enabled)
                if not self._enabled:
                    self._drain_locked()
            if max_bytes is not None:
                self._max_bytes = int(max_bytes)
        bound = self._bound()
        with self._lock:
            self._evict_locked(bound)

    def _bound(self) -> int:
        """Effective residency bound: the injected policy's budget (it
        consults sentinel device health), or the raw configured max.
        Resolved OUTSIDE the pool lock — the policy reads sentinel
        state behind its own lock."""
        if self._policy is None:
            return self._max_bytes
        return self._policy.pool_budget(self._max_bytes)

    def enabled(self) -> bool:
        """Pool usable right now: configured on AND the backend sentinel
        has not latched degraded (a sick backend must get the historical
        synchronous path, not fresh async device traffic)."""
        return self._enabled and not SENTINEL.is_degraded

    @property
    def max_bytes(self) -> int:
        return self._max_bytes

    # -- the free-list cycle -----------------------------------------------
    def acquire(self, shape, dtype=np.uint8):
        """Pop a free buffer of exactly this geometry (None = miss).
        Stats count the hit/miss either way — `put` is the usual caller."""
        key = _geom(shape, dtype)
        buf = None
        with self._lock:
            bufs = self._free.get(key)
            if bufs:
                self._free.move_to_end(key)
                buf = bufs.pop()
                if not bufs:
                    self._free.pop(key, None)
                self._resident -= buf.nbytes
                self._stats["hits"] += 1
                resident = self._resident
            else:
                self._stats["misses"] += 1
        if buf is not None:
            TELEMETRY.record_pool(hits=1, resident_bytes=resident)
        else:
            TELEMETRY.record_pool(misses=1)
        return buf

    def release(self, dev) -> None:
        """Return a dead device buffer to its geometry's free list
        (bounded: least-recently-used geometries evict past max_bytes)."""
        if dev is None or not self._enabled:
            return
        try:
            key = _geom(dev.shape, dev.dtype)
            nbytes = int(dev.nbytes)
        except (AttributeError, TypeError):
            return
        bound = self._bound()  # outside the pool lock (sentinel reads)
        with self._lock:
            if not self._enabled:
                return
            self._free.setdefault(key, []).append(dev)
            self._free.move_to_end(key)
            self._resident += nbytes
            self._stats["releases"] += 1
            dropped = self._evict_locked(bound)
            resident = self._resident
        TELEMETRY.record_pool(evictions=len(dropped),
                              resident_bytes=resident)

    def put(self, host_array):
        """Commit one host array to the device through the pool: a free
        same-geometry buffer becomes donation fuel for the transfer (its
        storage recycled where the backend supports donation), else a
        fresh device_put.  Always returns a device array."""
        host_array = np.ascontiguousarray(host_array)
        with self._lock:
            self._stats["puts"] += 1
        recycled = self.acquire(host_array.shape, host_array.dtype) \
            if self.enabled() else None
        if recycled is not None and donation_supported():
            with self._lock:
                self._stats["donations"] += 1
            TELEMETRY.record_pool(donations=1)
            return _refill(recycled, host_array)
        # no recycled buffer, or a backend that ignores donation (CPU —
        # the popped buffer is simply dropped; the hit still measures
        # free-list reuse for the day the tunnel un-wedges)
        return jax.device_put(host_array)  # noqa: CL8 — the pool IS the transfer seam

    # -- bookkeeping -------------------------------------------------------
    def _evict_locked(self, bound: int | None = None) -> list:
        if bound is None:
            bound = self._max_bytes
        dropped = []
        while self._resident > bound and self._free:
            key, bufs = self._free.popitem(last=False)  # LRU geometry
            for b in bufs:
                self._resident -= b.nbytes
                dropped.append(b)
            self._stats["evictions"] += len(bufs)
        return dropped

    def _drain_locked(self) -> list:
        dropped = [b for bufs in self._free.values() for b in bufs]
        self._free.clear()
        self._resident = 0
        return dropped

    def clear(self) -> None:
        """Drop every pooled buffer (tests; backend resets)."""
        with self._lock:
            self._drain_locked()
            resident = self._resident
        TELEMETRY.record_pool(resident_bytes=resident)

    def stats(self) -> dict:
        bound = self._bound()
        with self._lock:
            out = dict(self._stats)
            out["resident_bytes"] = self._resident
            out["geometries"] = len(self._free)
            out["max_bytes"] = self._max_bytes
            out["budget_bytes"] = bound
            out["enabled"] = self._enabled
        return out


POOL = DevicePool()

#: conf already applied to the process-wide pool (first daemon wins,
#: like the sentinel policy — later daemons must not silently undo an
#: operator's escape hatch or re-size the bound)
_conf_applied = False


def configure_from_conf(conf, policy=None) -> None:
    """Wire the declared options into the process-wide pool at daemon
    start (CL5's declared-AND-read contract for both knobs).  FIRST
    daemon in the process wins; the write batcher additionally re-reads
    ``ec_device_pool`` per flush, so the hatch stays per-daemon and
    runtime there.  `policy` threads the daemon's DevicePolicy into the
    pool bound (cephtopo: sentinel-shrunk mesh => shrunk pool)."""
    global _conf_applied
    if _conf_applied:
        return
    _conf_applied = True
    POOL.configure(
        enabled=bool(conf.get("ec_device_pool")),
        max_bytes=int(conf.get("ec_device_pool_max_bytes")),
        policy=policy,
    )
