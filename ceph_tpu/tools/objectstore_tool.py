"""ceph-objectstore-tool analog — offline surgery on a stopped OSD's store.

Reference: src/tools/ceph_objectstore_tool.cc (list/info/export/import/
remove objects and fsck against an offline data path; SURVEY.md §2.8).

Works on a KStore or BlueStore directory (auto-detected by the block
device file; the ceph-bluestore-tool fsck/repair role folds in here for
bluestore paths).  Export format
is a self-contained JSON document (data/xattrs/omap base64'd) so an object
or a whole PG's shard collection can be moved between stores — the
analog of the reference's export/import stream.

    python -m ceph_tpu.tools.objectstore_tool --data-path /osd0 --op list
    python -m ceph_tpu.tools.objectstore_tool --data-path /osd0 \
        --op export --pgid 1.3s0 > pg.json
    python -m ceph_tpu.tools.objectstore_tool --data-path /osd1 \
        --op import < pg.json
"""
from __future__ import annotations

import argparse
import base64
import json
import sys

from ..store.kstore import KStore
from ..store.object_store import NotFound, Transaction


def _open(path: str):
    import os

    if os.path.exists(os.path.join(path, "block")):
        from ..store.bluestore import BlueStore

        # size from the existing device file — never resize on open
        dev = os.path.getsize(os.path.join(path, "block"))
        store = BlueStore(path, device_size=dev)
    else:
        store = KStore(path)
    store.mount()
    return store


def op_list(store, pgid: str | None, out) -> int:
    for cid in sorted(store.list_collections()):
        if pgid and cid != pgid:
            continue
        for oid in sorted(store.list_objects(cid)):
            print(json.dumps([cid, oid]), file=out)
    return 0


def op_info(store, pgid: str, oid: str, out) -> int:
    try:
        st = store.stat(pgid, oid)
        xattrs = {
            k: base64.b64encode(v).decode()
            for k, v in store.getattrs(pgid, oid).items()
        }
    except (NotFound, KeyError):
        print(f"No object {pgid}/{oid}", file=sys.stderr)
        return 2
    print(json.dumps({"cid": pgid, "oid": oid, "stat": st,
                      "xattrs": xattrs}, indent=2), file=out)
    return 0


def op_export(store, pgid: str | None, oid: str | None, out) -> int:
    doc = {"version": 1, "objects": []}
    for cid in sorted(store.list_collections()):
        if pgid and cid != pgid:
            continue
        for o in sorted(store.list_objects(cid)):
            if oid and o != oid:
                continue
            try:
                data = store.read(cid, o)
            except (NotFound, KeyError):
                data = b""
            doc["objects"].append({
                "cid": cid,
                "oid": o,
                "data": base64.b64encode(data).decode(),
                "xattrs": {
                    k: base64.b64encode(v).decode()
                    for k, v in store.getattrs(cid, o).items()
                },
                "omap": {
                    k: base64.b64encode(v).decode()
                    for k, v in store.omap_get(cid, o).items()
                },
            })
    json.dump(doc, out)
    out.write("\n")
    return 0


def op_import(store, src, force: bool) -> int:
    doc = json.load(src)
    if doc.get("version") != 1:
        print("unrecognized export document", file=sys.stderr)
        return 22
    for obj in doc["objects"]:
        cid, oid = obj["cid"], obj["oid"]
        if not force and store.collection_exists(cid) and \
                store.exists(cid, oid):
            print(f"{cid}/{oid} exists; --force to overwrite",
                  file=sys.stderr)
            return 17
    for obj in doc["objects"]:
        cid, oid = obj["cid"], obj["oid"]
        data = base64.b64decode(obj["data"])
        t = Transaction()
        t.try_create_collection(cid)
        if store.collection_exists(cid) and store.exists(cid, oid):
            # replace, don't merge: stale xattrs/omap on the destination
            # must not survive into the "identical" imported copy
            t.remove(cid, oid)
        t.touch(cid, oid)
        t.write(cid, oid, 0, data)
        t.truncate(cid, oid, len(data))
        for k, v in obj.get("xattrs", {}).items():
            t.setattr(cid, oid, k, base64.b64decode(v))
        omap = {
            k: base64.b64decode(v) for k, v in obj.get("omap", {}).items()
        }
        if omap:
            t.omap_setkeys(cid, oid, omap)
        store.queue_transaction(t)
    print(f"imported {len(doc['objects'])} objects", file=sys.stderr)
    return 0


def op_remove(store, pgid: str, oid: str) -> int:
    t = Transaction()
    try:
        store.stat(pgid, oid)
    except (NotFound, KeyError):
        print(f"No object {pgid}/{oid}", file=sys.stderr)
        return 2
    t.remove(pgid, oid)
    store.queue_transaction(t)
    return 0


def main(argv=None, out=sys.stdout) -> int:
    ap = argparse.ArgumentParser(
        prog="ceph-objectstore-tool",
        description="offline object store surgery (stop the OSD first)",
    )
    ap.add_argument("--data-path", required=True, help="KStore directory")
    ap.add_argument("--op", required=True,
                    choices=("list", "info", "export", "import", "remove",
                             "fsck", "kv-list", "kv-get"))
    ap.add_argument("--pgid", help="shard collection id, e.g. 1.3s0")
    ap.add_argument("object", nargs="?", help="object name")
    ap.add_argument("--force", action="store_true")
    ap.add_argument("--prefix", default="",
                    help="key prefix filter for kv-list")
    args = ap.parse_args(argv)

    if args.op in ("kv-list", "kv-get"):
        # ceph-kvstore-tool role (reference: src/tools/kvstore_tool.cc):
        # raw READ-ONLY inspection of the store's KV layer, no store
        # mount — works on kstore and bluestore data dirs (both keep a
        # LogKV; bluestore under kv/).  Keys embed NUL separators, so
        # listings print them ESCAPED (\0 for NUL, \\ for backslash)
        # and kv-get accepts the same escaped form — argv cannot carry
        # raw NULs.
        import os as _os

        from ..store.kv import LogKV

        def esc(k: str) -> str:
            return k.replace("\\", "\\\\").replace("\x00", "\\0")

        def unesc(k: str) -> str:
            out_chars = []
            i = 0
            while i < len(k):
                if k[i] == "\\" and i + 1 < len(k):
                    out_chars.append(
                        "\x00" if k[i + 1] == "0" else k[i + 1])
                    i += 2
                else:
                    out_chars.append(k[i])
                    i += 1
            return "".join(out_chars)

        kv_dir = args.data_path
        if _os.path.isdir(_os.path.join(args.data_path, "kv")):
            kv_dir = _os.path.join(args.data_path, "kv")
        if not (_os.path.exists(_os.path.join(kv_dir, "wal"))
                or _os.path.exists(_os.path.join(kv_dir, "snapshot"))):
            # a typo'd path must error, not conjure an empty store
            print(f"{kv_dir}: no KV store (no wal/snapshot)",
                  file=sys.stderr)
            return 2
        kv = LogKV(kv_dir, readonly=True)
        try:
            if args.op == "kv-list":
                n = 0
                for key, val in kv.iterate(unesc(args.prefix)):
                    print(f"{esc(key)}\t{len(val)}", file=out)
                    n += 1
                print(f"{n} key(s)", file=out)
                return 0
            if not args.object:
                ap.error("kv-get needs a key name")
            val = kv.get(unesc(args.object))
            if val is None:
                print(f"no key {args.object!r}", file=sys.stderr)
                return 2
            # byte-clean on a real stdout; latin-1 text (no repr noise)
            # on injected text streams
            buf = getattr(out, "buffer", None)
            if buf is not None:
                buf.write(bytes(val))
            else:
                out.write(bytes(val).decode("latin-1"))
            return 0
        finally:
            kv.close()

    store = _open(args.data_path)
    try:
        if args.op == "list":
            return op_list(store, args.pgid, out)
        if args.op == "info":
            if not (args.pgid and args.object):
                ap.error("info needs --pgid and an object name")
            return op_info(store, args.pgid, args.object, out)
        if args.op == "export":
            return op_export(store, args.pgid, args.object, out)
        if args.op == "import":
            return op_import(store, sys.stdin, args.force)
        if args.op == "remove":
            if not (args.pgid and args.object):
                ap.error("remove needs --pgid and an object name")
            return op_remove(store, args.pgid, args.object)
        if args.op == "fsck":
            from ..store.bluestore import BlueStore

            report = store.fsck(
                **({"deep": True, "repair": args.force}
                   if isinstance(store, BlueStore) else {})
            )
            if isinstance(report, dict):  # bluestore: structured report
                errors = report["errors"]
                for e in errors:
                    print(e, file=out)
                print(
                    f"fsck: {len(errors)} error(s), "
                    f"{report['leaked_blocks']} leaked block(s)"
                    + (f", repaired {report['repaired']}"
                       if report.get("repaired") else ""),
                    file=out,
                )
                return 1 if errors or report["leaked_blocks"] else 0
            for e in report:
                print(e, file=out)
            print(f"fsck: {len(report)} error(s)", file=out)
            return 1 if report else 0
        return 2
    finally:
        store.umount()


if __name__ == "__main__":
    sys.exit(main())
