"""crushtool analog — compile/decompile/test CRUSH maps from the shell.

Reference: src/tools/crushtool.cc (CLI surface) + src/crush/CrushTester.cc
(--test: map a range of x values through a rule and report mappings and
per-device utilization — the reference's own "batch CRUSH" consumer and the
golden-output oracle of its cram tests, src/test/cli/crushtool/*.t).

The map file format is the text grammar of CrushWrapper.format_text (the
CrushCompiler analog); --test runs the batched TPU mapper, so this tool is
also the quickest way to eyeball crush_do_rule_batch against a real map.
"""
from __future__ import annotations

import argparse
import sys

import numpy as np

from ..crush import CrushWrapper, ITEM_NONE, build_hierarchical_map


def _load(path: str) -> CrushWrapper:
    with open(path) as f:
        return CrushWrapper.parse_text(f.read())


def run_test(
    w: CrushWrapper,
    rules: list[int],
    num_rep: int,
    min_x: int,
    max_x: int,
    show_mappings: bool,
    show_utilization: bool,
    show_bad_mappings: bool,
    weights: np.ndarray,
    out=sys.stdout,
) -> None:
    """CrushTester::test analog; output format mirrors the reference's
    `CRUSH rule R x X [osds]` / `device N: stored : S expected : E` lines."""
    xs = np.arange(min_x, max_x + 1, dtype=np.int64)
    for rid in rules:
        got = np.asarray(w.do_rule_batch(rid, xs, num_rep, weights))
        if show_mappings:
            for x, row in zip(xs, got):
                osds = [int(o) for o in row if o != ITEM_NONE]
                print(f"CRUSH rule {rid} x {int(x)} {osds}", file=out)
        if show_bad_mappings:
            for x, row in zip(xs, got):
                osds = [int(o) for o in row if o != ITEM_NONE]
                if len(osds) != num_rep:
                    print(
                        f"bad mapping rule {rid} x {int(x)} num_rep "
                        f"{num_rep} result {osds}",
                        file=out,
                    )
        if show_utilization:
            n_objects = len(xs)
            placed = got[got != ITEM_NONE]
            devs, counts = np.unique(placed, return_counts=True)
            sizes = (got != ITEM_NONE).sum(axis=1)
            for size in range(num_rep + 1):
                n = int((sizes == size).sum())
                if n:
                    print(
                        f"rule {rid} ({w.map.rules[rid].rule_id}) num_rep "
                        f"{num_rep} result size == {size}:\t{n}/{n_objects}",
                        file=out,
                    )
            # expected share uses the rule's reachable subtree only (a
            # class rule must not count other classes' devices), scaled by
            # the reweight vector as CRUSH itself applies it
            rule_w = w.get_rule_weight_osd_map(rid)
            eff = {
                d: cw * weights[d] / 0x10000 for d, cw in rule_w.items()
            }
            total_w = sum(eff.values())
            for d, c in zip(devs, counts):
                exp = (
                    len(placed) * eff.get(int(d), 0.0) / total_w
                    if total_w
                    else 0.0
                )
                print(
                    f"  device {int(d)}:\t stored : {int(c)}\t expected : "
                    f"{exp:.2f}",
                    file=out,
                )


def main(argv=None, out=sys.stdout) -> int:
    ap = argparse.ArgumentParser(
        prog="crushtool", description=__doc__.splitlines()[0]
    )
    ap.add_argument("-i", "--infn", help="input map (text form)")
    ap.add_argument("-o", "--outfn", help="output file")
    ap.add_argument(
        "-d", "--decompile", action="store_true",
        help="print the map in text form (canonicalized)",
    )
    ap.add_argument(
        "-c", "--compile", dest="compile_", action="store_true",
        help="parse and re-emit the map (validates the grammar)",
    )
    ap.add_argument(
        "--build", nargs=2, type=int, metavar=("HOSTS", "OSDS_PER_HOST"),
        help="build a root/host/osd test map (crushtool --build analog)",
    )
    ap.add_argument("--test", action="store_true", help="run CrushTester")
    ap.add_argument("--rule", type=int, action="append", default=None)
    ap.add_argument("--num-rep", type=int, default=3)
    ap.add_argument("--min-x", type=int, default=0)
    ap.add_argument("--max-x", type=int, default=1023)
    ap.add_argument("--show-mappings", action="store_true")
    ap.add_argument("--show-utilization", action="store_true")
    ap.add_argument("--show-bad-mappings", action="store_true")
    ap.add_argument(
        "--weight", nargs=2, action="append", default=[],
        metavar=("OSD", "WEIGHT"),
        help="override an osd reweight for --test (0.0..1.0)",
    )
    args = ap.parse_args(argv)

    if args.build:
        w = CrushWrapper(build_hierarchical_map(*args.build))
    elif args.infn:
        w = _load(args.infn)
    else:
        print("crushtool: no input map (-i or --build)", file=sys.stderr)
        return 1

    if args.decompile or args.compile_:
        text = w.format_text()
        if args.outfn:
            with open(args.outfn, "w") as f:
                f.write(text)
        else:
            out.write(text)

    if args.test:
        weights = np.full(w.map.max_devices, 0x10000, dtype=np.int64)
        for osd, wt in args.weight:
            try:
                osd_id, value = int(osd), float(wt)
            except ValueError:
                print(f"crushtool: bad --weight {osd} {wt}", file=sys.stderr)
                return 1
            if not 0 <= osd_id < w.map.max_devices:
                print(
                    f"crushtool: --weight osd.{osd_id} out of range "
                    f"(map has max_devices {w.map.max_devices})",
                    file=sys.stderr,
                )
                return 1
            weights[osd_id] = int(value * 0x10000)
        rules = args.rule if args.rule else sorted(w.map.rules)
        run_test(
            w,
            rules,
            args.num_rep,
            args.min_x,
            args.max_x,
            args.show_mappings,
            args.show_utilization,
            args.show_bad_mappings,
            weights,
            out=out,
        )
    elif args.build and not (args.decompile or args.compile_):
        # --build with no other action emits the built map (to -o or stdout)
        if args.outfn:
            with open(args.outfn, "w") as f:
                f.write(w.format_text())
        else:
            out.write(w.format_text())
    elif not (args.decompile or args.compile_):
        ap.print_usage(file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. `crushtool ... | head`
        sys.exit(141)
