"""Operator CLI tools — the src/tools analogs (SURVEY.md §2.8).

Each tool is an argparse `main(argv) -> int` so tests drive it in-process
(the analog of the reference's cram-style CLI transcript tests,
src/test/cli/*/*.t) and `python -m ceph_tpu.tools.<tool>` drives it from a
shell.
"""
